"""Self-speculative drafting: n-gram / prompt-lookup proposal, host side.

Speculative decoding (ISSUE 13) splits each decode tick into *propose*
and *verify*.  This module is the propose half — and deliberately the
cheapest possible one: **no draft model**.  A request's own token
stream (prompt + everything it has emitted) is the draft source: match
the stream's recent suffix against its earlier occurrences and propose
the tokens that followed last time.  Prompts with shared templates,
code, quoted context, and the short cycles small greedy models fall
into are all highly self-predictive — exactly the regime where the
device is decoding one memory-bound token per tick and k free drafts
turn into k nearly-free verifications (the ``[max_batch, k+1]`` step in
:meth:`~apex_tpu.serving.model.DecodeModel.decode_step`).

The proposer is *advisory by construction*: drafts only ever enter the
verify step, whose accepted tokens are bitwise the tokens the
non-speculative engine would have produced (greedy argmax, or the
seed+``output_index``-keyed draws of :mod:`.sampling`).  A wrong draft
costs one wasted query position, never a wrong token — so the proposer
needs no correctness contract at all, only a hit rate worth its width.

**Adaptive back-off** keeps the worst-case *tick count* pinned at
today's one-tick-per-token cadence: a request whose proposals keep
getting fully rejected (``backoff`` consecutive zero-accept ticks)
stops drafting — ``n_draft = 0`` is *data*, the step never recompiles —
re-probes with a single-token proposal every ``probe_every`` quiet
ticks, and one accepted probe re-arms it.  (The compiled step itself
stays ``k+1`` wide; the extra query positions ride the same paged
gather, nearly free on the memory-bound TPU decode and compute-visible
on CPU — which is why bench ``serving_spec`` gates ``vs_baseline >= 1``
there.)  The counters ride the
:class:`~apex_tpu.serving.scheduler.Request`, so preemption and
recompute-on-readmit keep a request's drafting posture.

The engine's proposer slot is duck-typed (``propose(req, max_k)`` /
``observe(req, proposed, accepted)``), which is how the forced
acceptance/rejection tests drive the verify step with oracle and
adversarial drafts.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SpeculativeConfig", "NGramProposer", "ngram_propose"]


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Knobs of the self-speculative decode (docs/serving.md).

    ``k`` — max drafted tokens per slot per tick; the decode step
    compiles once at the fixed ``[max_batch, k + 1]`` verify shape, and
    every per-slot draft count in ``[0, k]`` is data.  ``max_ngram`` /
    ``min_ngram`` — suffix lengths tried (longest first) when matching
    the stream against its own history.  ``backoff`` — consecutive
    fully-rejected proposals before a request stops drafting (its tick
    count degrades to the plain one-tick-per-token cadence, never
    below it).
    ``probe_every`` — a backed-off request re-probes with a
    single-token proposal every this-many quiet ticks: a stream that
    turns self-predictive later (a template tail, a greedy cycle) gets
    its drafting back — one accepted probe re-arms it — while a
    hopeless stream wastes one query position per ``probe_every``
    ticks, not k per tick.
    """

    k: int = 4
    max_ngram: int = 3
    min_ngram: int = 1
    backoff: int = 4
    probe_every: int = 16

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(
                f"speculative k must be >= 1 (omit the config to disable "
                f"speculation), got {self.k}")
        if self.min_ngram < 1 or self.max_ngram < self.min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min {self.min_ngram} / max {self.max_ngram}")
        if self.backoff < 1:
            raise ValueError(f"backoff must be >= 1, got {self.backoff}")
        if self.probe_every < 1:
            raise ValueError(
                f"probe_every must be >= 1, got {self.probe_every}")


def ngram_propose(tokens: Sequence[int], k: int, *, max_ngram: int = 3,
                  min_ngram: int = 1) -> List[int]:
    """Prompt-lookup drafts: up to ``k`` tokens continuing ``tokens``.

    For n from ``max_ngram`` down to ``min_ngram``: take the stream's
    last n tokens and find their most recent *earlier* occurrence; on a
    hit, propose the ``k`` tokens that followed it.  The continuation
    may overlap the suffix and **self-extend** past the stream's end
    (a draft near the tail keeps reading from its own proposal), which
    is what makes a cycling stream — the tiny-model greedy attractor,
    and any periodic template — fully self-predictive at full width.
    Vectorized over a sliding window view — O(len) per n, no Python
    inner loop over the stream.  Returns ``[]`` on no match.
    """
    L = len(tokens)
    if k < 1 or L < min_ngram + 1:
        return []
    arr = np.asarray(tokens, np.int64)
    for n in range(min(max_ngram, L - 1), min_ngram - 1, -1):
        suffix = arr[L - n:]
        # windows of arr starting at 0 .. L-1-n: every occurrence
        # strictly before the suffix's own position
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
        hits = np.nonzero((windows == suffix).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n         # most recent occurrence
            out: List[int] = []
            for j in range(k):
                idx = start + j
                out.append(int(arr[idx]) if idx < L else out[idx - L])
            return out
    return []


class NGramProposer:
    """Per-request adaptive wrapper over :func:`ngram_propose` — the
    engine's default proposer when ``ServingConfig.speculative`` is
    set.

    Back-off keying (ISSUE 18 satellite): an adapter-tagged request
    (``req.sampling.adapter_id`` set) keys its back-off/re-arm state
    per ``(slot, adapter_id)`` instead of per request, so one
    template-poor tenant backing off cannot silence drafting for a
    different adapter that later lands in the same slot — and a
    well-predicted adapter's re-arm survives across that tenant's
    consecutive requests.  Bare requests keep the original per-request
    counters (``req.spec_fails`` / ``req.spec_quiet``) untouched."""

    _STATE_CAP = 1024   # bounded (slot, adapter) memory

    def __init__(self, config: SpeculativeConfig):
        self.config = config
        # (slot, adapter_id) -> [fails, quiet]
        self._adapter_state: Dict[Tuple[int, str], List[int]] = {}

    def _keyed(self, req) -> Optional[List[int]]:
        """The (slot, adapter) back-off cell, or None for bare/unslotted
        requests (those keep per-request state)."""
        aid = getattr(req.sampling, "adapter_id", None) \
            if req.sampling is not None else None
        if aid is None or req.slot is None:
            return None
        key = (req.slot, aid)
        cell = self._adapter_state.get(key)
        if cell is None:
            if len(self._adapter_state) >= self._STATE_CAP:
                self._adapter_state.pop(
                    next(iter(self._adapter_state)))
            cell = self._adapter_state[key] = [0, 0]
        return cell

    def propose(self, req, max_k: int) -> List[int]:
        """Draft up to ``max_k`` tokens for ``req`` (the engine has
        already clamped ``max_k`` to the context cap, the remaining
        budget, and the configured ``k``).  A backed-off request
        proposes nothing — except one probe every ``probe_every`` quiet
        ticks, which is what makes the documented re-arm reachable (the
        engine only reports verify outcomes for ticks that drafted)."""
        cell = self._keyed(req)
        fails = cell[0] if cell is not None else req.spec_fails
        if fails >= self.config.backoff:
            if cell is not None:
                cell[1] += 1
                quiet, reset = cell[1], (lambda: cell.__setitem__(1, 0))
            else:
                req.spec_quiet += 1
                quiet = req.spec_quiet
                reset = (lambda: setattr(req, "spec_quiet", 0))
            if quiet < self.config.probe_every:
                return []
            reset()
            max_k = min(max_k, 1)   # a probe wastes ONE query position
        return ngram_propose(
            req.sequence_tokens(), max_k,
            max_ngram=self.config.max_ngram,
            min_ngram=self.config.min_ngram)

    def observe(self, req, proposed: int, accepted: int) -> None:
        """Account one verify outcome: a fully-rejected proposal counts
        toward the back-off, any acceptance re-arms the request (for an
        adapter-tagged request: re-arms the *(slot, adapter)* cell)."""
        if proposed <= 0:
            return
        cell = self._keyed(req)
        if cell is not None:
            cell[0] = 0 if accepted > 0 else cell[0] + 1
        elif accepted > 0:
            req.spec_fails = 0
        else:
            req.spec_fails += 1
