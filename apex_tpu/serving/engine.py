"""The serving engine: continuous batching over the paged-cache decode.

One object owns the whole runtime: the compiled prefill/decode programs
(each built ONCE — request churn, chunked prefill, prefix-cache hits,
eviction, preemption and per-request sampling policies are all data,
never shape, so both steps compile exactly once per process;
:meth:`ServingEngine.decode_compile_count` pins this in tests), the
sharded KV arenas (donated through every step so XLA updates them in
place — APX204, analyzer entry ``serving_decode``), the host scheduler,
the PR 5 metrics, and the PR 3 preemption drain.

Step anatomy (:meth:`ServingEngine.step`)::

    [preemption?] -> admit waiting requests   (slot + first-chunk
                                               blocks; prefix-cache
                                               hits shared, not
                                               recomputed)
                  -> one chunked-prefill call  (each prefilling slot
                                               advances <= prefill_len
                                               tokens — a long prompt
                                               never stalls the tick)
                  -> grow decode blocks        (evict cached LRU, then
                                               preempt newest)
                  -> one batched decode step   (paged attention +
                                               in-graph sampling; with
                                               speculation: the k+1
                                               verify — n-gram drafts
                                               proposed host-side,
                                               verified in-graph, the
                                               accepted prefix emitted
                                               as 1..k+1 tokens)
                  -> append/finish bookkeeping (host; rejection = the
                                               length never advances —
                                               O(1), no KV copies)

Metric catalog (rank-aware registry, docs/observability.md +
docs/serving.md):

- ``serving/ttft_ms``      histogram (sampled: p50/p99) — submit to
  first token, per request
- ``serving/tpot_ms``      histogram (sampled: p50/p99) — inter-token
  interval on the decode path, per token
- ``serving/tokens_generated`` / ``serving/requests_finished`` /
  ``serving/requests_cancelled`` / ``serving/requests_rejected``
  counters (rejected = refused at submit while draining — a typed
  terminal state, distinct from accepted-then-drained cancellation)
- ``serving/active_slots`` / ``serving/free_blocks`` gauges
- ``serving/kv_occupancy`` gauge — fraction of the block pool holding
  live or cached KV (the occupancy worst-case reservation kept low)
- ``serving/prefix_cache_hits`` counter — blocks served from the
  prefix cache instead of recomputed
- ``serving/preemptions``  counter — requests evicted back to the
  queue for recompute-on-readmit
- ``serving/evictions``    counter — prefix-cache blocks returned to
  the free list under pool pressure
- ``serving/preemption_drains`` counter
- ``serving/spec_proposed`` / ``serving/spec_accepted`` counters —
  drafted tokens entering the k+1 verify and the drafts it accepted
  (ISSUE 13; zero when ``ServingConfig.speculative`` is off)
- ``serving/spec_acceptance`` gauge — lifetime accepted/proposed ratio
  (the drafting hit rate the adaptive back-off steers on)
- ``serving/mfu``          gauge — decode-step MFU when the device peak
  is known (``introspect()["mfu_reason"]`` says why otherwise)

Run-timeline (ISSUE 10): with a flight recorder armed
(:mod:`apex_tpu.observability.timeline`) the engine additionally logs
the full request lifecycle keyed by request id — including
``request_preempt`` and the re-``request_admit`` of the recompute —
see the class docstring and docs/observability.md.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.observability import timeline
from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel.mesh import TENSOR_AXIS, get_mesh
from apex_tpu.serving.kv_cache import (
    ExportLedger,
    KVCacheConfig,
    arena_partition_spec,
    init_kv_arena,
    scale_partition_spec,
)
from apex_tpu.serving.lora import (
    AdapterArena,
    LoRAConfig,
    adapter_partition_specs,
    init_adapter_arena,
    init_adapter_weights,
    pack_adapter_values,
)
from apex_tpu.serving.model import DecodeModel
from apex_tpu.serving.sampling import SamplingParams
from apex_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
    trace_fields,
)
from apex_tpu.serving.speculative import NGramProposer, SpeculativeConfig

__all__ = ["ServingConfig", "ServingEngine"]


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Static shape of the runtime (everything that pins a compile).

    ``prefill_len`` is the per-slot chunk width of the batched chunked
    prefill — the most prompt tokens any one request advances per tick
    (long prompts slice across ticks and never stall the decode).
    ``admission`` selects occupancy admission (on-demand growth +
    eviction + preemption, the production policy) or the PR 8
    worst-case ``"reserve"`` baseline; ``prefix_caching`` toggles
    copy-on-write prompt-prefix sharing (occupancy mode only).
    ``cache_dtype=jnp.int8`` stores the KV arenas quantized with
    per-row fp32 scales dequantized inside the paged kernels.
    ``speculative`` (a :class:`~apex_tpu.serving.speculative.
    SpeculativeConfig`, ISSUE 13) turns the decode step into the
    ``[max_batch, k + 1]`` self-speculative verify — ``k + 1`` pins the
    compiled decode shape (one compile; per-slot draft counts are
    data); ``None`` keeps the plain one-token step.
    ``lora`` (a :class:`~apex_tpu.serving.lora.LoRAConfig`) enables
    batched multi-LoRA serving: per-request adapters gathered from a
    paged adapter arena inside the same compiled step — rank and slot
    count pin the compile; which adapter each slot runs is data.
    ``None`` keeps the engine byte-identical to the bare path.
    """

    max_batch: int = 8           # concurrent decode slots
    block_size: int = 16         # tokens per KV block
    max_seq: int = 256           # per-request context cap (prompt+output)
    n_blocks: Optional[int] = None   # arena size; default = worst case
    prefill_len: Optional[int] = None  # chunk width; default max_seq
    cache_dtype: Any = None      # arena storage dtype; default param dtype
    fused_attention: bool = True   # Pallas paged kernels vs unfused XLA
    fuse_epilogue: bool = True     # fused residual/norm epilogue kernel
    admission: str = "occupancy"   # or "reserve" (PR 8 worst-case A/B)
    prefix_caching: bool = True    # share prompt-prefix blocks
    speculative: Optional[SpeculativeConfig] = None  # n-gram drafting
    lora: Optional[LoRAConfig] = None  # multi-LoRA adapter arena

    def __post_init__(self):
        if self.admission not in ("occupancy", "reserve"):
            raise ValueError(
                f"admission must be 'occupancy' or 'reserve', got "
                f"{self.admission!r}")

    def resolve_n_blocks(self, max_blocks_per_request: int) -> int:
        if self.n_blocks is not None:
            return self.n_blocks
        return self.max_batch * max_blocks_per_request


class ServingEngine:
    """Continuous-batching decode runtime over a GPT checkpoint.

    ``params``: a :class:`~apex_tpu.transformer.testing.
    gpt_parallel_train.GPT3DParams` with the layer stack in the
    canonical ``[vpp, pp, ...]`` form (what ``build_gpt_3d``'s init and
    the :mod:`~apex_tpu.serving.loader` restore both produce — the two
    leading dims are merged row-major into the ``[L, ...]`` serving
    stack).  ``guard``: an optional
    :class:`~apex_tpu.resilience.PreemptionGuard`; once it trips, the
    engine drains — no admissions, running requests decode to
    completion and deliver, waiting ones are cancelled.

    ``heartbeat``: an optional :class:`~apex_tpu.observability.metrics.
    HeartbeatMonitor` — the engine beats it at the end of every
    :meth:`step` (after the decode results materialize), so a hung
    device step (dead collective, wedged transfer) stops the beats, the
    monitor's ``on_hang`` fires the guard, and the engine's next alive
    moment **drains** — delivering in-flight responses — instead of the
    scheduler wedging forever (ISSUE 10 satellite; wire ``on_hang`` to
    the same ``guard``).

    ``timeline_tick_every``: when a flight recorder is armed
    (:mod:`apex_tpu.observability.timeline`), every request's lifecycle
    is logged (submit → admit → prefill chunks → decode ticks →
    preempt/re-admit → finish/cancel, keyed by ``rid``); decode ticks
    are sampled every N generated tokens so the hot loop pays one host
    dict per N tokens, not per token.
    """

    def __init__(self, config, serving: ServingConfig, params, *,
                 mesh=None, tp_axis: str = TENSOR_AXIS, registry=None,
                 guard=None, heartbeat=None, timeline_tick_every: int = 8):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from apex_tpu.observability.metrics import default_registry
        from apex_tpu.transformer.tensor_parallel import infer_param_specs

        self.mesh = mesh if mesh is not None else get_mesh()
        self.tp_axis = tp_axis
        self.serving = serving
        if (config.position_embedding_type == "learned"
                and config.max_position_embeddings < serving.max_seq):
            raise ValueError(
                f"max_seq ({serving.max_seq}) exceeds the learned position "
                f"table ({config.max_position_embeddings})")
        # speculative decode (ISSUE 13): the decode step's query width
        # is k+1 — a compile-time constant; per-slot draft counts are
        # data, so acceptance churn never recompiles
        self.spec = serving.speculative
        self.spec_width = 1 + (self.spec.k if self.spec is not None else 0)
        if serving.max_seq < self.spec_width:
            raise ValueError(
                f"max_seq ({serving.max_seq}) below the speculative "
                f"width ({self.spec_width})")
        self.proposer = (NGramProposer(self.spec)
                         if self.spec is not None else None)

        cache_dtype = (serving.cache_dtype if serving.cache_dtype is not None
                       else config.param_dtype)
        probe = KVCacheConfig(
            n_layers=config.num_layers, n_blocks=1,
            block_size=serving.block_size, kv_heads=config.query_groups,
            head_dim=config.head_dim, max_seq=serving.max_seq,
            dtype=cache_dtype)
        self.cache = dataclasses.replace(
            probe,
            n_blocks=serving.resolve_n_blocks(probe.max_blocks_per_request))
        self.model = DecodeModel(
            config, self.cache, fused_attention=serving.fused_attention,
            fuse_epilogue=serving.fuse_epilogue, lora=serving.lora)
        self.prefill_len = serving.prefill_len or serving.max_seq
        # Live-retunable knobs (ISSUE 18): data-only caps an autopilot
        # can actuate at runtime over the command wire.  Neither touches
        # a compiled shape — the prefill call keeps its [B, T] program
        # and the verify keeps [B, k+1]; the caps only shrink how much
        # of each fixed-shape call is *used*, so retuning never
        # recompiles.  None means "engine default" (the knob is unset).
        self.live_prefill_chunk: Optional[int] = None
        self.live_spec_k: Optional[int] = None

        # [vpp, pp, ...] -> [L, ...] (row-major merge == virtual-stage
        # major == plain layer order; gpt3d_logical_folds rationale)
        L = config.num_layers
        params = params._replace(layers=jax.tree_util.tree_map(
            lambda l: l.reshape((L,) + l.shape[2:]), params.layers))
        self.params = params

        e_specs = infer_param_specs(params.embedding, axis=tp_axis)
        per_layer = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype),
            params.layers)
        l_specs = jax.tree_util.tree_map(
            lambda s: P(None, *tuple(s)),
            infer_param_specs(per_layer, axis=tp_axis),
            is_leaf=lambda x: isinstance(x, P))
        ln_specs = jax.tree_util.tree_map(lambda _: P(), params.final_ln)
        self.param_specs = type(params)(
            embedding=e_specs, layers=l_specs, final_ln=ln_specs)

        self.arenas: Tuple[Any, ...] = init_kv_arena(
            self.cache, self.mesh, tp_axis)
        a_spec = arena_partition_spec(tp_axis)
        arena_specs: Tuple[Any, ...] = (a_spec, a_spec)
        if self.cache.quantized:
            s_spec = scale_partition_spec(tp_axis)
            arena_specs = (a_spec, a_spec, s_spec, s_spec)

        # multi-LoRA (ISSUE 17): the adapter arrays are a second donated
        # arena set threaded through both steps; each request's arena
        # slot is [max_batch] data gathered in-kernel, so the adapter
        # mix never pins a compile
        self.lora = serving.lora
        self.adapter_arena: Optional[AdapterArena] = None
        self.adapters: Optional[Tuple[Any, ...]] = None
        self._adapter_dtype = config.param_dtype
        if self.lora is not None:
            self.adapter_arena = AdapterArena(self.lora.n_slots)
            self.adapters = init_adapter_arena(
                config, self.lora, self.mesh, tp_axis)

        rep = P()
        if self.lora is None:
            decode_body = cc.shard_over(
                self.model.decode_step, mesh=self.mesh,
                in_specs=(arena_specs, self.param_specs) + (rep,) * 10,
                out_specs=(arena_specs, P(None, None), P(None),
                           P(None, None, None)),
            )
            prefill_body = cc.shard_over(
                self.model.prefill, mesh=self.mesh,
                in_specs=(arena_specs, self.param_specs) + (rep,) * 13,
                out_specs=(arena_specs, P(None), P(None, None, None)),
            )
        else:
            adapter_specs = adapter_partition_specs(tp_axis)
            model = self.model

            def decode_step_lora(arenas, adapters, params, tokens,
                                 positions, block_tables, active, n_draft,
                                 adapter_slots, temperature, top_k, top_p,
                                 seeds, steps):
                return model.decode_step(
                    arenas, params, tokens, positions, block_tables,
                    active, n_draft, temperature, top_k, top_p, seeds,
                    steps, adapters=adapters,
                    adapter_slots=adapter_slots)

            def prefill_lora(arenas, adapters, params, tokens,
                             position_ids, block_tables, lengths, limits,
                             dest_blocks, dest_offsets, sample_index,
                             adapter_slots, temperature, top_k, top_p,
                             seeds, steps):
                return model.prefill(
                    arenas, params, tokens, position_ids, block_tables,
                    lengths, limits, dest_blocks, dest_offsets,
                    sample_index, temperature, top_k, top_p, seeds,
                    steps, adapters=adapters,
                    adapter_slots=adapter_slots)

            decode_body = cc.shard_over(
                decode_step_lora, mesh=self.mesh,
                in_specs=(arena_specs, adapter_specs, self.param_specs)
                + (rep,) * 11,
                out_specs=(arena_specs, adapter_specs, P(None, None),
                           P(None), P(None, None, None)),
            )
            prefill_body = cc.shard_over(
                prefill_lora, mesh=self.mesh,
                in_specs=(arena_specs, adapter_specs, self.param_specs)
                + (rep,) * 14,
                out_specs=(arena_specs, adapter_specs, P(None),
                           P(None, None, None)),
            )
        # the arenas are donated: the KV cache must alias in->out or the
        # biggest HBM tenant of the chip doubles (APX204, entry
        # serving_decode); with LoRA the adapter arrays donate alongside
        donated = (0,) if self.lora is None else (0, 1)
        self._decode = jax.jit(decode_body, donate_argnums=donated)
        self._prefill = jax.jit(prefill_body, donate_argnums=donated)
        # adapter (un)load: one donated in-place row update per
        # registration — the slot index is traced data, so churning
        # adapters through the arena reuses one compiled scatter
        self._adapter_set = jax.jit(
            lambda ad, slot, vals: tuple(
                a.at[:, slot].set(v) for a, v in zip(ad, vals)),
            donate_argnums=(0,))
        # KV-block migration (ISSUE 16): one donated scatter lands a
        # whole imported run in the arenas per migration flush — one
        # device put per flush, never one per block
        self._import_scatter = jax.jit(
            lambda arenas, idx, vals: tuple(
                a.at[:, idx].set(v) for a, v in zip(arenas, vals)),
            donate_argnums=(0,))
        self._jnp = jnp

        self.scheduler = Scheduler(
            self.cache, serving.max_batch, chunk_tokens=self.prefill_len,
            admission=serving.admission,
            prefix_caching=serving.prefix_caching)
        # pin-until-ack ledger for exported (migrating) block runs: the
        # run stays held until the decode side acks, then frees into the
        # prefix cache as evictable capacity
        self.exports = ExportLedger(self.scheduler.allocator,
                                    self.scheduler.prefix_cache)
        self.registry = registry if registry is not None else \
            default_registry()
        self.guard = guard
        self.heartbeat = heartbeat
        if timeline_tick_every < 1:
            raise ValueError(
                f"timeline_tick_every must be >= 1, got "
                f"{timeline_tick_every}")
        self.timeline_tick_every = timeline_tick_every
        self._tables = np.zeros(
            (serving.max_batch, self.cache.max_blocks_per_request),
            np.int32)
        self._steps = 0
        self._decode_calls = 0         # device decode/verify invocations
        self._slot_steps = 0           # per-slot verify participations
        #                                (mean accept length denominator)
        self._counted_preempts = 0     # flushed-so-far deltas
        self._counted_hits = 0
        self._counted_evictions = 0
        self.spec_proposed = 0         # drafted tokens (lifetime)
        self.spec_accepted = 0         # drafts accepted by the verify
        # adapter_id -> [proposed, accepted] (ISSUE 18 satellite):
        # per-tenant acceptance so one template-poor adapter is visible
        # on /fleet/statusz instead of hidden inside the fleet mean
        self.spec_by_adapter: Dict[str, List[int]] = {}
        # MFU bookkeeping (ISSUE 10 satellite): FLOPs of the decode
        # program probed once (lazily, pre-donation), last decode wall
        # time measured each step; serving/mfu flushed as a gauge when
        # defined, else the reason string is kept for /statusz.
        self._decode_flops: Optional[float] = None
        self._last_decode_s: Optional[float] = None
        self._flops_probed = False
        self._probe_fail_reason: Optional[str] = None
        self.mfu: Optional[float] = None
        self.mfu_reason: Optional[str] = "decode step has not run yet"

    # -------------------------------------------------------------- intro

    def decode_compile_count(self) -> int:
        """Compiled-variant count of the decode step (the zero-recompile
        contract: stays 1 across any request churn, preemption,
        eviction, and sampling-policy mix)."""
        return int(self._decode._cache_size())

    def prefill_compile_count(self) -> int:
        """Compiled-variant count of the chunked prefill (the fixed
        ``[max_batch, prefill_len]`` chunk shape: also exactly 1)."""
        return int(self._prefill._cache_size())

    # -------------------------------------------------------------- knobs

    def knobs(self) -> Dict[str, Any]:
        """Current live-knob state plus the engine's compile-time
        bounds — the autopilot reads the bounds off the state heartbeat
        to pick targets, and the ack of ``set_knobs`` echoes this dict
        so the controller's committed view matches the replica's."""
        return {"prefill_chunk": self.live_prefill_chunk,
                "spec_k": self.live_spec_k,
                "prefill_len": int(self.prefill_len),
                "spec_k_max": int(self.spec_width - 1)}

    def set_knobs(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Apply live-retunable serving knobs (ISSUE 18).

        Recognized keys (each optional; ``None`` resets to the engine
        default):

        - ``prefill_chunk``: cap on tokens prefilled per slot per tick
          (clamped to ``[1, prefill_len]``).  Shrinking it trades
          prefill throughput for decode-tick latency when ``prefill``
          dominates tail traces.
        - ``spec_k``: cap on drafted tokens per tick (clamped to
          ``[0, spec_width - 1]``; 0 disables drafting).  Lowering it
          cuts wasted verify work when acceptance sags.

        Both are data-only: the compiled [B, T] prefill and
        [B, spec_width] verify shapes never change, so a knob change
        never recompiles.  Unknown keys raise (a typo'd controller must
        fail its ack, not silently no-op).  Returns :meth:`knobs` — the
        applied state, echoed back over the ack wire."""
        unknown = set(payload) - {"prefill_chunk", "spec_k"}
        if unknown:
            raise ValueError(f"unknown knobs: {sorted(unknown)}")
        if "prefill_chunk" in payload:
            v = payload["prefill_chunk"]
            if v is not None:
                v = int(v)
                if v < 1:
                    raise ValueError(
                        f"prefill_chunk must be >= 1, got {v}")
                v = min(v, int(self.prefill_len))
            self.live_prefill_chunk = v
            # mirror into admission's first-chunk sizing so the ask for
            # blocks matches what the device call will actually cover
            self.scheduler.chunk_tokens = (
                v if v is not None else int(self.prefill_len))
        if "spec_k" in payload:
            v = payload["spec_k"]
            if v is not None:
                v = int(v)
                if v < 0:
                    raise ValueError(f"spec_k must be >= 0, got {v}")
                v = min(v, int(self.spec_width - 1))
            self.live_spec_k = v
        return self.knobs()

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    # -------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               sampling: Optional[SamplingParams] = None,
               trace: Optional[dict] = None) -> Request:
        """``trace``: the fleet-minted trace context riding the replica
        wire (``{"trace_id": ..., "attempt": ...}``, ISSUE 15) — every
        timeline event of this request then carries the fleet-wide id,
        so N processes' spills stitch into one span tree.  ``None``
        (standalone engines, untraced fleets) keeps the events exactly
        as before."""
        if len(np.shape(prompt)) != 1:
            raise ValueError(
                f"prompt must be 1-D, got shape {np.shape(prompt)}")
        req = self.scheduler.submit(prompt, max_new_tokens, eos_id,
                                    sampling)
        if trace is not None:
            req.trace_id = trace.get("trace_id")
            req.trace_attempt = int(trace.get("attempt", 0))
        aid = getattr(sampling, "adapter_id", None) \
            if sampling is not None else None
        if (aid is not None and req.state is not RequestState.REJECTED
                and (self.adapter_arena is None
                     or not self.adapter_arena.resident(aid))):
            # unknown adapter: refuse with the same typed terminal
            # state as the drain window — never queued, never a hang;
            # the router re-routes (another replica may hold it)
            self.scheduler.waiting.remove(req)
            req.state = RequestState.REJECTED
        timeline.emit("request_submit", rid=req.rid,
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=max_new_tokens,
                      **trace_fields(req))
        if req.state is RequestState.REJECTED:
            # submitted into the drain window: refused with a typed
            # terminal state (never queued, never a hang) and counted
            # apart from drain cancellations — a router re-routes a
            # REJECTED request, it does not mourn it
            self.registry.counter("serving/requests_rejected").inc()
            timeline.emit("request_reject", rid=req.rid,
                          **trace_fields(req))
        elif aid is not None:
            # pinned for the request's whole life (queue wait included):
            # its adapter can never be LRU-evicted out from under it
            self.adapter_arena.pin(aid, req.rid)
            self.registry.gauge("serving/adapter_active").set(
                self.adapter_arena.active)
        return req

    # --------------------------------------------------------------- drain

    def drain(self) -> List[Request]:
        """Preemption path: cancel the queue, keep decoding the running
        requests until their responses are delivered."""
        timeline.emit("preemption", wall_ts=time.time())
        cancelled = self.scheduler.drain()
        if cancelled:
            self.registry.counter("serving/requests_cancelled").inc(
                len(cancelled))
        for req in cancelled:
            self._unpin_adapter(req)
            timeline.emit("request_cancel", rid=req.rid,
                          **trace_fields(req))
        self.registry.counter("serving/preemption_drains").inc()
        return cancelled

    # ------------------------------------------------- KV migration (ISSUE 16)

    def export_request(self, req: Request) -> Tuple[dict, List[tuple]]:
        """Extract a RUNNING request's KV-block run for migration to a
        decode replica.

        One batched device gather per arena pulls the run
        (``blocks_for(cache_len)`` blocks) to the host; each block
        becomes one payload tuple — ``(k, v)`` or ``(k, v, k_scale,
        v_scale)`` per-block slabs — sized to ride one wire frame, so
        the transfer streams and resumes at block boundaries.  The run
        is then **pinned** in the export ledger (refcount +1 under the
        export owner) and the request leaves the scheduler silently (no
        finish/cancel event — the stream continues on the decode side);
        its own block refs free normally, so the run survives at
        refcount 1 until :meth:`release_export`.

        Returns ``(meta, payloads)``.  Raises ``ValueError`` when the
        request is not in an exportable state (still prefilling, no
        token emitted yet, already exporting) — the caller degrades to
        letting it keep decoding locally."""
        if req.state is not RequestState.RUNNING or req.slot is None:
            raise ValueError(
                f"request {req.rid} is {req.state}, not exportable")
        if req.prefilling or not req.output_tokens:
            raise ValueError(
                f"request {req.rid} has not completed prefill + first "
                "token; nothing to migrate yet")
        seq = req.sequence_tokens()
        if req.cache_len != len(seq) - 1:
            raise ValueError(
                f"request {req.rid} cache_len {req.cache_len} out of "
                f"phase with its {len(seq)}-token stream")
        n_blocks = self.cache.blocks_for(req.cache_len)
        run = list(req.blocks[:n_blocks])
        idx = self._jnp.asarray(np.asarray(run, np.int32))
        # one gather + one device->host transfer per arena (batched tx)
        slabs = [np.asarray(a[:, idx]) for a in self.arenas]
        payloads = [tuple(slab[:, j] for slab in slabs)
                    for j in range(n_blocks)]
        n_bytes = int(sum(s.nbytes for s in slabs))
        self.exports.pin(req.rid, run, seq[:req.cache_len],
                         req.cache_len)
        # the request leaves this engine silently: the slot's table row
        # zeroes and its own refs free (the export pin keeps the run);
        # the destination replica takes its own adapter pin
        self._tables[req.slot][:] = 0
        self.scheduler.finish(req)
        self._unpin_adapter(req)
        self.registry.counter("serving/kv_export_blocks").inc(n_blocks)
        timeline.emit("request_export", rid=req.rid,
                      tokens=len(req.output_tokens), blocks=n_blocks,
                      **trace_fields(req))
        meta = {
            "cache_len": req.cache_len,
            "n_blocks": n_blocks,
            "n_out": len(req.output_tokens),
            "block_size": self.cache.block_size,
            "n_layers": self.cache.n_layers,
            "kv_heads": self.cache.kv_heads,
            "head_dim": self.cache.head_dim,
            "dtype": str(np.dtype(self.cache.dtype)),
            "bytes": n_bytes,
        }
        return meta, payloads

    def release_export(self, rid, *, ok: bool) -> None:
        """Drop the pin on an exported run (the decode side's ack, or
        the router's abort).  Either way the run's full blocks index
        into the local prefix cache — the KV is valid content, and a
        failed migration's re-prefill routed back here then hits it —
        and the pin frees.  Idempotent: a duplicate/stale ack is a
        no-op."""
        self.exports.release(rid, to_cache=True)
        if not ok:
            self.registry.counter("serving/kv_export_aborts").inc()

    def _check_import_payloads(self, payloads: List[tuple]) -> None:
        """Reject a malformed migration payload BEFORE any device put —
        a torn or mismatched transfer must degrade to re-prefill, never
        land partial garbage in the arena."""
        want_shapes = [a.shape[:1] + a.shape[2:] for a in self.arenas]
        want_dtypes = [a.dtype for a in self.arenas]
        for j, p in enumerate(payloads):
            if len(p) != len(self.arenas):
                raise ValueError(
                    f"imported block {j} carries {len(p)} slabs, arena "
                    f"set has {len(self.arenas)}")
            for s, shape, dtype in zip(p, want_shapes, want_dtypes):
                if tuple(np.shape(s)) != tuple(shape) \
                        or np.dtype(getattr(s, "dtype", None)) != dtype:
                    raise ValueError(
                        f"imported block {j} slab shape/dtype "
                        f"{np.shape(s)}/{getattr(s, 'dtype', None)} != "
                        f"arena {tuple(shape)}/{dtype}")

    def import_request(self, prompt: Sequence[int], max_new_tokens: int,
                       eos_id: Optional[int] = None,
                       sampling: Optional[SamplingParams] = None,
                       trace: Optional[dict] = None, *,
                       cache_len: int,
                       payloads: List[tuple]) -> Request:
        """Admit a migrated request with its KV run injected into the
        local arenas (the decode side of a KV-block migration).

        ``prompt`` is the request's full wire sequence so far (original
        prompt + every token already streamed — exactly the failover-
        replay wire), ``cache_len`` the tokens the imported run covers
        (always ``len(prompt) - 1``: the last wire token recomputes
        here, which is what makes the continued stream bitwise the
        replay stream), ``payloads`` the per-block slabs from
        :meth:`export_request`.  The injection is ONE donated scatter
        per migration flush across all arenas.  Raises on missing
        capacity or a malformed payload — the caller reports a typed
        failure and the router degrades to re-prefill."""
        self._check_import_payloads(payloads)
        aid = getattr(sampling, "adapter_id", None) \
            if sampling is not None else None
        if aid is not None and (self.adapter_arena is None
                                or not self.adapter_arena.resident(aid)):
            # checked BEFORE admission claims a slot: the typed failure
            # relays as a failed import and the router degrades
            raise ValueError(
                f"adapter {aid!r} is not resident on this replica")
        req = self.scheduler.admit_imported(
            prompt, max_new_tokens, eos_id, sampling,
            cache_len=cache_len, n_blocks=len(payloads))
        if trace is not None:
            req.trace_id = trace.get("trace_id")
            req.trace_attempt = int(trace.get("attempt", 0))
        timeline.emit("request_submit", rid=req.rid,
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=max_new_tokens, imported=True,
                      **trace_fields(req))
        if req.state is RequestState.REJECTED:
            self.registry.counter("serving/requests_rejected").inc()
            timeline.emit("request_reject", rid=req.rid,
                          **trace_fields(req))
            return req
        if aid is not None:
            self.adapter_arena.pin(aid, req.rid)
            self.registry.gauge("serving/adapter_active").set(
                self.adapter_arena.active)
        idx = self._jnp.asarray(
            np.asarray(req.blocks[:len(payloads)], np.int32))
        vals = tuple(
            np.stack([p[i] for p in payloads], axis=1)
            for i in range(len(self.arenas)))
        self.arenas = self._import_scatter(self.arenas, idx, vals)
        self.scheduler.note_imported(req)
        self.registry.counter("serving/kv_import_blocks").inc(
            len(payloads))
        timeline.emit("request_admit", rid=req.rid, slot=req.slot,
                      blocks=len(req.blocks), hit_blocks=0,
                      imported=True, **trace_fields(req))
        return req

    # ---------------------------------------------------------------- step

    def step(self) -> None:
        """One engine tick: admit, advance prefill chunks, one decode
        step."""
        if (self.guard is not None and self.guard.triggered
                and not self.draining):
            self.drain()
        admitted = self.scheduler.admit()
        for req in admitted:
            timeline.emit("request_admit", rid=req.rid, slot=req.slot,
                          blocks=len(req.blocks),
                          hit_blocks=req.hit_blocks,
                          **trace_fields(req))
        self._prefill_tick()
        self._decode_once()
        self._steps += 1
        self.registry.gauge("serving/active_slots").set(
            len(self.scheduler.running()))
        self.registry.gauge("serving/free_blocks").set(
            self.scheduler.allocator.n_free)
        self.registry.gauge("serving/kv_occupancy").set(
            self.scheduler.kv_occupancy())
        self._flush_occupancy_counters()
        # the beat lands only after this tick's device work materialized
        # — a wedged decode stops the beats and the monitor fires the
        # guard, turning a scheduler wedge into an ordinary drain
        if self.heartbeat is not None:
            self.heartbeat.beat(self._steps)

    def _flush_occupancy_counters(self) -> None:
        sched = self.scheduler
        if sched.preemptions > self._counted_preempts:
            self.registry.counter("serving/preemptions").inc(
                sched.preemptions - self._counted_preempts)
            self._counted_preempts = sched.preemptions
        pc = sched.prefix_cache
        if pc is not None:
            if pc.hits > self._counted_hits:
                self.registry.counter("serving/prefix_cache_hits").inc(
                    pc.hits - self._counted_hits)
                self._counted_hits = pc.hits
            if pc.evictions > self._counted_evictions:
                self.registry.counter("serving/evictions").inc(
                    pc.evictions - self._counted_evictions)
                self._counted_evictions = pc.evictions

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        """Drive :meth:`step` until no request is waiting or running
        (under drain: until the running ones have delivered)."""
        for _ in range(max_steps):
            if self.scheduler.idle:
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # ------------------------------------------------------------ adapters

    def register_adapter(self, adapter_id: str, weights=None, *,
                         seed: Optional[int] = None) -> int:
        """Load (or hot-swap) a LoRA adapter into the arena; returns
        its slot.

        ``weights`` is the ``{proj: (A [L, in, r], B [L, r, out])}``
        dict — typically from :func:`~apex_tpu.serving.lora.
        restore_adapter_for_serving` (the spec-layer restore path) or
        :func:`~apex_tpu.serving.lora.init_adapter_weights`.  ``None``
        builds a deterministic fixture seeded by ``seed`` (default: a
        hash of the id, so the same id loads the same adapter on every
        replica).  A resident id re-registers **in place** — the
        hot-swap path: one donated row update, in-flight requests keep
        decoding (the swap lands between ticks, never mid-step).  A new
        id LRU-evicts the coldest unpinned adapter when the arena is
        full; all-pinned raises
        :class:`~apex_tpu.serving.lora.OutOfAdapterSlotsError`.
        """
        if self.adapter_arena is None:
            raise RuntimeError(
                "ServingConfig.lora is None; this engine serves the "
                "bare checkpoint only")
        if weights is None:
            if seed is None:
                seed = zlib.crc32(str(adapter_id).encode())
            weights = init_adapter_weights(self.model.cfg, self.lora,
                                           seed=int(seed))
        vals = pack_adapter_values(self.model.cfg, self.lora, weights,
                                   self._adapter_dtype)
        slot, evicted = self.adapter_arena.register(adapter_id)
        self.adapters = self._adapter_set(
            self.adapters, np.int32(slot), vals)
        self.registry.counter("serving/adapter_loads").inc()
        if evicted is not None:
            self.registry.counter("serving/adapter_evictions").inc()
        self.registry.gauge("serving/adapter_active").set(
            self.adapter_arena.active)
        timeline.emit(
            "adapter_load", adapter_id=str(adapter_id), slot=int(slot),
            evicted=(str(evicted) if evicted is not None else None))
        return int(slot)

    def unregister_adapter(self, adapter_id: str) -> None:
        """Drop an adapter from the registry: new submits naming it are
        REJECTED; in-flight pinners keep their slot until they finish
        (the rows are only reused after the last pin releases)."""
        if self.adapter_arena is None:
            raise RuntimeError(
                "ServingConfig.lora is None; this engine serves the "
                "bare checkpoint only")
        slot = self.adapter_arena.unregister(adapter_id)
        timeline.emit("adapter_unload", adapter_id=str(adapter_id),
                      slot=int(slot))

    def _adapter_slot_array(self) -> np.ndarray:
        """Each slot's arena row for this tick ([max_batch] DATA; idle
        and ``adapter_id=None`` slots gather the zero adapter)."""
        slots = np.zeros((self.serving.max_batch,), np.int32)
        for req in self.scheduler.running():
            slots[req.slot] = self.adapter_arena.pinned_slot(req.rid)
        return slots

    # ------------------------------------------------------------- prefill

    def _refresh_tables(self) -> None:
        """Rebuild the slot -> physical-block table rows from the live
        requests (preemption and growth both rewrite block lists; the
        rebuild is max_batch * max_blocks ints — noise next to a device
        step)."""
        self._tables[:] = 0
        for req in self.scheduler.running():
            row = self._tables[req.slot]
            row[:len(req.blocks)] = req.blocks

    def _sampling_arrays(self):
        """Per-slot sampling-policy data ([max_batch] each, rebuilt per
        call — policies are data, never shape)."""
        B = self.serving.max_batch
        temp = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.uint32)
        steps = np.zeros((B,), np.int32)
        for req in self.scheduler.running():
            s = req.sampling
            temp[req.slot] = s.temperature
            top_k[req.slot] = s.top_k
            top_p[req.slot] = s.top_p
            seeds[req.slot] = s.seed & 0xFFFFFFFF
            # step_offset rebases the draw counter for fleet failover
            # replays (prompt already carries the emitted prefix)
            steps[req.slot] = s.step_offset + len(req.output_tokens)
        return temp, top_k, top_p, seeds, steps

    def _prefill_tick(self) -> None:
        """Advance every prefilling slot by at most one chunk
        (``prefill_len`` tokens) in ONE fixed-shape device call; slots
        whose prompt completes this chunk sample their first token
        in-graph."""
        B, T = self.serving.max_batch, self.prefill_len
        bs = self.cache.block_size
        cands = sorted(
            (r for r in self.scheduler.running() if r.prefilling),
            key=lambda r: r.admit_seq)
        plan: List[Tuple[Request, int]] = []
        for req in cands:
            if req.slot is None or not req.prefilling:
                continue    # preempted by an older request's growth
            chunk = min(req.prefill_target - req.cache_len, T)
            if self.live_prefill_chunk is not None:
                # live retune (ISSUE 18): the cap is data — the device
                # call keeps its compiled [B, T] shape and fills less
                chunk = min(chunk, self.live_prefill_chunk)
            covered = self.scheduler.try_grow_to(
                req, req.cache_len + chunk)
            chunk = min(chunk, covered - req.cache_len)
            if chunk > 0:
                plan.append((req, chunk))
        if not plan:
            return

        tokens = np.zeros((B, T), np.int32)
        pos_ids = np.zeros((B, T), np.int32)
        limits = np.zeros((B, T), np.int32)
        lengths = np.zeros((B,), np.int32)
        dest_b = np.full((B, T), self.cache.n_blocks, np.int32)  # OOB=drop
        dest_o = np.zeros((B, T), np.int32)
        sample_index = np.full((B,), T, np.int32)                # OOB=none
        for req, chunk in plan:
            s = req.slot
            wire = req.sequence_tokens()
            lo = req.cache_len
            tokens[s, :chunk] = wire[lo:lo + chunk]
            pos_ids[s, :chunk] = np.arange(lo, lo + chunk)
            limits[s, :chunk] = np.arange(lo + 1, lo + chunk + 1)
            lengths[s] = lo + chunk
            dest_b[s, :chunk] = [req.blocks[(lo + t) // bs]
                                 for t in range(chunk)]
            dest_o[s, :chunk] = [(lo + t) % bs for t in range(chunk)]
            if lo + chunk == req.prefill_target:
                sample_index[s] = chunk - 1
        self._refresh_tables()
        samp = self._sampling_arrays()

        with timeline.scope("prefill", rids=[r.rid for r, _ in plan],
                            tokens=int(sum(c for _, c in plan))):
            if self.adapter_arena is None:
                self.arenas, next_tokens, _ = self._prefill(
                    self.arenas, self.params, tokens, pos_ids,
                    self._jnp.asarray(self._tables), lengths, limits,
                    dest_b, dest_o, sample_index, *samp)
            else:
                self.arenas, self.adapters, next_tokens, _ = \
                    self._prefill(
                        self.arenas, self.adapters, self.params, tokens,
                        pos_ids, self._jnp.asarray(self._tables),
                        lengths, limits, dest_b, dest_o, sample_index,
                        self._adapter_slot_array(), *samp)
            next_np = np.asarray(next_tokens)

        now = time.monotonic()
        for req, chunk in plan:
            self.scheduler.note_prefilled(req, chunk)
            if not req.prefilling:
                # prompt complete: the in-graph sample at its last
                # prompt position is the request's next output token.
                # The prefilled marker is the trace walk's prefill →
                # decode boundary (ISSUE 15) — re-emitted per admission
                # (a preempted request's recompute prefill ends here too)
                timeline.emit("request_prefilled", rid=req.rid,
                              tokens=req.prefill_target,
                              **trace_fields(req))
                self._emit(req, int(next_np[req.slot]), now)

    # -------------------------------------------------------------- decode

    def _propose_drafts(self, req: Request) -> List[int]:
        """Ask the proposer for this tick's drafts, clamped to the
        verify width, the context cap, and the remaining budget (the
        verify's own output covers the final token, so a request one
        token from its budget drafts nothing)."""
        if self.proposer is None:
            return []
        max_k = min(self.spec_width - 1,
                    self.cache.max_seq - (req.cache_len + 1),
                    req.max_new_tokens - len(req.output_tokens) - 1)
        if self.live_spec_k is not None:
            # live retune (ISSUE 18): verify keeps its compiled
            # [B, spec_width] shape; k=0 disables drafting entirely
            max_k = min(max_k, self.live_spec_k)
        if max_k <= 0:
            return []
        return list(self.proposer.propose(req, max_k))[:max_k]

    def _decode_once(self) -> None:
        B, S = self.serving.max_batch, self.spec_width
        # a request at the context cap cannot write another token:
        # deliver what it has (truncation is a response, not a hang)
        for req in list(self.scheduler.running()):
            if not req.prefilling and req.cache_len >= self.cache.max_seq:
                self._finish(req)
        # grow this tick's write blocks oldest-first (evict cached LRU,
        # then preempt strictly newer requests); a newer request that
        # cannot grow just sits this tick out — it keeps its cache
        decoding = sorted(
            (r for r in self.scheduler.running() if not r.prefilling),
            key=lambda r: r.admit_seq)
        reqs: List[Request] = []
        drafts: dict = {}
        for req in decoding:
            if req.slot is None or req.state is not RequestState.RUNNING:
                continue    # preempted by an older request's growth
            covered = self.scheduler.try_grow_to(req, req.cache_len + 1)
            if covered < req.cache_len + 1:
                continue
            draft = self._propose_drafts(req)
            if draft:
                # blocks for drafted rows come from the free list or the
                # cache LRU only, NEVER preemption: speculation is an
                # optimization and must not evict a neighbour's real KV.
                # A short grow just truncates the draft (data, not shape).
                covered = self.scheduler.try_grow_to(
                    req, req.cache_len + 1 + len(draft), preempt=False)
                draft = draft[:max(0, covered - (req.cache_len + 1))]
            drafts[req.rid] = draft
            reqs.append(req)
        if not reqs:
            return
        tokens = np.zeros((B, S), np.int32)
        positions = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        n_draft = np.zeros((B,), np.int32)
        for req in reqs:
            d = drafts[req.rid]
            tokens[req.slot, 0] = req.last_token
            if d:
                tokens[req.slot, 1:1 + len(d)] = d
            positions[req.slot] = req.cache_len
            active[req.slot] = True
            n_draft[req.slot] = len(d)
        self._refresh_tables()
        samp = self._sampling_arrays()

        tables = self._jnp.asarray(self._tables)
        if self.adapter_arena is None:
            args = (self.arenas, self.params, tokens, positions, tables,
                    active, n_draft) + samp
        else:
            args = (self.arenas, self.adapters, self.params, tokens,
                    positions, tables, active, n_draft,
                    self._adapter_slot_array()) + samp
        if not self._flops_probed:
            # One-time FLOPs probe for the MFU gauge: lowering traces
            # the decode body (no second XLA compile, no execution —
            # the arenas are not donated by a trace) and the HLO cost
            # pass reports the program's FLOPs.  Must happen BEFORE the
            # call below consumes the donated arenas.
            self._probe_decode_flops(args)
        t0 = time.perf_counter()
        if self.adapter_arena is None:
            self.arenas, out_tokens, accepted, _ = self._decode(*args)
        else:
            self.arenas, self.adapters, out_tokens, accepted, _ = \
                self._decode(*args)
        out_np = np.asarray(out_tokens)
        acc_np = np.asarray(accepted)
        self._last_decode_s = time.perf_counter() - t0
        self._decode_calls += 1
        self._slot_steps += len(reqs)
        self._refresh_mfu()

        now = time.monotonic()
        proposed_total = accepted_total = 0
        for req in reqs:
            d = drafts[req.rid]
            acc = int(acc_np[req.slot])
            if d:
                proposed_total += len(d)
                accepted_total += acc
                if self.proposer is not None:
                    self.proposer.observe(req, len(d), acc)
                aid = getattr(req.sampling, "adapter_id", None)
                if aid is not None and (
                        aid in self.spec_by_adapter
                        or len(self.spec_by_adapter) < 256):
                    # per-adapter acceptance (ISSUE 18 satellite) —
                    # the signal behind LoRA-aware back-off and the
                    # autopilot's spec-k retune; bounded key set
                    row = self.spec_by_adapter.setdefault(aid, [0, 0])
                    row[0] += len(d)
                    row[1] += acc
            # rejection rollback is O(1) by construction: positions past
            # the accepted prefix were written but cache_len simply does
            # not advance over them — pointer/length moves on the host,
            # no KV copies; the rows are overwritten by the next tick
            req.cache_len += 1            # column 0: the real last token
            for j in range(acc + 1):
                if j > 0:
                    req.cache_len += 1    # draft j == the token just
                    #                       emitted — its row is real
                self._emit(req, int(out_np[req.slot, j]), now)
                if req.state is not RequestState.RUNNING:
                    break                 # eos/budget: drop the rest
        if proposed_total:
            self.registry.counter("serving/spec_proposed").inc(
                proposed_total)
            self.spec_proposed += proposed_total
        if accepted_total:
            self.registry.counter("serving/spec_accepted").inc(
                accepted_total)
            self.spec_accepted += accepted_total
        if self.spec_proposed:
            self.registry.gauge("serving/spec_acceptance").set(
                self.spec_accepted / self.spec_proposed)

    # ------------------------------------------------------------------ mfu

    def _probe_decode_flops(self, args) -> None:
        """Fill ``self._decode_flops`` (or the reason it is unknown)."""
        from apex_tpu.observability.metrics import compiled_flops

        self._flops_probed = True
        try:
            lowered = self._decode.lower(*args)
        except Exception as e:  # telemetry never breaks serving
            self._probe_fail_reason = (
                f"decode lowering for cost analysis failed: {e!r}")
            self.mfu_reason = self._probe_fail_reason
            return
        self._decode_flops = compiled_flops(lowered)

    def _refresh_mfu(self) -> None:
        """Derive MFU from the last decode's wall time; flush the gauge
        when defined, keep the None-reason (unknown device peak vs
        missing cost analysis) for ``/statusz`` and logs otherwise."""
        from apex_tpu.observability.metrics import mfu_or_reason

        if self._last_decode_s is None:
            return
        if self._probe_fail_reason is not None:
            # keep the specific probe failure — the generic "no
            # cost-analysis FLOPs" message would misdiagnose it
            self.mfu, self.mfu_reason = None, self._probe_fail_reason
            return
        n_devices = self.mesh.devices.size
        value, reason = mfu_or_reason(
            self._decode_flops, self._last_decode_s,
            device=self.mesh.devices.flat[0], n_devices=n_devices)
        self.mfu, self.mfu_reason = value, reason
        if value is not None:
            self.registry.gauge("serving/mfu").set(value)

    # ---------------------------------------------------------- introspection

    def introspect(self) -> dict:
        """Live engine state for ``/statusz`` (read-only snapshot; the
        :class:`~apex_tpu.observability.debug_server.DebugServer`
        duck-types this)."""
        sched = self.scheduler
        pc = sched.prefix_cache
        return {
            "steps": self._steps,
            "active_slots": len(sched.running()),
            "free_slots": len(sched.free_slots()),
            "free_blocks": sched.allocator.n_free,
            "total_blocks": sched.allocator.n_blocks,
            "queue_depth": len(sched.waiting),
            "draining": self.draining,
            "decode_compiles": self.decode_compile_count(),
            "admission": sched.admission,
            "kv_occupancy": round(sched.kv_occupancy(), 4),
            "prefix_cached_blocks": (pc.n_blocks if pc is not None
                                     else None),
            "prefix_cache_hits": (pc.hits if pc is not None else None),
            "evictions": (pc.evictions if pc is not None else None),
            "preemptions": sched.preemptions,
            "kv_exports_pinned": len(self.exports),
            "spec_width": self.spec_width,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_acceptance": (
                round(self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else None),
            "spec_by_adapter": {
                aid: {"proposed": int(p), "accepted": int(a),
                      "acceptance": round(a / p, 4) if p else None}
                for aid, (p, a) in sorted(self.spec_by_adapter.items())},
            "knobs": self.knobs(),
            "decode_calls": self._decode_calls,
            "adapters_resident": (
                self.adapter_arena.residents()
                if self.adapter_arena is not None else None),
            "adapter_active": (self.adapter_arena.active
                               if self.adapter_arena is not None
                               else None),
            "adapter_loads": (self.adapter_arena.loads
                              if self.adapter_arena is not None
                              else None),
            "adapter_evictions": (self.adapter_arena.evictions
                                  if self.adapter_arena is not None
                                  else None),
            "cache_dtype": str(np.dtype(self.cache.dtype)),
            "last_decode_ms": (round(self._last_decode_s * 1e3, 3)
                               if self._last_decode_s is not None else None),
            "mfu": self.mfu,
            "mfu_reason": self.mfu_reason,
        }

    # ---------------------------------------------------------- bookkeeping

    def _emit(self, req: Request, token: int, now: float) -> None:
        """Record one generated token; finish on eos/budget."""
        if req.t_first_token is None:
            req.t_first_token = now
            self.registry.histogram(
                "serving/ttft_ms", keep_samples=4096).observe(
                    (now - req.t_submit) * 1e3)
        elif req.t_last_token is not None:
            self.registry.histogram(
                "serving/tpot_ms", keep_samples=65536).observe(
                    (now - req.t_last_token) * 1e3)
        req.t_last_token = now
        req.output_tokens.append(token)
        self.registry.counter("serving/tokens_generated").inc()
        n = len(req.output_tokens)
        if n % self.timeline_tick_every == 0:
            timeline.emit("decode_tick", rid=req.rid, tokens=n,
                          **trace_fields(req))
        if (n >= req.max_new_tokens
                or (req.eos_id is not None and token == req.eos_id)):
            self._finish(req)

    def _finish(self, req: Request) -> None:
        self._tables[req.slot][:] = 0
        self.scheduler.finish(req)
        self._unpin_adapter(req)
        self.registry.counter("serving/requests_finished").inc()
        timeline.emit("request_finish", rid=req.rid,
                      tokens=len(req.output_tokens),
                      **trace_fields(req))

    def _unpin_adapter(self, req: Request) -> None:
        """Release a terminal request's adapter pin (no-op for the
        ``adapter_id=None`` majority — every terminal path calls this
        unconditionally)."""
        if self.adapter_arena is not None:
            self.adapter_arena.unpin(req.rid)
            self.registry.gauge("serving/adapter_active").set(
                self.adapter_arena.active)
