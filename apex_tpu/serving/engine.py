"""The serving engine: continuous batching over the paged-cache decode.

One object owns the whole runtime: the compiled prefill/decode programs
(built ONCE — request churn is data, never shape, so the decode step
compiles exactly once per process; :meth:`ServingEngine.
decode_compile_count` pins this in tests), the sharded KV arenas
(donated through every step so XLA updates them in place — APX204,
analyzer entry ``serving_decode``), the host scheduler, the PR 5
metrics, and the PR 3 preemption drain.

Step anatomy (:meth:`ServingEngine.step`)::

    [preemption?] -> admit waiting requests     (slots + blocks)
                  -> prefill the admitted ones  (packed rows, flash)
                  -> one batched decode step    (paged attention)
                  -> append/finish bookkeeping  (host)

Metric catalog (rank-aware registry, docs/observability.md +
docs/serving.md):

- ``serving/ttft_ms``      histogram (sampled: p50/p99) — submit to
  first token, per request
- ``serving/tpot_ms``      histogram (sampled: p50/p99) — inter-token
  interval on the decode path, per token
- ``serving/tokens_generated`` / ``serving/requests_finished`` /
  ``serving/requests_cancelled`` / ``serving/requests_rejected``
  counters (rejected = refused at submit while draining — a typed
  terminal state, distinct from accepted-then-drained cancellation)
- ``serving/active_slots`` / ``serving/free_blocks`` gauges
- ``serving/preemption_drains`` counter
- ``serving/mfu``          gauge — decode-step MFU when the device peak
  is known (``introspect()["mfu_reason"]`` says why otherwise)

Run-timeline (ISSUE 10): with a flight recorder armed
(:mod:`apex_tpu.observability.timeline`) the engine additionally logs
the full request lifecycle keyed by request id — see the class
docstring and docs/observability.md.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, List, Optional, Sequence

import numpy as np

from apex_tpu.observability import timeline
from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel.mesh import TENSOR_AXIS, get_mesh
from apex_tpu.serving.kv_cache import (
    KVCacheConfig,
    arena_partition_spec,
    init_kv_arena,
)
from apex_tpu.serving.model import DecodeModel
from apex_tpu.serving.scheduler import Request, RequestState, Scheduler

__all__ = ["ServingConfig", "ServingEngine"]


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Static shape of the runtime (everything that pins a compile)."""

    max_batch: int = 8           # concurrent decode slots
    block_size: int = 16         # tokens per KV block
    max_seq: int = 256           # per-request context cap (prompt+output)
    n_blocks: Optional[int] = None   # arena size; default = worst case
    prefill_len: Optional[int] = None  # packed prefill row; default max_seq
    cache_dtype: Any = None      # arena storage dtype; default param dtype
    fused_attention: bool = True   # Pallas paged kernel vs unfused XLA
    fuse_epilogue: bool = True     # fused residual/norm epilogue kernel

    def resolve_n_blocks(self, max_blocks_per_request: int) -> int:
        if self.n_blocks is not None:
            return self.n_blocks
        return self.max_batch * max_blocks_per_request


class ServingEngine:
    """Continuous-batching greedy-decode runtime over a GPT checkpoint.

    ``params``: a :class:`~apex_tpu.transformer.testing.
    gpt_parallel_train.GPT3DParams` with the layer stack in the
    canonical ``[vpp, pp, ...]`` form (what ``build_gpt_3d``'s init and
    the :mod:`~apex_tpu.serving.loader` restore both produce — the two
    leading dims are merged row-major into the ``[L, ...]`` serving
    stack).  ``guard``: an optional
    :class:`~apex_tpu.resilience.PreemptionGuard`; once it trips, the
    engine drains — no admissions, running requests decode to
    completion and deliver, waiting ones are cancelled.

    ``heartbeat``: an optional :class:`~apex_tpu.observability.metrics.
    HeartbeatMonitor` — the engine beats it at the end of every
    :meth:`step` (after the decode results materialize), so a hung
    device step (dead collective, wedged transfer) stops the beats, the
    monitor's ``on_hang`` fires the guard, and the engine's next alive
    moment **drains** — delivering in-flight responses — instead of the
    scheduler wedging forever (ISSUE 10 satellite; wire ``on_hang`` to
    the same ``guard``).

    ``timeline_tick_every``: when a flight recorder is armed
    (:mod:`apex_tpu.observability.timeline`), every request's lifecycle
    is logged (submit → admit → prefill → decode ticks → finish/
    cancel, keyed by ``rid``); decode ticks are sampled every N
    generated tokens so the hot loop pays one host dict per N tokens,
    not per token.
    """

    def __init__(self, config, serving: ServingConfig, params, *,
                 mesh=None, tp_axis: str = TENSOR_AXIS, registry=None,
                 guard=None, heartbeat=None, timeline_tick_every: int = 8):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from apex_tpu.observability.metrics import default_registry
        from apex_tpu.transformer.tensor_parallel import infer_param_specs

        self.mesh = mesh if mesh is not None else get_mesh()
        self.tp_axis = tp_axis
        self.serving = serving
        if (config.position_embedding_type == "learned"
                and config.max_position_embeddings < serving.max_seq):
            raise ValueError(
                f"max_seq ({serving.max_seq}) exceeds the learned position "
                f"table ({config.max_position_embeddings})")

        cache_dtype = (serving.cache_dtype if serving.cache_dtype is not None
                       else config.param_dtype)
        probe = KVCacheConfig(
            n_layers=config.num_layers, n_blocks=1,
            block_size=serving.block_size, kv_heads=config.query_groups,
            head_dim=config.head_dim, max_seq=serving.max_seq,
            dtype=cache_dtype)
        self.cache = dataclasses.replace(
            probe,
            n_blocks=serving.resolve_n_blocks(probe.max_blocks_per_request))
        self.model = DecodeModel(
            config, self.cache, fused_attention=serving.fused_attention,
            fuse_epilogue=serving.fuse_epilogue)
        self.prefill_len = serving.prefill_len or serving.max_seq

        # [vpp, pp, ...] -> [L, ...] (row-major merge == virtual-stage
        # major == plain layer order; gpt3d_logical_folds rationale)
        L = config.num_layers
        params = params._replace(layers=jax.tree_util.tree_map(
            lambda l: l.reshape((L,) + l.shape[2:]), params.layers))
        self.params = params

        e_specs = infer_param_specs(params.embedding, axis=tp_axis)
        per_layer = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype),
            params.layers)
        l_specs = jax.tree_util.tree_map(
            lambda s: P(None, *tuple(s)),
            infer_param_specs(per_layer, axis=tp_axis),
            is_leaf=lambda x: isinstance(x, P))
        ln_specs = jax.tree_util.tree_map(lambda _: P(), params.final_ln)
        self.param_specs = type(params)(
            embedding=e_specs, layers=l_specs, final_ln=ln_specs)

        self.arenas = init_kv_arena(self.cache, self.mesh, tp_axis)
        a_spec = arena_partition_spec(tp_axis)

        rep = P()
        decode_body = cc.shard_over(
            self.model.decode_step, mesh=self.mesh,
            in_specs=(a_spec, a_spec, self.param_specs, rep, rep, rep, rep),
            out_specs=(a_spec, a_spec, P(None), P(None, None)),
        )
        prefill_body = cc.shard_over(
            self.model.prefill, mesh=self.mesh,
            in_specs=(a_spec, a_spec, self.param_specs, rep, rep, rep, rep,
                      rep),
            out_specs=(a_spec, a_spec, P(None), P(None, None)),
        )
        # the arenas are donated: the KV cache must alias in->out or the
        # biggest HBM tenant of the chip doubles (APX204, entry
        # serving_decode)
        self._decode = jax.jit(decode_body, donate_argnums=(0, 1))
        self._prefill = jax.jit(prefill_body, donate_argnums=(0, 1))
        self._jnp = jnp

        self.scheduler = Scheduler(self.cache, serving.max_batch)
        self.registry = registry if registry is not None else \
            default_registry()
        self.guard = guard
        self.heartbeat = heartbeat
        if timeline_tick_every < 1:
            raise ValueError(
                f"timeline_tick_every must be >= 1, got "
                f"{timeline_tick_every}")
        self.timeline_tick_every = timeline_tick_every
        self._tables = np.zeros(
            (serving.max_batch, self.cache.max_blocks_per_request),
            np.int32)
        self._steps = 0
        # MFU bookkeeping (ISSUE 10 satellite): FLOPs of the decode
        # program probed once (lazily, pre-donation), last decode wall
        # time measured each step; serving/mfu flushed as a gauge when
        # defined, else the reason string is kept for /statusz.
        self._decode_flops: Optional[float] = None
        self._last_decode_s: Optional[float] = None
        self._flops_probed = False
        self._probe_fail_reason: Optional[str] = None
        self.mfu: Optional[float] = None
        self.mfu_reason: Optional[str] = "decode step has not run yet"

    # -------------------------------------------------------------- intro

    def decode_compile_count(self) -> int:
        """Compiled-variant count of the decode step (the zero-recompile
        contract: stays 1 across any request churn)."""
        return int(self._decode._cache_size())

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    # -------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_id: Optional[int] = None) -> Request:
        if len(np.shape(prompt)) != 1 or len(prompt) > self.prefill_len:
            raise ValueError(
                f"prompt must be 1-D with at most prefill_len="
                f"{self.prefill_len} tokens, got shape {np.shape(prompt)}")
        req = self.scheduler.submit(prompt, max_new_tokens, eos_id)
        timeline.emit("request_submit", rid=req.rid,
                      prompt_tokens=len(req.prompt),
                      max_new_tokens=max_new_tokens)
        if req.state is RequestState.REJECTED:
            # submitted into the drain window: refused with a typed
            # terminal state (never queued, never a hang) and counted
            # apart from drain cancellations — a router re-routes a
            # REJECTED request, it does not mourn it
            self.registry.counter("serving/requests_rejected").inc()
            timeline.emit("request_reject", rid=req.rid)
        return req

    # --------------------------------------------------------------- drain

    def drain(self) -> List[Request]:
        """Preemption path: cancel the queue, keep decoding the running
        requests until their responses are delivered."""
        timeline.emit("preemption", wall_ts=time.time())
        cancelled = self.scheduler.drain()
        if cancelled:
            self.registry.counter("serving/requests_cancelled").inc(
                len(cancelled))
        for req in cancelled:
            timeline.emit("request_cancel", rid=req.rid)
        self.registry.counter("serving/preemption_drains").inc()
        return cancelled

    # ---------------------------------------------------------------- step

    def step(self) -> None:
        """One engine tick: admit + prefill joiners, one decode step."""
        if (self.guard is not None and self.guard.triggered
                and not self.draining):
            self.drain()
        admitted = self.scheduler.admit()
        for req in admitted:
            timeline.emit("request_admit", rid=req.rid, slot=req.slot,
                          blocks=len(req.blocks))
        for row in self._pack_rows(admitted):
            self._prefill_row(row)
        self._decode_once()
        self._steps += 1
        self.registry.gauge("serving/active_slots").set(
            len(self.scheduler.running()))
        self.registry.gauge("serving/free_blocks").set(
            self.scheduler.allocator.n_free)
        # the beat lands only after this tick's device work materialized
        # — a wedged decode stops the beats and the monitor fires the
        # guard, turning a scheduler wedge into an ordinary drain
        if self.heartbeat is not None:
            self.heartbeat.beat(self._steps)

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        """Drive :meth:`step` until no request is waiting or running
        (under drain: until the running ones have delivered)."""
        for _ in range(max_steps):
            if self.scheduler.idle:
                return
            self.step()
        raise RuntimeError(f"not drained after {max_steps} steps")

    # ------------------------------------------------------------- prefill

    def _pack_rows(self, reqs: List[Request]) -> List[List[Request]]:
        """First-fit pack admitted prompts into ``[1, prefill_len]``
        rows — several requests prefill in one flash pass (segment ids
        keep them from attending to each other)."""
        rows: List[List[Request]] = []
        fill = 0
        for req in reqs:
            n = len(req.prompt)
            if not rows or fill + n > self.prefill_len:
                rows.append([])
                fill = 0
            rows[-1].append(req)
            fill += n
        return rows

    def _prefill_row(self, reqs: List[Request]) -> None:
        L = self.prefill_len
        bs = self.cache.block_size
        tokens = np.zeros((1, L), np.int32)
        pos_ids = np.zeros((1, L), np.int32)
        seg_ids = np.zeros((1, L), np.int32)
        dest_b = np.full((L,), self.cache.n_blocks, np.int32)  # OOB=dropped
        dest_o = np.zeros((L,), np.int32)
        last_index = {}
        cursor = 0
        for si, req in enumerate(reqs, start=1):
            p = len(req.prompt)
            sl = slice(cursor, cursor + p)
            tokens[0, sl] = req.prompt
            pos_ids[0, sl] = np.arange(p)
            seg_ids[0, sl] = si
            dest_b[sl] = [req.blocks[t // bs] for t in range(p)]
            dest_o[sl] = [t % bs for t in range(p)]
            last_index[req.rid] = cursor + p - 1
            cursor += p

        k, v = self.arenas
        with timeline.scope("prefill", rids=[r.rid for r in reqs],
                            tokens=cursor):
            k, v, next_tokens, _ = self._prefill(
                k, v, self.params, tokens, pos_ids, seg_ids, dest_b, dest_o)
            self.arenas = (k, v)
            next_np = np.asarray(next_tokens)

        now = time.monotonic()
        for req in reqs:
            req.cache_len = len(req.prompt)
            row = self._tables[req.slot]
            row[:] = 0
            row[:len(req.blocks)] = req.blocks
            self._emit(req, int(next_np[last_index[req.rid]]), now)

    # -------------------------------------------------------------- decode

    def _decode_once(self) -> None:
        B = self.serving.max_batch
        # a request at the context cap cannot write another token:
        # deliver what it has (truncation is a response, not a hang)
        for req in list(self.scheduler.running()):
            if req.cache_len >= self.cache.max_seq:
                self._finish(req)
        reqs = self.scheduler.running()
        if not reqs:
            return
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        for req in reqs:
            tokens[req.slot, 0] = req.last_token
            positions[req.slot] = req.cache_len
            active[req.slot] = True

        k, v = self.arenas
        tables = self._jnp.asarray(self._tables)
        if not self._flops_probed:
            # One-time FLOPs probe for the MFU gauge: lowering traces
            # the decode body (no second XLA compile, no execution —
            # the arenas are not donated by a trace) and the HLO cost
            # pass reports the program's FLOPs.  Must happen BEFORE the
            # call below consumes the donated arenas.
            self._probe_decode_flops(
                (k, v, self.params, tokens, positions, tables, active))
        t0 = time.perf_counter()
        k, v, next_tokens, _ = self._decode(
            k, v, self.params, tokens, positions, tables, active)
        self.arenas = (k, v)
        next_np = np.asarray(next_tokens)
        self._last_decode_s = time.perf_counter() - t0
        self._refresh_mfu()

        now = time.monotonic()
        for req in reqs:
            req.cache_len += 1
            self._emit(req, int(next_np[req.slot]), now)

    # ------------------------------------------------------------------ mfu

    def _probe_decode_flops(self, args) -> None:
        """Fill ``self._decode_flops`` (or the reason it is unknown)."""
        from apex_tpu.observability.metrics import compiled_flops

        self._flops_probed = True
        try:
            lowered = self._decode.lower(*args)
        except Exception as e:  # telemetry never breaks serving
            self._probe_fail_reason = (
                f"decode lowering for cost analysis failed: {e!r}")
            self.mfu_reason = self._probe_fail_reason
            return
        self._decode_flops = compiled_flops(lowered)

    def _refresh_mfu(self) -> None:
        """Derive MFU from the last decode's wall time; flush the gauge
        when defined, keep the None-reason (unknown device peak vs
        missing cost analysis) for ``/statusz`` and logs otherwise."""
        from apex_tpu.observability.metrics import mfu_or_reason

        if self._last_decode_s is None:
            return
        if self._probe_fail_reason is not None:
            # keep the specific probe failure — the generic "no
            # cost-analysis FLOPs" message would misdiagnose it
            self.mfu, self.mfu_reason = None, self._probe_fail_reason
            return
        n_devices = self.mesh.devices.size
        value, reason = mfu_or_reason(
            self._decode_flops, self._last_decode_s,
            device=self.mesh.devices.flat[0], n_devices=n_devices)
        self.mfu, self.mfu_reason = value, reason
        if value is not None:
            self.registry.gauge("serving/mfu").set(value)

    # ---------------------------------------------------------- introspection

    def introspect(self) -> dict:
        """Live engine state for ``/statusz`` (read-only snapshot; the
        :class:`~apex_tpu.observability.debug_server.DebugServer`
        duck-types this)."""
        return {
            "steps": self._steps,
            "active_slots": len(self.scheduler.running()),
            "free_slots": len(self.scheduler.free_slots()),
            "free_blocks": self.scheduler.allocator.n_free,
            "total_blocks": self.scheduler.allocator.n_blocks,
            "queue_depth": len(self.scheduler.waiting),
            "draining": self.draining,
            "decode_compiles": self.decode_compile_count(),
            "last_decode_ms": (round(self._last_decode_s * 1e3, 3)
                               if self._last_decode_s is not None else None),
            "mfu": self.mfu,
            "mfu_reason": self.mfu_reason,
        }

    # ---------------------------------------------------------- bookkeeping

    def _emit(self, req: Request, token: int, now: float) -> None:
        """Record one generated token; finish on eos/budget."""
        if req.t_first_token is None:
            req.t_first_token = now
            self.registry.histogram(
                "serving/ttft_ms", keep_samples=4096).observe(
                    (now - req.t_submit) * 1e3)
        else:
            self.registry.histogram(
                "serving/tpot_ms", keep_samples=65536).observe(
                    (now - req.t_last_token) * 1e3)
        req.t_last_token = now
        req.output_tokens.append(token)
        self.registry.counter("serving/tokens_generated").inc()
        n = len(req.output_tokens)
        if n % self.timeline_tick_every == 0:
            timeline.emit("decode_tick", rid=req.rid, tokens=n)
        if (n >= req.max_new_tokens
                or (req.eos_id is not None and token == req.eos_id)):
            self._finish(req)

    def _finish(self, req: Request) -> None:
        self._tables[req.slot][:] = 0
        self.scheduler.finish(req)
        self.registry.counter("serving/requests_finished").inc()
        timeline.emit("request_finish", rid=req.rid,
                      tokens=len(req.output_tokens))
