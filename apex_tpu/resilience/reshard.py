"""Mesh-agnostic checkpoint resharding — restore-anywhere (ISSUE 6).

PR 3's crash-safe checkpoints restore bit-identically, but only onto the
mesh shape that wrote them: a sharded save records *placed* arrays, and
three of our state families are placement-DEPENDENT in shape, not just
in slicing —

- ZeRO flat-bucket buffers (``contrib/optimizers/_flat_bucket.py``):
  ``(rows, chunk)`` per dtype-group bucket, rows padded to a multiple of
  ``world * n_buckets`` — a different dp world size is a different
  *global shape*;
- ZeRO per-leaf chunked state: rank-major padded ravels, padded to the
  world size;
- pipeline layer stacks (``gpt_parallel_train.GPT3DParams.layers``):
  ``[vpp, pp, ...]`` whose leading dims re-factor when the pipeline
  depth changes (``tp=2,pp=2`` -> ``tp=4,pp=1`` turns ``[1, 2, ...]``
  into ``[2, 1, ...]``).

The fix is the veScale / TorchTitan-DCP idea (PAPERS.md,
arxiv 2509.07003 / 2410.06511): describe state *logically* —
independent of placement — and reshard on load.  This module owns that
logical layer:

- :class:`ShardingSpec` / :func:`build_spec` — the JSON-serializable
  logical description of a checkpointed tree: per-leaf partition axis
  names, fold counts (leading dims that are a reshape of one logical
  axis), padded-ravel markers, and the ``chunked_meta`` bucket layout of
  every ZeRO flat-bucket dtype-group.  The save path embeds it in the
  manifest next to the crc32 entries (``checkpoint.py``, manifest
  version 2).
- :func:`restore_resharded` — map a committed checkpoint (flat file or
  sharded dir) onto an *arbitrary* target template: leaves whose global
  shape is unchanged restore through the existing lazy slice-assembly
  path; shape-changed leaves are assembled to their logical form on host
  (pure reshape/concat/truncate — **no arithmetic**, so the round trip
  is fp32-bit-lossless) and re-laid-out for the target mesh, including
  unflattening and re-chunking flat buckets for a different dp world.
- :func:`load_logical` — the canonical mesh-independent fingerprint of
  a checkpoint (every leaf in logical form, on host): what the elastic
  fault harness (``testing/crash_resume.py`` /
  ``scripts/elastic_resume_smoke.sh``) compares bitwise across mesh
  shapes.

``CheckpointManager.restore_latest(like, spec=...)`` dispatches here
when a candidate's stored shapes disagree with the template, preserving
verification and corrupt-fallback (``resilience/manager.py``).  The
failure model and supported transitions are documented in
``docs/resilience.md`` ("restore-anywhere") and ``docs/checkpoint.md``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.checkpoint import CheckpointCorruptError

__all__ = [
    "ShardingSpec",
    "build_spec",
    "restore_resharded",
    "load_logical",
]

SPEC_VERSION = 1


def _spec_error(msg: str) -> CheckpointCorruptError:
    """Spec problems are corruption-class: ``restore_latest`` must be
    able to fall back past a checkpoint whose logical description is
    missing or inconsistent, exactly like a failed checksum."""
    return CheckpointCorruptError(msg)


@dataclasses.dataclass
class ShardingSpec:
    """Logical sharding description of one checkpointed tree.

    ``leaves``: checkpoint leaf path -> record with
        ``axes``      per-dim mesh axis names (``None`` = replicated) —
                      recorded from the live shardings for audit;
        ``fold``      N > 0: the leading N dims are a reshape of ONE
                      logical axis (row-major, so merging them by plain
                      reshape recovers the logical stack — the
                      ``[vpp, pp]`` -> ``[L]`` virtual-stage-major map);
        ``ravel_of``  logical shape whose zero-padded ravel this leaf
                      stores (ZeRO per-leaf chunked state);
        ``group`` / ``bucket``  membership of a flat-bucket group.
    ``groups``: group key -> record with the ordered bucket leaf
        ``paths``, the ``chunk`` width, ``n_buckets``, and the logical
        ``shapes`` of the member leaves (``chunked_meta`` layout inputs:
        concat(buckets) unflattens to exactly these leaves).
    ``mesh``: axis name -> size at build time (audit/debug only: the
        restore math needs no source world size — the buffer rows encode
        it).
    """

    mesh: Dict[str, int] = dataclasses.field(default_factory=dict)
    leaves: Dict[str, dict] = dataclasses.field(default_factory=dict)
    groups: Dict[str, dict] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {"version": SPEC_VERSION, "mesh": dict(self.mesh),
                "leaves": self.leaves, "groups": self.groups}

    @classmethod
    def from_json(cls, doc: Any, *, where: str = "checkpoint"
                  ) -> "ShardingSpec":
        if not isinstance(doc, dict):
            raise _spec_error(
                f"{where}: sharding_spec is not an object ({type(doc)})")
        ver = doc.get("version")
        if ver != SPEC_VERSION:
            raise _spec_error(
                f"{where}: sharding_spec.version is {ver!r}, this reader "
                f"supports {SPEC_VERSION}")
        for field in ("leaves", "groups"):
            if not isinstance(doc.get(field), dict):
                raise _spec_error(
                    f"{where}: sharding_spec.{field} missing or invalid")
        return cls(mesh=dict(doc.get("mesh") or {}),
                   leaves=doc["leaves"], groups=doc["groups"])

    def leaf(self, path: str) -> dict:
        return self.leaves.get(path) or {}


def _leaf_axes(x) -> Optional[List[Optional[List[str]]]]:
    """Per-dim mesh axis names from a leaf's NamedSharding (None when
    the leaf is not a committed named-sharded array)."""
    import jax

    if not isinstance(x, jax.Array):
        return None
    spec = getattr(getattr(x, "sharding", None), "spec", None)
    if spec is None:
        return None
    ndim = np.ndim(x)
    out: List[Optional[List[str]]] = []
    for d in range(ndim):
        entry = spec[d] if d < len(spec) else None
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append([str(entry)])
    return out


def _tree_paths(tree) -> List[Tuple[str, Any]]:
    import jax

    from apex_tpu.checkpoint import _path_str

    return [(_path_str(p), x)
            for p, x in jax.tree_util.tree_leaves_with_path(tree)]


def build_spec(tree, *, mesh=None, folds=None,
               zero_states: Sequence[Tuple[str, Any, Any]] = ()
               ) -> ShardingSpec:
    """Build the :class:`ShardingSpec` for ``tree`` as it will be saved.

    ``mesh``   — the live :class:`jax.sharding.Mesh` (axis sizes are
                 recorded for audit).
    ``folds``  — optional pytree of ints, same structure as ``tree``
                 (0 = plain leaf): number of leading dims that fold into
                 one logical axis (see
                 ``gpt_parallel_train.gpt3d_logical_folds``).
    ``zero_states`` — ``(path_prefix, optimizer, params)`` triples for
                 every ZeRO-sharded ``OptState`` inside ``tree`` (e.g.
                 ``("opt", opt, params)`` when the saved tree is
                 ``{"opt": state, ...}``): flat-bucket optimizers get
                 per-dtype-group bucket layouts, per-leaf optimizers get
                 padded-ravel markers.
    """
    import jax

    flat = _tree_paths(tree)
    paths = [p for p, _ in flat]
    leaves: Dict[str, dict] = {}

    fold_by_path: Dict[str, int] = {}
    if folds is not None:
        fflat = jax.tree_util.tree_leaves(folds)
        if len(fflat) != len(flat):
            raise ValueError(
                f"folds tree has {len(fflat)} leaves, tree has "
                f"{len(flat)} — structures must match")
        fold_by_path = {p: int(f) for (p, _), f in zip(flat, fflat) if f}

    for path, x in flat:
        rec: dict = {}
        axes = _leaf_axes(x)
        if axes is not None:
            rec["axes"] = axes
        fold = fold_by_path.get(path, 0)
        if fold:
            shape = tuple(np.shape(x))
            if fold >= len(shape) + 1:
                raise ValueError(
                    f"{path}: fold={fold} exceeds rank {len(shape)}")
            rec["fold"] = fold
        if rec:
            leaves[path] = rec

    groups: Dict[str, dict] = {}
    for prefix, opt, params in zero_states:
        _add_zero_state(leaves, groups, paths, prefix, opt, params)

    mesh_sizes = dict(mesh.shape) if mesh is not None else {}
    return ShardingSpec(mesh=mesh_sizes, leaves=leaves, groups=groups)


def _add_zero_state(leaves, groups, paths, prefix, opt, params) -> None:
    """Annotate one ZeRO ``OptState``'s leaves under ``prefix``."""
    from apex_tpu.checkpoint import _path_str  # noqa: F401  (doc link)
    import jax

    param_flat = _tree_paths(params)
    if getattr(opt, "flat_bucket", False):
        from apex_tpu.contrib.optimizers import _flat_bucket as fbk

        _, leaves_list, raw_groups = fbk.host_groups(params)
        n_buckets = int(opt.n_buckets)
        chunk = int(opt.chunk)
        for slot in _zero_slot_names(paths, prefix):
            for g, (_, idx) in enumerate(raw_groups):
                key = f"{prefix}/{slot}/{g}"
                bucket_paths = [
                    f"{prefix}/.{slot_path(slot)}/{g}/{k}"
                    for k in range(n_buckets)
                ]
                missing = [p for p in bucket_paths if p not in paths]
                if missing:
                    raise ValueError(
                        f"zero_states[{prefix!r}]: expected bucket leaves "
                        f"{missing} not found in the saved tree — is the "
                        "OptState stored under a different key?")
                groups[key] = {
                    "paths": bucket_paths,
                    "chunk": chunk,
                    "n_buckets": n_buckets,
                    "shapes": [list(np.shape(leaves_list[i])) for i in idx],
                }
                for k, p in enumerate(bucket_paths):
                    rec = leaves.setdefault(p, {})
                    rec["group"] = key
                    rec["bucket"] = k
    else:
        # per-leaf layout: every slot/master leaf is the zero-padded
        # rank-major ravel of the same-suffixed param leaf
        by_suffix = {p: tuple(np.shape(x)) for p, x in param_flat}
        for path in paths:
            suffix = _zero_leaf_suffix(path, prefix)
            if suffix is None or suffix not in by_suffix:
                continue
            leaves.setdefault(path, {})["ravel_of"] = \
                list(by_suffix[suffix])


def slot_path(slot: str) -> str:
    """Tree-path component of one state family: ``slots/<name>`` leaves
    live under ``.slots/<name>``, the master copy under ``.master``."""
    return "master" if slot == "master" else f"slots/{slot}"


def _zero_slot_names(paths, prefix) -> List[str]:
    """Slot names present in the saved tree (plus ``master`` when the
    optimizer keeps a master copy)."""
    names = []
    slots_prefix = f"{prefix}/.slots/"
    for p in paths:
        if p.startswith(slots_prefix):
            name = p[len(slots_prefix):].split("/", 1)[0]
            if name not in names:
                names.append(name)
    if any(p.startswith(f"{prefix}/.master/") for p in paths):
        names.append("master")
    return names


def _zero_leaf_suffix(path, prefix) -> Optional[str]:
    slots_prefix = f"{prefix}/.slots/"
    if path.startswith(slots_prefix):
        rest = path[len(slots_prefix):]
        parts = rest.split("/", 1)
        return parts[1] if len(parts) == 2 else None
    master_prefix = f"{prefix}/.master/"
    if path.startswith(master_prefix):
        return path[len(master_prefix):]
    return None


# ---------------------------------------------------------------------------
# Source-side: committed checkpoint -> full/logical host arrays
# ---------------------------------------------------------------------------


class _Source:
    """Read-side view of a committed checkpoint (flat ``.npz`` file or
    sharded dir): manifest + ``full(i)`` assembling leaf ``i``'s whole
    global value on host.  Keeps the npz handles open (lazy decompress)
    until ``close``."""

    def __init__(self, path: str):
        from apex_tpu import checkpoint as ckpt

        self._files = []
        self._shards: Dict[str, Any] = {}
        self._flat = None
        if os.path.isdir(path):
            shard_paths = ckpt._shard_paths(path)
            if not shard_paths:
                raise FileNotFoundError(f"no shard_*.npz under {path!r}")
            manifest = None
            for p in shard_paths:
                data = np.load(p, allow_pickle=False)
                self._files.append(data)
                m = json.loads(str(data["__manifest__"]))
                ckpt._check_manifest_version(m, p)
                if manifest is None:
                    manifest = m
                elif (m.get("step") != manifest.get("step")
                      or m.get("process_count")
                      != manifest.get("process_count")):
                    # same torn/mixed-checkpoint guard as the plain
                    # sharded restore: without it a legacy (manifest-
                    # less) dir holding shards of two different steps
                    # would silently assemble a chimera state
                    raise CheckpointCorruptError(
                        f"inconsistent shard files under {path!r}: "
                        f"{os.path.basename(p)} has step={m.get('step')} "
                        f"process_count={m.get('process_count')} vs "
                        f"step={manifest.get('step')} process_count="
                        f"{manifest.get('process_count')} — torn or "
                        "mixed checkpoint")
                for key in data.files:
                    if key != "__manifest__":
                        self._shards[key] = data
        else:
            data = np.load(path, allow_pickle=False)
            self._files.append(data)
            manifest = json.loads(str(data["__manifest__"]))
            ckpt._check_manifest_version(manifest, path)
            self._flat = data
        self.path = path
        self.manifest = manifest
        self.leaves = manifest["leaves"]

    def spec(self) -> ShardingSpec:
        doc = self.manifest.get("sharding_spec")
        if doc is None:
            raise _spec_error(
                f"{self.path}: manifest (version "
                f"{self.manifest.get('version', 1)}) has no sharding_spec "
                "— it predates the logical-spec layer, so it can only be "
                "restored onto the mesh shape that wrote it (use the "
                "plain restore path / a matching template)")
        return ShardingSpec.from_json(doc, where=self.path)

    def full(self, i: int) -> np.ndarray:
        """Leaf ``i``'s complete global value as a host array."""
        from apex_tpu import checkpoint as ckpt

        shape = tuple(self.leaves[i]["shape"])
        if self._flat is not None:
            return np.asarray(self._flat[f"leaf_{i}"])
        key_full = f"leaf_{i}|full"
        if key_full in self._shards:
            return np.asarray(self._shards[key_full][key_full])
        index = tuple(slice(0, d) for d in shape)
        return np.asarray(
            ckpt._assemble_slice(self._shards, i, index, shape))

    def close(self) -> None:
        for f in self._files:
            f.close()
        self._files, self._shards, self._flat = [], {}, None

    def __enter__(self) -> "_Source":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _chunk_rows(size: int, chunk: int) -> int:
    """Rows one leaf occupies in a ``(rows, chunk)`` buffer — matches
    ``utils.tree.chunked_meta`` (zero-size leaves occupy zero rows)."""
    return -(-size // chunk)


def _unflatten_np(buffer: np.ndarray, shapes, chunk: int
                  ) -> List[np.ndarray]:
    """Host-side inverse of ``utils.tree.flatten_to_chunked``: slice each
    logical leaf's rows back out of the ``(rows, chunk)`` buffer (pure
    indexing — bit-exact)."""
    flat = np.ascontiguousarray(buffer).reshape(-1)
    out, row = [], 0
    for shape in shapes:
        size = int(np.prod(shape)) if shape else 1
        rows = _chunk_rows(size, chunk)
        start = row * chunk
        if start + size > flat.size:
            raise _spec_error(
                "flat-bucket buffer too small for its sharding_spec "
                f"shapes (need {start + size} elements, buffer has "
                f"{flat.size})")
        out.append(flat[start:start + size].reshape(shape))
        row += rows
    return out


def _flatten_np(leaves, chunk: int, rows_total: int, dtype) -> np.ndarray:
    """Host-side ``flatten_to_chunked``: pack logical leaves into a
    ``(rows_total, chunk)`` zero-padded buffer (pure indexing)."""
    flat = np.zeros((rows_total * chunk,), dtype=dtype)
    row = 0
    for leaf in leaves:
        size = int(leaf.size)
        rows = _chunk_rows(size, chunk)
        start = row * chunk
        if start + size > flat.size:
            raise _spec_error(
                "target flat-bucket layout too small for the logical "
                f"leaves (need {start + size} elements, buffer has "
                f"{flat.size} = {rows_total} x {chunk} rows)")
        flat[start:start + size] = np.ascontiguousarray(leaf).reshape(-1)
        row += rows
    return flat.reshape(rows_total, chunk)


def _leaf_logical(full: np.ndarray, rec: dict, path: str) -> np.ndarray:
    """Apply a leaf's inverse transform: stored full value -> logical."""
    fold = int(rec.get("fold", 0) or 0)
    ravel_of = rec.get("ravel_of")
    if fold:
        shape = full.shape
        if fold > len(shape):
            raise _spec_error(
                f"{path}: sharding_spec fold={fold} exceeds stored rank "
                f"{len(shape)}")
        return full.reshape((-1,) + tuple(shape[fold:]))
    if ravel_of is not None:
        target = tuple(int(d) for d in ravel_of)
        n = int(np.prod(target)) if target else 1
        flat = full.reshape(-1)
        if flat.size < n:
            raise _spec_error(
                f"{path}: stored padded ravel has {flat.size} elements, "
                f"sharding_spec.ravel_of {list(target)} needs {n}")
        return flat[:n].reshape(target)
    return full


def _leaf_placed(logical: np.ndarray, rec: dict, target_shape, path: str
                 ) -> np.ndarray:
    """Apply a leaf's forward transform: logical -> target layout."""
    target_shape = tuple(int(d) for d in target_shape)
    fold = int(rec.get("fold", 0) or 0)
    ravel_of = rec.get("ravel_of")
    if ravel_of is not None:
        n = int(np.prod(target_shape)) if target_shape else 1
        flat = np.ascontiguousarray(logical).reshape(-1)
        if flat.size > n:
            raise _spec_error(
                f"{path}: logical leaf has {flat.size} elements, target "
                f"padded ravel {list(target_shape)} holds only {n}")
        out = np.zeros((n,), dtype=logical.dtype)
        out[:flat.size] = flat
        return out.reshape(target_shape)
    if logical.size != int(np.prod(target_shape) if target_shape else 1):
        raise _spec_error(
            f"{path}: logical element count {logical.size} does not "
            f"match target shape {list(target_shape)}"
            + (f" (fold={fold})" if fold else ""))
    return logical.reshape(target_shape)


# ---------------------------------------------------------------------------
# The restore-anywhere entry points
# ---------------------------------------------------------------------------


def load_logical(path: str) -> Tuple[Dict[str, np.ndarray], Optional[int]]:
    """Canonical mesh-independent view of a committed checkpoint: every
    leaf assembled on host and mapped to its logical form.  Flat-bucket
    groups expand to their member leaves (keyed ``<group>[<j>]``); the
    bucket leaves themselves are omitted.  Returns ``(leaves, step)``.

    This is the fingerprint the elastic fault harness compares bitwise
    across mesh shapes: two checkpoints of the same training state saved
    under different dp/tp/pp layouts must load_logical identically.
    Spec-less (pre-reshard) checkpoints load as plain full leaves.
    """
    with _Source(path) as src:
        # Only a truly ABSENT spec falls back to the plain-leaf view; a
        # malformed or newer-version spec must propagate (fingerprinting
        # placed buffers instead would blame "state divergence" on what
        # is actually a corrupt spec).
        doc = src.manifest.get("sharding_spec")
        spec = (ShardingSpec() if doc is None
                else ShardingSpec.from_json(doc, where=src.path))
        index = {rec["path"]: i for i, rec in enumerate(src.leaves)}
        out: Dict[str, np.ndarray] = {}
        done_groups = set()
        for i, rec in enumerate(src.leaves):
            p = rec["path"]
            lrec = spec.leaf(p)
            key = lrec.get("group")
            if key is not None:
                if key in done_groups:
                    continue
                done_groups.add(key)
                for j, leaf in enumerate(
                        _group_logical(src, spec, key, index)):
                    out[f"{key}[{j}]"] = leaf
                continue
            out[p] = _leaf_logical(src.full(i), lrec, p)
        return out, src.manifest.get("step")


def _group_logical(src: "_Source", spec: ShardingSpec, key: str,
                   index: Dict[str, int]) -> List[np.ndarray]:
    """Assemble one flat-bucket group's logical leaves from its stored
    bucket buffers (concat rows, then positional unflatten)."""
    grp = spec.groups.get(key)
    if grp is None:
        raise _spec_error(
            f"{src.path}: leaf references sharding_spec group {key!r} "
            "which is not in sharding_spec.groups")
    for field in ("paths", "chunk", "shapes"):
        if field not in grp:
            raise _spec_error(
                f"{src.path}: sharding_spec.groups[{key!r}] missing "
                f"{field!r}")
    bufs = []
    for p in grp["paths"]:
        if p not in index:
            raise _spec_error(
                f"{src.path}: sharding_spec.groups[{key!r}] references "
                f"leaf {p!r} absent from the manifest")
        bufs.append(src.full(index[p]))
    buffer = np.concatenate(bufs, axis=0) if len(bufs) > 1 else bufs[0]
    shapes = [tuple(int(d) for d in s) for s in grp["shapes"]]
    return _unflatten_np(buffer, shapes, int(grp["chunk"]))


def restore_resharded(path: str, like: Any, spec: ShardingSpec):
    """Restore a committed checkpoint onto an **arbitrary** target mesh.

    ``like`` supplies the target structure, shapes, dtypes, and
    shardings (as for the plain restores); ``spec`` is the TARGET's
    logical spec (:func:`build_spec` over ``like`` with the target mesh
    and the same folds / ``zero_states``).  The source's spec is read
    from the manifest; leaves are matched by tree path, groups by key.
    Returns ``(tree, step)``.

    Shape-preserved leaves restore through lazy per-shard slice assembly
    (no full materialization); shape-changed leaves go through the
    logical form on host.  Every transform is a reshape/concat/pad/
    truncate — no arithmetic — so restored values are bit-identical to
    the saved logical state.
    """
    import jax

    from apex_tpu import checkpoint as ckpt

    with _Source(path) as src:
        src_spec = src.spec()
        like_flat = _tree_paths(like)
        _, treedef = jax.tree_util.tree_flatten(like)
        if len(like_flat) != len(src.leaves):
            raise _spec_error(
                f"{path}: checkpoint has {len(src.leaves)} leaves, "
                f"template has {len(like_flat)}")
        index = {rec["path"]: i for i, rec in enumerate(src.leaves)}

        # Materialize every target flat-bucket group once: logical
        # leaves from the source layout, re-chunked into the target's.
        # Group layout (paths/chunk/n_buckets/logical shapes) is
        # mesh-INDEPENDENT — every target-dependent size comes from the
        # template's leaf shapes — so where the target spec lacks a
        # group record (a bare spec from ``restore_latest(mesh=...)``)
        # the source's is authoritative; an optimizer-config mismatch
        # (different chunk/n_buckets) fails loudly on the template's
        # leaf paths/shapes below.
        tgt_groups = dict(src_spec.groups)
        tgt_groups.update(spec.groups)
        group_out: Dict[str, np.ndarray] = {}
        for key, tgt in tgt_groups.items():
            logical = _group_logical(src, src_spec, key, index)
            shapes = [tuple(int(d) for d in s) for s in tgt["shapes"]]
            if [tuple(l.shape) for l in logical] != shapes:
                raise _spec_error(
                    f"{path}: group {key!r} logical shapes "
                    f"{[list(l.shape) for l in logical]} do not match "
                    f"the target sharding_spec shapes "
                    f"{[list(s) for s in shapes]}")
            by_path = {p: x for p, x in like_flat}
            tgt_rows = []
            for p in tgt["paths"]:
                if p not in by_path:
                    raise _spec_error(
                        f"target sharding_spec group {key!r} references "
                        f"template leaf {p!r} absent from the template")
                tgt_rows.append(int(np.shape(by_path[p])[0]))
            chunk_t = int(tgt["chunk"])
            buffer = _flatten_np(logical, chunk_t, sum(tgt_rows),
                                 logical[0].dtype if logical
                                 else np.float32)
            off = 0
            for p, rows in zip(tgt["paths"], tgt_rows):
                group_out[p] = buffer[off:off + rows]
                off += rows

        out = []
        for i, ((tpath, tleaf), rec) in enumerate(
                zip(like_flat, src.leaves)):
            if rec["path"] != tpath:
                raise _spec_error(
                    f"{path}: leaf {i} path mismatch: checkpoint "
                    f"{rec['path']!r} vs template {tpath!r}")
            src_shape = tuple(rec["shape"])
            tgt_shape = tuple(np.shape(tleaf))
            dtype = ckpt._template_dtype(tleaf)
            src_rec = src_spec.leaf(tpath)
            # fold / ravel_of are mesh-independent structure markers, so
            # a bare target spec inherits them from the source — the
            # target SHAPE always comes from the template
            tgt_rec = spec.leaf(tpath) or src_rec

            if tpath in group_out:
                host = np.asarray(group_out[tpath], dtype=dtype)
            elif src_shape == tgt_shape:
                # layout-preserved: lazy per-shard assembly, or for a
                # flat source simply the stored array
                host = None
                if src._flat is None and isinstance(tleaf, jax.Array) \
                        and getattr(tleaf, "sharding", None) is not None:
                    out.append(_lazy_shard_leaf(src, i, tgt_shape, dtype,
                                                tleaf.sharding))
                    continue
                host = np.asarray(src.full(i), dtype=dtype)
            else:
                if src_rec.get("group") is not None:
                    raise _spec_error(
                        f"{path}: {tpath} belongs to source group "
                        f"{src_rec['group']!r} but the target spec maps "
                        "it to no group — flat-bucket state cannot "
                        "restore into a non-bucketed layout here (use "
                        "checkpoint.gather_zero_state's portable form)")
                logical = _leaf_logical(src.full(i), src_rec, tpath)
                host = np.asarray(
                    _leaf_placed(logical, tgt_rec, tgt_shape, tpath),
                    dtype=dtype)

            if isinstance(tleaf, jax.Array):
                out.append(jax.make_array_from_callback(
                    tgt_shape, tleaf.sharding,
                    lambda idx, h=host: h[idx]))
            else:
                out.append(host)
        return (jax.tree_util.tree_unflatten(treedef, out),
                src.manifest.get("step"))


def _lazy_shard_leaf(src: "_Source", i: int, shape, dtype, sharding):
    """Shape-preserved sharded leaf: materialize only the slices the
    target sharding asks for (the existing restore_checkpoint_sharded
    behavior, kept for the common leaves so resharding a huge model
    never assembles its unsharded tensors)."""
    import jax

    from apex_tpu import checkpoint as ckpt

    def cb(index):
        key = f"leaf_{i}|{ckpt._shard_key(index, shape)}"
        got = src._shards.get(key)
        if got is not None:
            return np.asarray(got[key], dtype=dtype)
        return np.asarray(
            ckpt._assemble_slice(src._shards, i, index, shape),
            dtype=dtype)

    return jax.make_array_from_callback(tuple(shape), sharding, cb)
