"""Unified non-finite sentinel — one overflow guard for every trainer.

The reference skips the optimizer step when any gradient is non-finite
(``apex/amp/handle.py:128-154`` patches ``optimizer.step`` to a no-op;
every multi-tensor kernel early-outs on the ``noop_flag``).  Our amp path
already had that (``amp/scaler.py`` + ``skip_update``), but the ZeRO and
3D-parallel trainers grew without it — a single NaN step would poison
Adam moments and master weights across the whole job.  This module is the
one guard all of them share:

- :class:`SentinelState` carries the ``amp`` scaler state plus a
  ``skipped_steps`` counter (surfaced through the trainers, the analog of
  counting ``optimizer.step`` skips in the reference's logs);
- :func:`sentinel_update` reuses ``amp.all_finite`` and the scaler's
  ``update`` — overflow detection and loss-scale backoff are ONE
  implementation, never re-derived per trainer;
- :func:`guarded_optimizer_step` wraps the whole optimizer apply in a
  single ``lax.cond``: on a non-finite step *nothing* runs — no
  reduce-scatter, no Adam math, no all-gather; params and state pass
  through bit-unchanged.  The predicate is a traced scalar, so the guard
  stays inside the one compiled program (no host round-trip — analyzer
  rule APX203 in :mod:`apex_tpu.analysis` checks that ``conditional``
  survives jit, for the sentinel tests and ``scripts/graph_lint.sh``
  alike).

Collective-safety: inside ``shard_map`` the local grads differ per rank,
so a rank-local finite flag could diverge and deadlock the collectives
inside the guarded branch.  ``sentinel_update(axes=...)`` therefore
``pmin``-reduces the flag over the data axes first — every rank takes the
same branch (the reference all-reduces its overflow flag for the same
reason, ``apex/amp/scaler.py:usage in DDP``).  Analyzer rule APX102
mechanizes this contract: a collective under a ``lax.cond`` whose
predicate is not agreed over its axes is a red finding.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaleState, all_finite

__all__ = [
    "SentinelState",
    "sentinel_init",
    "sentinel_update",
    "sentinel_guarded_apply",
    "guarded_optimizer_step",
]


class SentinelState(NamedTuple):
    """Jit-carried overflow-sentinel state.

    ``scaler``        — the ``amp`` :class:`LossScaleState` (scale,
                        growth/hysteresis trackers, ``found_inf``).
    ``skipped_steps`` — int32 count of updates skipped so far (the
                        counter the 3D GPT trainer surfaces).
    """

    scaler: LossScaleState
    skipped_steps: jnp.ndarray

    @property
    def scale(self):
        return self.scaler.scale


def sentinel_init(scaler_algo) -> SentinelState:
    """Fresh sentinel state for a scaler algorithm
    (``DynamicLossScale()``, ``StaticLossScale(...)``, ...)."""
    return SentinelState(scaler=scaler_algo.init(),
                         skipped_steps=jnp.int32(0))


def sentinel_update(
    scaler_algo,
    grads: Any,
    state: SentinelState,
    *,
    axes: Optional[Any] = None,
) -> Tuple[jnp.ndarray, SentinelState]:
    """One sentinel tick: check ``grads``, update scaler + skip counter.

    Returns ``(finite, new_state)`` where ``finite`` is a traced bool —
    globally agreed over ``axes`` when given (REQUIRED inside shard_map
    whenever the guarded step contains collectives; see module
    docstring).  Everything is jnp arithmetic: no host sync.
    """
    finite = all_finite(grads)
    if axes is not None:
        # pmin over the mesh: any rank's NaN vetoes the step everywhere.
        finite = lax.pmin(finite.astype(jnp.int32), axes) > 0
    new_scaler = scaler_algo.update(state.scaler, finite)
    skipped = state.skipped_steps + jnp.where(finite, 0, 1).astype(jnp.int32)
    return finite, SentinelState(scaler=new_scaler, skipped_steps=skipped)


def sentinel_guarded_apply(
    scaler_algo,
    optimizer,
    grads: Any,
    opt_state: Any,
    params: Any,
    state: SentinelState,
    *,
    axes: Optional[Any] = None,
    lr=None,
    grad_scale=None,
):
    """The whole sentinel tick + guarded apply in one call — the ONE
    copy of the check→update→cond-apply sequence every trainer threads
    (a second hand-rolled copy is exactly how per-trainer overflow
    handling diverged before this module).  Returns ``(params,
    opt_state, new_sentinel_state)``.  ``axes`` is REQUIRED inside
    ``shard_map`` when the optimizer communicates (see module
    docstring); ``grad_scale`` is the scale the loss was multiplied by
    — capture it BEFORE this call, since the update may back off."""
    finite, state = sentinel_update(scaler_algo, grads, state, axes=axes)
    params, opt_state = guarded_optimizer_step(
        optimizer, grads, opt_state, params, finite,
        lr=lr, grad_scale=grad_scale)
    return params, opt_state, state


def guarded_optimizer_step(
    optimizer,
    grads: Any,
    opt_state: Any,
    params: Any,
    finite: jnp.ndarray,
    *,
    lr=None,
    grad_scale=None,
):
    """The single ``lax.cond``-guarded apply: run ``optimizer.step`` only
    when ``finite``; otherwise params and optimizer state pass through
    bit-unchanged (and none of the step's collectives execute — a skipped
    step costs no wire bytes, like the reference's skipped
    ``optimizer.step``).

    ``finite`` must be identical on every rank of any mesh axes the
    optimizer communicates over (use ``sentinel_update(axes=...)``).
    ``grad_scale`` folds the loss-scale division into the update
    (``div_scale`` of the reference's multi-tensor kernels).
    """

    def do_step(g, s, p):
        new_p, new_s = optimizer.step(g, s, p, lr=lr, grad_scale=grad_scale)
        # step counters stay consistent with the number of APPLIED
        # updates even though this branch only runs on finite steps.
        return new_p, new_s

    def skip_step(g, s, p):
        return p, s

    return lax.cond(jnp.asarray(finite), do_step, skip_step,
                    grads, opt_state, params)
