"""Fault-tolerant training — the survival layer over the fast paths.

The reference's production value was never only speed: the GradScaler/DDP
machinery exists so long mixed-precision runs *survive* (skipped steps on
overflow, recoverable state — ``apex/amp/handle.py:128-154``,
``apex/amp/scaler.py``).  This package is that layer for the TPU stack,
covering the failures a production run on preemptible slices actually
hits:

- :mod:`.manager` — :class:`CheckpointManager`: crash-safe checkpoint
  lifecycle (atomic verified saves, keep-last-k retention,
  retry-with-backoff on transient I/O, ``restore_latest`` falling back
  past corrupt checkpoints) over both the flat and sharded layouts of
  :mod:`apex_tpu.checkpoint`, ZeRO-sharded optimizer state included.
- :mod:`.sentinel` — the unified non-finite sentinel:
  :class:`SentinelState` and the single ``lax.cond``-guarded optimizer
  apply reusing ``amp.all_finite``/``DynamicLossScale.update``, threaded
  through ``zero_data_parallel_train_step`` and the 3D GPT trainer so an
  overflow step skips the parameter/optimizer update everywhere with no
  host sync.
- :mod:`.preemption` — :class:`PreemptionGuard`: SIGTERM-driven clean
  shutdown (drain in-flight async saves, final checkpoint, exit 0) — the
  ADLR autoresume idea at the signal layer.
- :mod:`.reshard` — restore-anywhere: :class:`ShardingSpec` (the
  logical-state description embedded in every spec-carrying manifest)
  and :func:`restore_resharded`, mapping a committed checkpoint onto an
  arbitrary target mesh — ZeRO flat buckets re-chunked, pipeline layer
  stacks re-factored — so an elastic fleet losing/gaining slices resumes
  bit-losslessly (the veScale / TorchTitan-DCP logical-state idea,
  docs/resilience.md "restore-anywhere").

The matching fault-injection harness lives in
:mod:`apex_tpu.testing.faults`; the failure model and recovery matrix in
``docs/resilience.md``.
"""

from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.reshard import (
    ShardingSpec,
    build_spec,
    load_logical,
    restore_resharded,
)
from apex_tpu.resilience.sentinel import (
    SentinelState,
    guarded_optimizer_step,
    sentinel_init,
    sentinel_update,
)

__all__ = [
    "CheckpointManager",
    "PreemptionGuard",
    "SentinelState",
    "ShardingSpec",
    "build_spec",
    "guarded_optimizer_step",
    "load_logical",
    "restore_resharded",
    "sentinel_init",
    "sentinel_update",
]
