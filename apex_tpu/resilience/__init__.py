"""Fault-tolerant training — the survival layer over the fast paths.

The reference's production value was never only speed: the GradScaler/DDP
machinery exists so long mixed-precision runs *survive* (skipped steps on
overflow, recoverable state — ``apex/amp/handle.py:128-154``,
``apex/amp/scaler.py``).  This package is that layer for the TPU stack,
covering the failures a production run on preemptible slices actually
hits:

- :mod:`.manager` — :class:`CheckpointManager`: crash-safe checkpoint
  lifecycle (atomic verified saves, keep-last-k retention,
  retry-with-backoff on transient I/O, ``restore_latest`` falling back
  past corrupt checkpoints) over both the flat and sharded layouts of
  :mod:`apex_tpu.checkpoint`, ZeRO-sharded optimizer state included.
- :mod:`.sentinel` — the unified non-finite sentinel:
  :class:`SentinelState` and the single ``lax.cond``-guarded optimizer
  apply reusing ``amp.all_finite``/``DynamicLossScale.update``, threaded
  through ``zero_data_parallel_train_step`` and the 3D GPT trainer so an
  overflow step skips the parameter/optimizer update everywhere with no
  host sync.
- :mod:`.preemption` — :class:`PreemptionGuard`: SIGTERM-driven clean
  shutdown (drain in-flight async saves, final checkpoint, exit 0) — the
  ADLR autoresume idea at the signal layer.

The matching fault-injection harness lives in
:mod:`apex_tpu.testing.faults`; the failure model and recovery matrix in
``docs/resilience.md``.
"""

from apex_tpu.resilience.manager import CheckpointManager
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.resilience.sentinel import (
    SentinelState,
    guarded_optimizer_step,
    sentinel_init,
    sentinel_update,
)

__all__ = [
    "CheckpointManager",
    "PreemptionGuard",
    "SentinelState",
    "guarded_optimizer_step",
    "sentinel_init",
    "sentinel_update",
]
