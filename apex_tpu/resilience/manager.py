"""Crash-safe checkpoint lifecycle — the ``CheckpointManager``.

The primitives in :mod:`apex_tpu.checkpoint` make ONE save atomic and
verifiable (temp + fsync + rename, per-array crc32); this module owns the
*sequence* of saves a long run produces: step-indexed directories,
keep-last-k retention, retry-with-backoff on transient I/O errors, and a
``restore_latest`` that falls back to the previous intact checkpoint when
the newest fails verification — the recoverable-checkpoint contract
TorchTitan treats as a first-class production requirement (PAPERS.md) and
veScale's save/restore consistency argument applies to our sharded layout.

Layout under ``directory``::

    step_00000003.npz        # flat layout (sharded=False)
    step_00000007/           # sharded layout (sharded=True)
        shard_0.npz ... shard_{P-1}.npz
        manifest.json        # committed last; authority for restore

Both layouts carry any pytree — params, ``OptState``s (including
ZeRO-sharded flat-bucket state as global arrays), scaler/sentinel state,
counters — because the underlying functions are tree-generic.

Multi-host note: ``save`` (sync, sharded) is collective — call it from
every process, like ``save_checkpoint_sharded``.  Retries are
single-process only: a collective save has a fixed barrier sequence,
and re-entering it on one rank would deadlock its peers, so with
``process_count > 1`` every save gets one attempt and a failure is the
job runtime's to handle (like any collective failure).
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import time
from typing import Any, Optional

from apex_tpu import checkpoint as ckpt
from apex_tpu.observability import timeline
from apex_tpu.observability.spans import span

__all__ = ["CheckpointManager"]

logger = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d{8,})(\.npz)?$")  # :08d grows past 8


class CheckpointManager:
    """Manage a directory of step-indexed checkpoints.

    ``keep``      — retain at most this many newest **committed**
                    checkpoints (older ones are deleted after a
                    successful save).  Only committed steps count toward
                    the window: an uncommitted step dir (crashed or
                    in-flight save) never displaces a durable checkpoint
                    from it, so the last-committed step can never be
                    retention-deleted while a crash artifact or an
                    in-flight async save sits above it (ISSUE 6
                    retention bugfix; pinned by fault-injection tests).
                    The in-flight async step and any step a
                    ``restore_latest`` is currently reading are pinned
                    too.  Uncommitted dirs strictly older than the
                    newest committed step are dead crash artifacts and
                    are reaped (a live writer is never older than a
                    later commit); newer ones are left to their writer.
    ``sharded``   — use the per-process ``save_checkpoint_sharded``
                    layout (one subdirectory per step) instead of the
                    flat single-file layout.
    ``spec``      — optional :class:`~apex_tpu.resilience.reshard.
                    ShardingSpec`: embedded into every save's manifest
                    (the logical-state description that makes the
                    checkpoint restorable onto a different mesh) and
                    used as the default target spec for
                    ``restore_latest``.
    ``retries`` / ``backoff_s`` — transient-I/O policy for SYNC saves
                    (and the snapshot/submission part of async ones): an
                    ``OSError`` is retried up to ``retries`` times with
                    exponentially growing sleeps (``backoff_s * 2**k``).
                    A failure inside an async save's BACKGROUND write is
                    not retried — the snapshot is consumed by the worker,
                    so it is surfaced once from ``wait()``/the next save
                    and the caller re-saves from live state.
                    Non-``OSError`` failures propagate immediately.

    The manager is host-side bookkeeping only — nothing here traces or
    jits; call it between steps (or hand it ``save_async`` handles).
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 sharded: bool = False, retries: int = 3,
                 backoff_s: float = 0.25, spec=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = keep
        self.sharded = sharded
        self.retries = retries
        self.backoff_s = backoff_s
        self.spec = spec
        self._inflight = None  # (step, handle) of the pending async save
        self._pinned: set = set()  # steps a restore is currently reading
        os.makedirs(directory, exist_ok=True)

    # -- paths ---------------------------------------------------------

    def _path(self, step: int) -> str:
        name = f"step_{step:08d}"
        return os.path.join(self.directory,
                            name if self.sharded else name + ".npz")

    def step_path(self, step: int) -> str:
        """Filesystem path of one step's checkpoint artifact (the shard
        directory, or the flat ``.npz``).  Public so subtree readers —
        e.g. ``serving.loader`` restoring only the params out of a full
        train state via ``reshard.load_logical`` — can address a
        verified step without reaching into manager internals."""
        return self._path(step)

    def all_steps(self):
        """Step numbers with a checkpoint present, ascending (presence,
        not integrity — ``restore_latest`` verifies)."""
        steps = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for name in entries:
            m = _STEP_RE.match(name)
            if m is None:
                continue
            is_dir = m.group(2) is None
            if is_dir != self.sharded:
                continue
            steps.append(int(m.group(1)))
        return sorted(steps)

    # -- save ----------------------------------------------------------

    def _with_retries(self, fn, what: str):
        """Run ``fn`` retrying transient ``OSError``s with backoff — the
        blip-on-NFS/GCS-fuse case; deterministic failures (corruption
        bugs, bad trees) are not ``OSError`` and propagate at once.

        Multi-process gets ONE attempt: the sharded save is a collective
        with a fixed barrier sequence, and one rank re-entering it while
        its peers sit at a later barrier would deadlock the job — a
        failed collective save belongs to the job runtime, not a local
        retry loop."""
        import jax

        retries = self.retries if jax.process_count() == 1 else 0
        for attempt in range(retries + 1):
            try:
                return fn()
            except OSError as e:
                if attempt == retries:
                    raise
                delay = self.backoff_s * (2.0 ** attempt)
                logger.warning(
                    "%s failed (%r), retry %d/%d in %.2fs",
                    what, e, attempt + 1, retries, delay)
                time.sleep(delay)

    def save(self, tree: Any, step: int) -> str:
        """Synchronous checkpoint of ``tree`` at ``step``; returns the
        checkpoint path.  Waits for any in-flight async save first (its
        failure, if any, is raised here — never silently dropped), then
        applies retention."""
        self.wait()
        path = self._path(step)
        # Host span (wall clock + trace range, docs/observability.md):
        # checkpoint stalls are a classic silent step-time thief — the
        # span_ms/checkpoint/save histogram makes them a metric, and the
        # flight-recorder event attributes the stall to the goodput
        # ``checkpoint`` bucket (no-op when no recorder is armed).
        with span("checkpoint/save"), \
                timeline.scope("checkpoint_save", step=step):
            if self.sharded:
                self._with_retries(
                    lambda: ckpt.save_checkpoint_sharded(
                        path, tree, step=step, spec=self.spec),
                    f"sharded save step {step}")
            else:
                self._with_retries(
                    lambda: ckpt.save_checkpoint(path, tree, step=step,
                                                 spec=self.spec),
                    f"save step {step}")
        self._apply_retention()
        return path

    def save_async(self, tree: Any, step: int):
        """Overlapped checkpoint: snapshot now (buffers may be donated
        immediately after return), write in the background.  Returns the
        underlying handle; the NEXT ``save``/``save_async``/``wait``
        drains it and re-raises any write failure.  Retention runs when
        the handle is drained (deleting old checkpoints while a writer
        is mid-flight cannot race the new file: retention only ever
        removes OTHER steps)."""
        self.wait()
        path = self._path(step)
        # Only the snapshot+submission is on the training thread — the
        # span (and the timeline event feeding the goodput ``checkpoint``
        # bucket) bounds exactly the step-time cost of an async save;
        # the background write overlaps compute and is deliberately NOT
        # timeline-attributed.
        with span("checkpoint/save_async_submit"), \
                timeline.scope("checkpoint_save_async_submit", step=step):
            if self.sharded:
                handle = self._with_retries(
                    lambda: ckpt.save_checkpoint_sharded_async(
                        path, tree, step=step, spec=self.spec),
                    f"async sharded save step {step}")
            else:
                handle = self._with_retries(
                    lambda: ckpt.save_checkpoint_async(
                        path, tree, step=step, spec=self.spec),
                    f"async save step {step}")
        self._inflight = (step, handle)
        return handle

    def wait(self, timeout: Optional[float] = None) -> None:
        """Drain the in-flight async save (finalizing the sharded commit
        barrier/manifest), re-raising its failure.  No-op when idle.
        Call before shutdown — a checkpoint is durable only once its
        handle has been waited on.

        A ``timeout`` expiry is NOT a failure: the writer is still in
        flight, so the handle stays tracked — call ``wait`` again.  No
        retry wraps the handle either: a failed ``Future``'s exception
        is sticky, so re-polling it could never succeed — the error is
        raised once and the torn state is left for verification to skip
        (never deleted: the same path may hold an older durable save)."""
        if self._inflight is None:
            return
        import concurrent.futures

        step, handle = self._inflight
        try:
            if hasattr(handle, "finalize"):  # ShardedSaveHandle
                handle.finalize(timeout)
            else:  # concurrent.futures.Future
                handle.result(timeout)
        except (TimeoutError, concurrent.futures.TimeoutError):
            raise  # still writing: keep tracking, caller may wait again
        except Exception:
            # Nothing is discarded on failure: the atomic-write/commit
            # protocol guarantees the failed save left either nothing
            # visible or a state verification detects (empty step dir,
            # uncommitted shards), and restore_latest falls back past
            # it — whereas deleting self._path(step) here would destroy
            # a previously DURABLE checkpoint when a step is re-saved
            # over an existing one.
            self._inflight = None
            raise
        self._inflight = None
        self._apply_retention()

    def _discard(self, path: str) -> None:
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            elif os.path.exists(path):
                os.unlink(path)
        except OSError:
            pass

    def _is_committed(self, step: int) -> bool:
        """A step is committed when its durable artifact exists: the
        ``.npz`` file (flat — the atomic rename IS the commit) or the
        step dir's ``manifest.json`` (sharded — written last, after the
        shard barrier).  Crashed/in-flight saves fail this check."""
        path = self._path(step)
        if not self.sharded:
            return os.path.exists(path)
        return os.path.exists(os.path.join(path, "manifest.json"))

    def _apply_retention(self) -> None:
        """Drop committed checkpoints beyond the ``keep`` newest
        COMMITTED ones.  Uncommitted step dirs (a crashed or in-flight
        save) never count against ``keep`` — otherwise two crash
        artifacts above the last durable save would push it out of the
        window and retention would delete the only restorable state
        (the ISSUE 6 retention bug).  The in-flight async step and any
        step a concurrent ``restore_latest`` is reading are pinned;
        uncommitted dirs older than the newest committed step are dead
        artifacts and are reaped."""
        all_steps = self.all_steps()
        committed = [s for s in all_steps if self._is_committed(s)]
        pinned = set(self._pinned)
        if self._inflight is not None:
            pinned.add(self._inflight[0])
        for step in committed[:-self.keep]:  # keep >= 1 (__init__)
            if step in pinned:
                logger.info(
                    "retention: step %d is referenced (in-flight save or "
                    "active restore), not dropping", step)
                continue
            logger.info("retention: dropping checkpoint step %d", step)
            self._discard(self._path(step))
        # Reap DEAD crash artifacts so repeated SIGKILLs cannot grow the
        # directory without bound: an uncommitted step dir strictly OLDER
        # than the newest committed step cannot belong to a live writer
        # (saves are step-monotonic; the in-flight/pinned steps are
        # exempt anyway) — the same older-than-the-commit rule as
        # checkpoint._clean_stale_shards.  Uncommitted dirs at or above
        # the newest committed step are left alone: they may be a writer
        # still in flight.
        if committed:
            for step in all_steps:
                if (step >= committed[-1] or step in pinned
                        or self._is_committed(step)):
                    continue
                logger.info(
                    "retention: reaping dead uncommitted artifact "
                    "step %d", step)
                self._discard(self._path(step))

    # -- restore -------------------------------------------------------

    def verify(self, step: int) -> dict:
        """Integrity pass over one step's checkpoint (checksums, torn
        files).  Raises :class:`apex_tpu.checkpoint.CheckpointCorruptError`."""
        path = self._path(step)
        with span("checkpoint/verify"), \
                timeline.scope("checkpoint_verify", step=step):
            if self.sharded:
                return ckpt.verify_checkpoint_sharded(path)
            return ckpt.verify_checkpoint(path)

    def _template_matches(self, step: int, like: Any) -> bool:
        """True when the stored leaf shapes equal the template's — the
        same-mesh case, restored through the plain (lazy) path.  Any
        read problem returns True so the plain restore raises the real,
        more informative error."""
        try:
            manifest = self._manifest(step)
            import jax
            import numpy as np

            like_flat = jax.tree_util.tree_leaves(like)
            leaves = manifest.get("leaves", [])
            if len(leaves) != len(like_flat):
                return True
            return all(tuple(rec["shape"]) == tuple(np.shape(x))
                       for rec, x in zip(leaves, like_flat))
        except Exception:
            return True

    def _manifest(self, step: int) -> dict:
        """One step's shard/flat manifest without a checksum pass."""
        import json

        import numpy as np

        path = self._path(step)
        if not self.sharded:
            with np.load(path, allow_pickle=False) as data:
                return json.loads(str(data["__manifest__"]))
        shard_paths = ckpt._shard_paths(path)
        if not shard_paths:
            raise ckpt.CheckpointCorruptError(
                f"{path}: no shard files")
        with np.load(shard_paths[0], allow_pickle=False) as data:
            return json.loads(str(data["__manifest__"]))

    def restore_latest(self, like: Any, *, verify: bool = True,
                       spec=None, mesh=None):
        """Restore the newest intact checkpoint into the structure (and
        shardings) of ``like``; returns ``(tree, step)``.

        Newest-first: each candidate is verified (full checksum pass)
        before restore; a candidate that fails verification OR restore
        is logged and skipped, falling back to the previous one — the
        corrupted-newest case (bit-flipped shard, save killed between
        rename and manifest commit) recovers automatically.  Raises
        ``FileNotFoundError`` when no intact checkpoint exists.

        **Restore-anywhere**: with a target ``spec`` (a
        :class:`~apex_tpu.resilience.reshard.ShardingSpec` built over
        ``like`` for the CURRENT mesh; defaults to the manager's
        ``spec``) — or a ``mesh`` from which a bare spec is built; the
        mesh-independent structure markers (flat-bucket group layouts,
        ``fold``/``ravel_of``) are then inherited from the SOURCE
        checkpoint's spec, so ZeRO state reshards under a bare spec
        too — a candidate whose stored shapes disagree with the
        template is
        restored through :func:`apex_tpu.resilience.reshard.
        restore_resharded`: logical leaves are reassembled from the
        committed shards and re-laid-out for the target dp/tp/pp
        counts, ZeRO flat buckets re-chunked.  Verification and
        corrupt-fallback behave identically on both paths.  A candidate
        written without a sharding spec (pre-reshard manifest) still
        restores when its shapes match the template; a shape-mismatched
        spec-less candidate fails (and is fallen back past) with an
        error naming the missing spec.

        The verify pass deliberately reads every array a second time
        (restore reads them again): complete integrity is established
        BEFORE any restore side effects, including for slices a sharded
        restore would lazily skip.  ``verify=False`` trades that for
        one-pass speed when the storage is trusted.

        Observability: the whole attempt runs under a host
        ``span_ms/checkpoint/restore_latest`` histogram, and the number
        of candidates skipped as corrupt before success is counted into
        the ``ckpt/fallback_depth`` metric — both land in the rank-aware
        default :class:`~apex_tpu.observability.metrics.MetricRegistry`
        (flushed by rank 0 only, docs/observability.md).
        """
        if spec is None:
            spec = self.spec
        if spec is None and mesh is not None:
            from apex_tpu.resilience import reshard

            spec = reshard.build_spec(like, mesh=mesh)
        failures = []
        with span("checkpoint/restore_latest"):
            for step in reversed(self.all_steps()):
                path = self._path(step)
                self._pinned.add(step)
                try:
                    if verify:
                        self.verify(step)
                    resharded = (spec is not None
                                 and not self._template_matches(step, like))
                    # timeline: verify and restore are emitted as their
                    # own disjoint intervals (NOT the restore_latest
                    # wrapper, which contains both — goodput buckets
                    # must never double-count).
                    with span("checkpoint/restore"), \
                            timeline.scope("checkpoint_restore", step=step,
                                           resharded=resharded):
                        if resharded:
                            from apex_tpu.resilience import reshard

                            tree, at = reshard.restore_resharded(
                                path, like, spec)
                        elif self.sharded:
                            tree, at = ckpt.restore_checkpoint_sharded(
                                path, like)
                        else:
                            tree, at = ckpt.restore_checkpoint(path, like)
                    if failures:
                        logger.warning(
                            "restore_latest fell back to step %d past %s",
                            step, "; ".join(failures))
                    from apex_tpu.observability.metrics import (
                        default_registry,
                    )

                    default_registry().counter(
                        "ckpt/fallback_depth").inc(len(failures))
                    return tree, at
                except (ckpt.CheckpointCorruptError, ValueError, OSError,
                        KeyError) as e:
                    failures.append(f"step {step}: {e!r}")
                    logger.warning(
                        "checkpoint step %d unusable (%r); falling back",
                        step, e)
                finally:
                    self._pinned.discard(step)
        raise FileNotFoundError(
            f"no intact checkpoint under {self.directory!r}"
            + (f" (tried: {'; '.join(failures)})" if failures else ""))
