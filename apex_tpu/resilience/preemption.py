"""SIGTERM-driven clean shutdown — preemptible-slice survival.

Preemptible TPU hosts get a SIGTERM grace window before the SIGKILL.  The
reference's answer at the scheduler layer is the ADLR autoresume polling
protocol (``apex/transformer/testing/global_vars.py`` →
``apex_tpu.transformer.testing.global_vars.AutoResume``); this module is
the signal-layer complement: catch the signal, finish the step, drain any
in-flight async checkpoint writes, take a final checkpoint, exit cleanly.

Usage (the crash/resume smoke trainer drives exactly this)::

    guard = PreemptionGuard()            # installs the SIGTERM handler
    mgr = CheckpointManager(ckpt_dir)
    for step in range(start, num_steps):
        state = train_step(state, batch(step))
        mgr.save_async(state, step)
        if guard.triggered:               # grace window: wind down
            mgr.wait()                    # drain: this step is durable
            break
    guard.uninstall()

The handler only sets a flag (async-signal-safe); all real work happens
on the main thread at the step boundary, so no jit dispatch, collective,
or file write is ever interrupted mid-flight by the handler itself.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Flag-setting signal handler for graceful preemption.

    ``signals`` defaults to SIGTERM (what preemption sends); add SIGINT
    to make Ctrl-C drain instead of tearing down mid-save.  Install from
    the **main thread** (a CPython signal-API requirement).  Use as a
    context manager or call :meth:`uninstall` to restore the previous
    handlers.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._event = threading.Event()
        self._previous = {}
        for sig in signals:
            self._previous[sig] = signal.signal(sig, self._handle)

    def _handle(self, signum, frame):
        self._event.set()

    @property
    def triggered(self) -> bool:
        """True once a shutdown signal has arrived (sticky)."""
        return self._event.is_set()

    def trigger(self) -> None:
        """Programmatic preemption (fault injection / tests)."""
        self._event.set()

    def uninstall(self) -> None:
        """Restore the previous signal handlers (idempotent)."""
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}

    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()
