"""SIGTERM-driven clean shutdown — preemptible-slice survival.

Preemptible TPU hosts get a SIGTERM grace window before the SIGKILL.  The
reference's answer at the scheduler layer is the ADLR autoresume polling
protocol (``apex/transformer/testing/global_vars.py`` →
``apex_tpu.transformer.testing.global_vars.AutoResume``); this module is
the signal-layer complement: catch the signal, finish the step, drain any
in-flight async checkpoint writes, take a final checkpoint, exit cleanly.

Usage (the crash/resume smoke trainer drives exactly this)::

    guard = PreemptionGuard()            # installs the SIGTERM handler
    mgr = CheckpointManager(ckpt_dir)
    for step in range(start, num_steps):
        state = train_step(state, batch(step))
        mgr.save_async(state, step)
        if guard.triggered:               # grace window: wind down
            mgr.wait()                    # drain: this step is durable
            break
    guard.uninstall()

The handler only sets a flag (async-signal-safe); all real work happens
on the main thread at the step boundary, so no jit dispatch, collective,
or file write is ever interrupted mid-flight by the handler itself.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Iterable

__all__ = ["PreemptionGuard"]

logger = logging.getLogger(__name__)


class PreemptionGuard:
    """Flag-setting signal handler for graceful preemption.

    ``signals`` defaults to SIGTERM (what preemption sends); add SIGINT
    to make Ctrl-C drain instead of tearing down mid-save.  CPython only
    allows handler installation from the **main thread**; constructed
    anywhere else (a fleet router's health-check thread, a replica
    child's worker thread) the guard degrades gracefully to the
    programmatic :meth:`trigger` path instead of raising —
    ``signals_installed`` says which mode this instance got.  Use as a
    context manager or call :meth:`uninstall` to restore the previous
    handlers.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._event = threading.Event()
        self._previous = {}
        self._signals_installed = True
        for sig in signals:
            try:
                self._previous[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # signal.signal raises ValueError BOTH off the main
                # thread and for an uncatchable/invalid signal number —
                # only the former gets the graceful fallback; a bad
                # signal on the main thread is a caller bug and must
                # keep raising, not produce a guard that silently never
                # fires.
                if threading.current_thread() is threading.main_thread():
                    raise
                self._signals_installed = False
                logger.warning(
                    "PreemptionGuard built off the main thread: signal "
                    "handlers not installed; only trigger() will trip "
                    "this guard")
                break

    @property
    def signals_installed(self) -> bool:
        """True when the OS signal handlers are live; False for a guard
        built off the main thread (programmatic :meth:`trigger` only)."""
        return self._signals_installed

    def _handle(self, signum, frame):
        self._event.set()

    @property
    def triggered(self) -> bool:
        """True once a shutdown signal has arrived (sticky)."""
        return self._event.is_set()

    def trigger(self) -> None:
        """Programmatic preemption (fault injection / tests)."""
        self._event.set()

    def uninstall(self) -> None:
        """Restore the previous signal handlers (idempotent)."""
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}

    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()
