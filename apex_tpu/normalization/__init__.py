"""apex_tpu.normalization — fused LayerNorm / RMSNorm.

TPU-native replacement for ``apex/normalization``
(``apex/normalization/fused_layer_norm.py``, kernels
``csrc/layer_norm_cuda_kernel.cu``).  On TPU a row-norm is a small fusion XLA
handles well; the value preserved from the reference is *semantics*:

- affine / non-affine, LayerNorm and RMSNorm;
- mixed-dtype mode (bf16 input, fp32 weights — the "MixedFused" Megatron
  variants, ``fused_layer_norm.py:430``);
- ``memory_efficient`` backward that recomputes the normalized input from
  the *output* instead of saving the input
  (``csrc/layer_norm_cuda_kernel.cu:576-717``), exposed as a custom_vjp so
  it composes with ``jax.checkpoint``.
"""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    manual_rms_norm,
)
