"""Fused LayerNorm / RMSNorm with memory-efficient custom backward.

Behavioral spec: ``apex/normalization/fused_layer_norm.py`` —
``FusedLayerNormAffineFunction:32``, ``FusedRMSNormAffineFunction:64``,
modules ``:230,329``, Megatron mixed-dtype variants ``:430`` — over
``csrc/layer_norm_cuda_kernel.cu`` (Welford forward ``cuApplyLayerNorm``
``:412-470``; memory-efficient backward recomputing x̂ from the output
``:576-717``).

Semantics preserved:

- statistics are always computed in fp32 (the kernel's accumulation type),
  output cast back to the input dtype;
- ``memory_efficient=True`` saves (output, weight, bias, invvar) and
  recomputes ``x̂ = (y - β)/γ`` in the backward instead of saving the input
  — trading a few flops for activation memory exactly like the reference;
- weight/bias gradients are reduced in fp32.

The forward is expressed so XLA fuses it into neighbouring ops; a Pallas
kernel (``apex_tpu.ops.pallas_norm``) exists for the odd-width cases where
XLA's row reduction is not optimal.
"""

from __future__ import annotations

import numbers
from functools import partial
from typing import Iterable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

try:  # flax is the module-layer convention in this framework
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

__all__ = [
    "fused_layer_norm",
    "fused_layer_norm_affine",
    "fused_rms_norm",
    "fused_rms_norm_affine",
    "manual_rms_norm",
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
]


def _clamp_by_magnitude(w, eps):
    """Keep |w| >= eps preserving sign — the reference's ``clamp_by_magnitude``
    (``csrc/layer_norm_cuda_kernel.cu:443,496``) guarding the
    memory-efficient recompute ``x̂ = (y-β)/γ`` against zero-init gamma."""
    mag = jnp.maximum(jnp.abs(w), eps)
    return jnp.where(w >= 0, mag, -mag)


def _norm_axes(x, normalized_shape):
    if isinstance(normalized_shape, numbers.Integral):
        normalized_shape = (int(normalized_shape),)
    normalized_shape = tuple(int(s) for s in normalized_shape)
    if tuple(x.shape[-len(normalized_shape):]) != normalized_shape:
        raise ValueError(
            f"normalized_shape {normalized_shape} does not match trailing "
            f"input dims {x.shape}"
        )
    return tuple(range(x.ndim - len(normalized_shape), x.ndim))


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------


def _ln_fwd_math(x, weight, bias, axes, eps):
    x32 = jnp.asarray(x, jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mean) * invvar
    y = xhat
    if weight is not None:
        y = y * jnp.asarray(weight, jnp.float32)
    if bias is not None:
        y = y + jnp.asarray(bias, jnp.float32)
    return jnp.asarray(y, x.dtype), xhat, invvar


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _layer_norm(x, weight, bias, normalized_shape, eps, memory_efficient):
    axes = _norm_axes(x, normalized_shape)
    y, _, _ = _ln_fwd_math(x, weight, bias, axes, eps)
    return y


def _ln_fwd(x, weight, bias, normalized_shape, eps, memory_efficient):
    axes = _norm_axes(x, normalized_shape)
    y, xhat, invvar = _ln_fwd_math(x, weight, bias, axes, eps)
    if memory_efficient:
        # save output, recompute xhat in bwd (layer_norm_cuda_kernel.cu:576)
        res = (y, weight, bias, invvar)
    else:
        res = (xhat, weight, bias, invvar)
    return y, res


def _ln_bwd(normalized_shape, eps, memory_efficient, res, dy):
    saved, weight, bias, invvar = res
    dy32 = jnp.asarray(dy, jnp.float32)
    n_axes = (
        1
        if isinstance(normalized_shape, numbers.Integral)
        else len(tuple(normalized_shape))
    )
    axes = tuple(range(dy.ndim - n_axes, dy.ndim))
    batch_axes = tuple(range(dy.ndim - n_axes))

    if memory_efficient:
        y32 = jnp.asarray(saved, jnp.float32)
        if bias is not None:
            y32 = y32 - jnp.asarray(bias, jnp.float32)
        if weight is not None:
            xhat = y32 / _clamp_by_magnitude(jnp.asarray(weight, jnp.float32), eps)
        else:
            xhat = y32
    else:
        xhat = saved

    if weight is not None:
        dxhat = dy32 * jnp.asarray(weight, jnp.float32)
    else:
        dxhat = dy32

    # dx = invvar * (dxhat - mean(dxhat) - xhat * mean(dxhat*xhat))
    m1 = jnp.mean(dxhat, axis=axes, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=axes, keepdims=True)
    dx = invvar * (dxhat - m1 - xhat * m2)

    dw = db = None
    if weight is not None:
        dw = jnp.asarray(
            jnp.sum(dy32 * xhat, axis=batch_axes), jnp.asarray(weight).dtype
        )
    if bias is not None:
        db = jnp.asarray(jnp.sum(dy32, axis=batch_axes), jnp.asarray(bias).dtype)
    return (jnp.asarray(dx, jnp.float32).astype(dy.dtype), dw, db)


_layer_norm.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm_affine(
    x, weight, bias, normalized_shape, eps: float = 1e-5,
    memory_efficient: bool = False,
):
    """``fused_layer_norm_affine`` (``apex/normalization/fused_layer_norm.py:194``)."""
    return _layer_norm(x, weight, bias, normalized_shape, eps, memory_efficient)


def fused_layer_norm(
    x, normalized_shape, eps: float = 1e-5, memory_efficient: bool = False
):
    """Non-affine variant (``fused_layer_norm.py:214``)."""
    return _layer_norm(x, None, None, normalized_shape, eps, memory_efficient)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------


def _rms_fwd_math(x, weight, axes, eps):
    x32 = jnp.asarray(x, jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=axes, keepdims=True)
    invvar = jax.lax.rsqrt(ms + eps)
    xhat = x32 * invvar
    y = xhat
    if weight is not None:
        y = y * jnp.asarray(weight, jnp.float32)
    return jnp.asarray(y, x.dtype), xhat, invvar


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms_norm(x, weight, normalized_shape, eps, memory_efficient):
    axes = _norm_axes(x, normalized_shape)
    y, _, _ = _rms_fwd_math(x, weight, axes, eps)
    return y


def _rms_fwd(x, weight, normalized_shape, eps, memory_efficient):
    axes = _norm_axes(x, normalized_shape)
    y, xhat, invvar = _rms_fwd_math(x, weight, axes, eps)
    if memory_efficient:
        res = (y, weight, invvar)
    else:
        res = (xhat, weight, invvar)
    return y, res


def _rms_bwd(normalized_shape, eps, memory_efficient, res, dy):
    saved, weight, invvar = res
    dy32 = jnp.asarray(dy, jnp.float32)
    n_axes = (
        1
        if isinstance(normalized_shape, numbers.Integral)
        else len(tuple(normalized_shape))
    )
    axes = tuple(range(dy.ndim - n_axes, dy.ndim))
    batch_axes = tuple(range(dy.ndim - n_axes))

    if memory_efficient:
        y32 = jnp.asarray(saved, jnp.float32)
        if weight is not None:
            xhat = y32 / _clamp_by_magnitude(jnp.asarray(weight, jnp.float32), eps)
        else:
            xhat = y32
    else:
        xhat = saved

    if weight is not None:
        dxhat = dy32 * jnp.asarray(weight, jnp.float32)
    else:
        dxhat = dy32

    # dx = invvar * (dxhat - xhat * mean(dxhat * xhat))
    m = jnp.mean(dxhat * xhat, axis=axes, keepdims=True)
    dx = invvar * (dxhat - xhat * m)

    dw = None
    if weight is not None:
        dw = jnp.asarray(
            jnp.sum(dy32 * xhat, axis=batch_axes), jnp.asarray(weight).dtype
        )
    return (jnp.asarray(dx, jnp.float32).astype(dy.dtype), dw)


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm_affine(
    x, weight, normalized_shape, eps: float = 1e-5, memory_efficient: bool = False
):
    """``fused_rms_norm_affine`` (``fused_layer_norm.py:189``)."""
    return _rms_norm(x, weight, normalized_shape, eps, memory_efficient)


def fused_rms_norm(
    x, normalized_shape, eps: float = 1e-5, memory_efficient: bool = False
):
    """Non-affine RMSNorm (``fused_layer_norm.py:219``)."""
    return _rms_norm(x, None, normalized_shape, eps, memory_efficient)


def manual_rms_norm(x, normalized_shape, weight, eps):
    """Pure-jnp fallback, parity with ``fused_layer_norm.py:18-30`` (the
    python path used when the extension is unavailable)."""
    axes = _norm_axes(x, normalized_shape)
    norm = jnp.mean(jnp.square(jnp.asarray(x, jnp.float32)), axes, keepdims=True)
    out = jnp.asarray(x, jnp.float32) * jax.lax.rsqrt(norm + eps)
    out = jnp.asarray(out, x.dtype)
    if weight is not None:
        out = out * weight
    return out


# ---------------------------------------------------------------------------
# Module layer (flax)
# ---------------------------------------------------------------------------

if nn is not None:

    class FusedLayerNorm(nn.Module):
        """Module analog of ``apex.normalization.FusedLayerNorm``
        (``fused_layer_norm.py:230``)."""

        normalized_shape: Union[int, Tuple[int, ...]]
        eps: float = 1e-5
        elementwise_affine: bool = True
        memory_efficient: bool = False
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            shape = (
                (self.normalized_shape,)
                if isinstance(self.normalized_shape, numbers.Integral)
                else tuple(self.normalized_shape)
            )
            if self.elementwise_affine:
                weight = self.param(
                    "scale", nn.initializers.ones, shape, self.param_dtype
                )
                bias = self.param(
                    "bias", nn.initializers.zeros, shape, self.param_dtype
                )
                return fused_layer_norm_affine(
                    x, weight, bias, shape, self.eps, self.memory_efficient
                )
            return fused_layer_norm(x, shape, self.eps, self.memory_efficient)

    class FusedRMSNorm(nn.Module):
        """Module analog of ``apex.normalization.FusedRMSNorm``
        (``fused_layer_norm.py:329``)."""

        normalized_shape: Union[int, Tuple[int, ...]]
        eps: float = 1e-5
        elementwise_affine: bool = True
        memory_efficient: bool = False
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            shape = (
                (self.normalized_shape,)
                if isinstance(self.normalized_shape, numbers.Integral)
                else tuple(self.normalized_shape)
            )
            if self.elementwise_affine:
                weight = self.param(
                    "scale", nn.initializers.ones, shape, self.param_dtype
                )
                return fused_rms_norm_affine(
                    x, weight, shape, self.eps, self.memory_efficient
                )
            return fused_rms_norm(x, shape, self.eps, self.memory_efficient)

    class MixedFusedLayerNorm(FusedLayerNorm):
        """Mixed-dtype LayerNorm: fp32 params on half inputs without input
        upcast-at-module-boundary (``MixedFusedLayerNorm``,
        ``fused_layer_norm.py:430``).  The functional core already computes
        statistics in fp32 and returns the input dtype, so this is the same
        module with fp32 params pinned."""

        param_dtype: jnp.dtype = jnp.float32

    class MixedFusedRMSNorm(FusedRMSNorm):
        """Mixed-dtype RMSNorm (``fused_layer_norm.py:465``)."""

        param_dtype: jnp.dtype = jnp.float32

else:  # pragma: no cover
    FusedLayerNorm = FusedRMSNorm = None
    MixedFusedLayerNorm = MixedFusedRMSNorm = None
