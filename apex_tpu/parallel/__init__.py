"""apex_tpu.parallel — mesh construction, collectives and data parallelism.

TPU-native replacement for ``apex/parallel`` (reference
``apex/parallel/__init__.py``): instead of NCCL process groups and a
DistributedDataParallel wrapper with hand-rolled flat-bucket all-reduce
(``apex/parallel/distributed.py:131``), parallelism is declared as shardings on
a named :class:`jax.sharding.Mesh` and gradient reduction is a ``psum`` the XLA
SPMD partitioner schedules and overlaps automatically.
"""

from apex_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    initialize_model_parallel,
    model_parallel_is_initialized,
    destroy_model_parallel,
    get_mesh,
    get_data_parallel_world_size,
    get_tensor_model_parallel_world_size,
    get_pipeline_model_parallel_world_size,
    get_context_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_rank,
    set_virtual_pipeline_model_parallel_rank,
    get_pipeline_model_parallel_split_rank,
    DATA_AXIS,
    TENSOR_AXIS,
    PIPELINE_AXIS,
    CONTEXT_AXIS,
)
from apex_tpu.parallel import collectives  # noqa: F401
from apex_tpu.parallel import launch  # noqa: F401
from apex_tpu.parallel.launch import initialize_distributed  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    all_reduce_gradients,
    data_parallel_train_step,
    grad_accumulation,
    zero_data_parallel_train_step,
    zero_init,
    dp_shard_batch,
    host_dp_ranks,
    replicate,
)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    sync_batch_norm_stats,
)
from apex_tpu.optimizers.larc import LARC  # noqa: F401
