"""Multi-process / multi-host bring-up.

Behavioral spec: ``apex/parallel/multiproc.py:1-35`` (spawn ``world_size``
local ranks with ``--rank i``) and the hybrid process-group construction of
``apex/transformer/parallel_state.py:83-153``.  The JAX analog is one call
per process to :func:`jax.distributed.initialize`; afterwards
``jax.devices()`` spans every process and the mesh builder
(:func:`apex_tpu.parallel.mesh.initialize_model_parallel`) lays the ``dcn``
axis across the process boundary, so no group bookkeeping survives.

Two entry points:

- :func:`initialize_distributed` — call at the top of each rank's script
  (env-var defaults match the common launchers: ``COORDINATOR_ADDRESS`` /
  ``JAX_COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID``, plus
  SLURM/TPU-pod autodetection inherited from ``jax.distributed``).
- :func:`run_multiprocess` — the ``multiproc`` launcher analog for tests
  and single-host experiments: spawn N copies of a script on local CPU
  devices, each with the right coordinator/rank env, and wait.

CPU ranks use the Gloo cross-process collective backend (JAX's default for
CPU), which is how the 2-process integration test
(``tests/test_multiprocess.py``) runs collectives without hardware —
SURVEY.md §4's "multi-node without a cluster" translation.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Optional, Sequence

__all__ = ["initialize_distributed", "run_multiprocess", "free_port"]

_INITIALIZED = False


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join (or trivially skip, single-process) the distributed job.

    Must run before any other JAX backend use in the process — like the
    reference's requirement that ``init_process_group`` precede CUDA work.
    Arguments default from the environment (``COORDINATOR_ADDRESS``,
    ``NUM_PROCESSES``, ``PROCESS_ID``); on managed platforms (TPU pods,
    SLURM) ``jax.distributed.initialize()`` autodetects everything and this
    wrapper passes straight through.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    import jax

    if num_processes is not None and num_processes <= 1:
        _INITIALIZED = True
        return
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # Pin at the *config* level too (a sitecustomize may force another
        # plugin over the env var), and enable the Gloo cross-process
        # collective backend — without it multi-process CPU collectives
        # deadlock.
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _INITIALIZED = True


def run_multiprocess(
    script: str,
    num_processes: int = 2,
    devices_per_process: int = 4,
    timeout: float = 600.0,
    extra_env: Optional[dict] = None,
    script_args: Optional[Sequence[str]] = None,
):
    """Spawn ``num_processes`` CPU ranks of ``script`` on this host and wait
    (the ``python -m apex.parallel.multiproc`` analog; per-rank output is
    returned rather than written to ``GPU_i.log``).

    Each rank gets ``JAX_PLATFORMS=cpu``, ``devices_per_process`` forced
    host devices, and coordinator/rank env consumed by
    :func:`initialize_distributed`; ``script_args`` are appended to every
    rank's argv.  Returns the list of ``CompletedProcess`` results; raises
    if any rank fails.
    """
    port = free_port()
    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        env.update(extra_env or {})
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={devices_per_process}"
        ).strip()
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = str(num_processes)
        env["PROCESS_ID"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, script, *(script_args or ())],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    results = []
    failed = []
    for rank, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            failed.append((rank, "timeout", err))
            continue
        results.append(subprocess.CompletedProcess(
            proc.args, proc.returncode, out, err))
        if proc.returncode != 0:
            failed.append((rank, proc.returncode, err))
    if failed:
        msgs = "\n".join(
            f"rank {r}: {rc}\n{e.decode(errors='replace')[-2000:]}"
            for r, rc, e in failed)
        raise RuntimeError(f"multiprocess launch failed:\n{msgs}")
    return results
