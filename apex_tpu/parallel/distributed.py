"""Data-parallel training — the ``apex.parallel.DistributedDataParallel`` analog.

Behavioral spec: ``apex/parallel/distributed.py:131`` — apex DDP hooks every
parameter's grad-accumulation, discovers flat buckets on the first iteration
(``:287``), and kicks off NCCL all-reduces on a side stream as buckets fill
during backward (``comm_ready_buckets:517``, ``allreduce_bucket:429``),
optionally pre-dividing by world size (``gradient_predivide_factor``).

Under XLA SPMD the *entire mechanism dissolves*: declare the batch sharded on
the ``dp`` mesh axis and parameters replicated, and the partitioner emits one
fused gradient all-reduce schedule, overlapped with the backward
automatically.  What remains worth shipping:

- :func:`data_parallel_train_step` — the recommended pjit path: a factory
  that shards the batch, replicates params, and returns a jitted step whose
  gradient reduction is implicit;
- :class:`DistributedDataParallel` — an explicit shard_map-style wrapper with
  the reference's knobs (``gradient_average``,
  ``gradient_predivide_factor``, ``allreduce_always_fp32`` — cf. apex DDP
  ctor ``distributed.py:131-198``) for users porting code that calls
  all-reduce by hand;
- :func:`all_reduce_gradients` — the bare collective, for custom loops.

The ``delay_allreduce`` / bucket-structure machinery has no analog: XLA
already schedules reductions optimally, so those knobs are intentionally
absent (SURVEY.md §7: rebuild capabilities, not mechanisms).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel import collectives as cc
from apex_tpu.parallel import mesh as mesh_lib

__all__ = [
    "all_reduce_gradients",
    "DistributedDataParallel",
    "data_parallel_train_step",
    "grad_accumulation",
    "zero_data_parallel_train_step",
    "zero_init",
    "dp_shard_batch",
    "host_dp_ranks",
    "replicate",
]


def all_reduce_gradients(
    grads,
    axis: str = mesh_lib.DATA_AXIS,
    *,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
):
    """All-reduce a gradient pytree over a mesh axis (inside shard_map).

    Mirrors ``allreduce_bucket`` (``apex/parallel/distributed.py:429-477``):
    optional fp32 upcast for the reduction, predivide before / postdivide
    after (``:434-450``), mean vs sum.
    """
    world = cc.axis_size(axis)

    def leaf(g):
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = jnp.asarray(g, jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = cc.all_reduce(g, axis, op="sum")
        if gradient_average:
            g = g / (world / gradient_predivide_factor)
        # gradient_average=False leaves the result at sum/predivide_factor,
        # exactly like allreduce_bucket (distributed.py:455-456 never
        # multiplies the predivide back)
        if allreduce_always_fp32:
            g = jnp.asarray(g, orig_dtype)
        return g

    return jax.tree_util.tree_map(leaf, grads)


def host_dp_ranks(mesh=None):
    """The GLOBAL data-parallel shard indices (flat over ``(dcn, dp)``,
    dcn-major — the order :func:`dp_shard_batch` lays rows in) whose
    devices THIS process hosts, sorted ascending.

    The per-host input-sharding contract: a multi-process job gives each
    loader ``dp_ranks=host_dp_ranks(mesh)`` so every host decodes only
    its own shards (no redundant global decode), then places them with
    ``dp_shard_batch(batch, mesh, local_ranks=host_dp_ranks(mesh))``.
    Single-process: all ranks — the loaders' default degenerates to the
    global batch.
    """
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    proc = jax.process_index()
    names = mesh.axis_names
    dp_size = mesh.shape.get(mesh_lib.DATA_AXIS, 1)
    ranks = set()
    devs = np.asarray(mesh.devices)
    for coord in np.ndindex(devs.shape):
        if devs[coord].process_index != proc:
            continue
        flat = 0
        for name, c in zip(names, coord):
            if name == mesh_lib.DCN_AXIS:
                flat += c * dp_size
            elif name == mesh_lib.DATA_AXIS:
                flat += c
        ranks.add(flat)
    return sorted(ranks)


def dp_shard_batch(batch, mesh=None, *, local_ranks=None):
    """Place a host batch sharded along the data-parallel axes (leading
    dim over ``(dcn, dp)`` — the outer/cross-slice axis is size 1 on a
    single slice, so this is correct at any scale).

    ``local_ranks`` (multi-host input sharding): the batch holds only the
    rows of THIS process's dp shards — ``len(local_ranks)`` equal
    windows, window ``i`` belonging to global dp rank ``local_ranks[i]``
    (use :func:`host_dp_ranks`).  The leaves are assembled into GLOBAL
    arrays via ``jax.make_array_from_single_device_arrays``: each
    addressable device receives exactly its shard's rows, no host ever
    materializes (or decodes) the global batch.  Every process must call
    this collectively with its own rows.  ``local_ranks=None`` (default,
    single-host) places the full global batch as before.
    """
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    dp_axes = tuple(a for a in (mesh_lib.DCN_AXIS, mesh_lib.DATA_AXIS)
                    if a in mesh.shape)

    if local_ranks is None:
        def leaf(x):
            if jnp.ndim(x) == 0:  # scalars (e.g. a mixup lambda) replicate
                spec = P()
            else:
                spec = P(dp_axes, *([None] * (jnp.ndim(x) - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree_util.tree_map(leaf, batch)

    local_ranks = list(local_ranks)
    dp_world = 1
    for a in dp_axes:
        dp_world *= mesh.shape[a]
    rank_pos = {r: i for i, r in enumerate(local_ranks)}

    def local_leaf(x):
        x = np.asarray(x)
        if x.ndim == 0:
            spec = P()
            global_shape = ()
        else:
            if x.shape[0] % len(local_ranks):
                raise ValueError(
                    f"local batch dim {x.shape[0]} not divisible by "
                    f"len(local_ranks)={len(local_ranks)}")
            per = x.shape[0] // len(local_ranks)
            spec = P(dp_axes, *([None] * (x.ndim - 1)))
            global_shape = (per * dp_world,) + x.shape[1:]
        sharding = NamedSharding(mesh, spec)
        idx_map = sharding.addressable_devices_indices_map(global_shape)
        arrays = []
        for dev, idx in idx_map.items():
            if x.ndim == 0:
                piece = x
            else:
                start = idx[0].start or 0
                rank = start // per
                if rank not in rank_pos:
                    raise ValueError(
                        f"device {dev} holds global dp shard {rank}, "
                        f"which local_ranks={local_ranks} does not cover "
                        "— pass host_dp_ranks(mesh) and give the loader "
                        "the same dp_ranks")
                pos = rank_pos[rank]
                piece = x[pos * per:(pos + 1) * per]
            arrays.append(jax.device_put(piece, dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, arrays)

    return jax.tree_util.tree_map(local_leaf, batch)


def replicate(tree, mesh=None):
    """Replicate params/optimizer state across the whole mesh."""
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


@dataclasses.dataclass
class DistributedDataParallel:
    """Explicit DDP wrapper (shard_map style) with the apex constructor knobs.

    ``grad_fn(params, batch) -> (loss, grads)`` computed per-shard; the
    wrapper all-reduces grads (and averages the loss) over ``dp``::

        ddp = DistributedDataParallel(grad_fn)
        step = ddp.build(mesh)        # jitted global-array function
        loss, grads = step(params, sharded_batch)

    cf. apex ctor options ``apex/parallel/distributed.py:131-198``.
    """

    grad_fn: Callable  # (params, batch) -> (loss, grads)
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    allreduce_always_fp32: bool = False
    # Default covers the outer (cross-slice DCN) data axis too, matching
    # dp_shard_batch — on a single slice dcn has size 1 and is a no-op.
    axis: Any = (mesh_lib.DCN_AXIS, mesh_lib.DATA_AXIS)

    def build(self, mesh=None):
        if mesh is None:
            mesh = mesh_lib.get_mesh()
        ndim_axis = tuple(a for a in (
            self.axis if isinstance(self.axis, (tuple, list))
            else (self.axis,)) if a in mesh.shape)

        def per_shard(params, batch):
            loss, grads = self.grad_fn(params, batch)
            grads = all_reduce_gradients(
                grads,
                ndim_axis,
                gradient_average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                allreduce_always_fp32=self.allreduce_always_fp32,
            )
            loss = cc.all_reduce(loss, ndim_axis, op="mean")
            return loss, grads

        def batch_spec(x):
            return P(ndim_axis, *([None] * (x.ndim - 1)))

        def wrapped(params, batch):
            in_specs = (
                jax.tree_util.tree_map(lambda _: P(), params),
                jax.tree_util.tree_map(batch_spec, batch),
            )
            out_specs = (P(), jax.tree_util.tree_map(lambda _: P(), params))
            return cc.shard_over(
                per_shard, mesh=mesh, in_specs=in_specs, out_specs=out_specs
            )(params, batch)

        return jax.jit(wrapped)


def grad_accumulation(grad_fn: Callable, microbatches: int) -> Callable:
    """Wrap ``grad_fn(params, batch) -> (loss, grads)`` to accumulate over
    ``microbatches`` sequential microbatches — the
    ``delay_allreduce``/``no_sync()`` capability of apex DDP
    (``apex/parallel/distributed.py:198`` ``delay_allreduce``; Megatron's
    interval accumulation) as a pure function transform.

    The batch's leading dim is split into ``microbatches`` equal slices
    and scanned; losses and grads are accumulated in fp32 and divided by
    ``microbatches`` once at the end, so the wrapper is a drop-in for
    ``grad_fn`` on the whole batch (gradient of the mean loss), with peak
    activation memory of ONE microbatch.

    Crucially the accumulation is *local arithmetic only* — no collective
    per microbatch.  Feeding the result to a ZeRO optimizer
    (``DistributedFusedAdam.step``, which reduce-scatters internally)
    folds the entire gradient reduction into the last microbatch — one
    reduce-scatter per N microbatches, the overlap structure of the
    reference's ``_pipeline_block_reductions``.
    """
    if microbatches == 1:
        return grad_fn

    def accum(params, batch):
        def split(x):
            n = jnp.shape(x)[0]
            if n % microbatches:
                raise ValueError(
                    f"batch dim {n} not divisible by microbatches="
                    f"{microbatches}")
            return x.reshape((microbatches, n // microbatches)
                             + tuple(jnp.shape(x)[1:]))

        micro = jax.tree_util.tree_map(split, batch)
        mb0 = jax.tree_util.tree_map(lambda x: x[0], micro)
        g_shape = jax.eval_shape(lambda p, b: grad_fn(p, b)[1], params, mb0)
        init = (
            jnp.float32(0),
            jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, jnp.float32), g_shape),
        )

        def body(carry, mb):
            loss, grads = grad_fn(params, mb)
            loss_acc, g_acc = carry
            g_acc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.asarray(g, jnp.float32), g_acc, grads)
            return (loss_acc + jnp.asarray(loss, jnp.float32), g_acc), None

        (loss_sum, g_sum), _ = jax.lax.scan(body, init, micro)
        inv = 1.0 / microbatches
        return loss_sum * inv, jax.tree_util.tree_map(
            lambda g: g * inv, g_sum)

    return accum


def data_parallel_train_step(
    loss_fn: Callable,
    optimizer,
    *,
    mesh=None,
    donate: bool = True,
    microbatches: int = 1,
):
    """The pjit path: build a jitted DP train step with implicit reduction.

    ``loss_fn(params, batch) -> scalar loss`` written over the *global*
    batch; batch enters sharded on ``dp`` (use :func:`dp_shard_batch`),
    params replicated.  Because the loss is a mean over the global batch,
    XLA inserts the gradient psum itself — this is the whole DDP feature set
    expressed as shardings.  Returns ``step(params, opt_state, batch) ->
    (params, opt_state, loss)``.

    ``microbatches > 1`` scans :func:`grad_accumulation` over the batch's
    leading dim — one-microbatch activation memory; reduction scheduling
    stays with the partitioner here (for the guaranteed
    single-reduce-scatter form, use :func:`zero_data_parallel_train_step`).
    """
    if mesh is None:
        mesh = mesh_lib.get_mesh()

    grad_fn = grad_accumulation(
        lambda p, b: jax.value_and_grad(loss_fn)(p, b), microbatches)

    def step(params, opt_state, batch, lr=None):
        loss, grads = grad_fn(params, batch)
        params, opt_state = optimizer.step(grads, opt_state, params, lr=lr)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def zero_init(optimizer, params, mesh=None):
    """Build the sharded (ZeRO) optimizer state as *global* arrays: runs
    ``optimizer.init`` inside a ``shard_map`` so each device holds only
    its 1/dp shard, laid out by ``optimizer.state_partition_specs``."""
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    specs = optimizer.state_partition_specs(params)
    init = cc.shard_over(
        optimizer.init, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),),
        out_specs=specs,
    )
    return jax.jit(init)(params)


def zero_data_parallel_train_step(
    loss_fn: Callable,
    optimizer,
    *,
    mesh=None,
    donate: bool = True,
    microbatches: int = 1,
    scaler=None,
    collect_stats: bool = False,
):
    """The shard_map ZeRO path: per-replica local grads feed a
    ZeRO-sharded optimizer (``DistributedFusedAdam``/``LAMB``) whose
    ``step`` reduce-scatters, steps the local shard, and all-gathers —
    with ``microbatches > 1`` the local grads accumulate with **no
    per-microbatch collective** and the single reduce-scatter folds into
    the last microbatch (the reference's overlapped
    ``_pipeline_block_reductions`` schedule, as program structure).

    ``loss_fn(params, batch) -> scalar loss`` over one replica's batch
    slice; batch enters sharded on the data axes (:func:`dp_shard_batch`),
    params replicated, optimizer state sharded (:func:`zero_init`).
    Returns ``step(params, opt_state, batch, lr=None) ->
    (params, opt_state, loss)`` on global arrays.

    ``scaler`` (an ``amp`` scaler algorithm, e.g. ``DynamicLossScale()``)
    arms the unified non-finite sentinel
    (:mod:`apex_tpu.resilience.sentinel`): the loss is scaled, gradients
    are overflow-checked with the flag ``pmin``-agreed over the data
    axes, and the ENTIRE optimizer apply — reduce-scatter, update,
    all-gather — runs under one ``lax.cond``, so an overflow step leaves
    params and optimizer state bit-unchanged and moves no collective
    bytes, with no host sync.  The step signature gains sentinel state
    LAST (the same position as the 3D GPT trainer's sentinel step):
    ``step(params, opt_state, batch, sentinel, lr=None) -> (params,
    opt_state, sentinel, loss)`` (init with
    :func:`apex_tpu.resilience.sentinel_init`; ``sentinel.skipped_steps``
    counts skipped updates; the reported loss is unscaled).

    ``collect_stats`` appends a jit-carried
    :class:`apex_tpu.observability.TrainStats` as the step's LAST output
    (after the loss).  The cross-rank fields (loss, grad sum-of-squares,
    non-finite leaf count) ride the step's EXISTING loss all-reduce as a
    widened ``(3,)`` payload — the instrumented step performs exactly the
    collectives the bare step did (``tests/test_observability.py`` pins
    the HLO opcode counts equal) and its params/optimizer state are
    bit-identical; ``grad_norm`` is the L2 norm over the stacked
    per-replica local grads (what actually rode the wire — see
    docs/observability.md).  Fetch stats on a host schedule with
    :class:`apex_tpu.observability.TrainStatsLogger` so steady-state
    steps stay fully async.
    """
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    dp_axes = tuple(a for a in (mesh_lib.DCN_AXIS, mesh_lib.DATA_AXIS)
                    if a in mesh.shape)

    def batch_spec(x):
        return P(dp_axes, *([None] * (jnp.ndim(x) - 1)))

    def jit_shard_step(per_shard, tail_specs=()):
        """ONE copy of the spec/shard_over/jit/donate plumbing for both
        shapes: ``rest`` is ``(batch,)`` or ``(batch, sentinel)`` — the
        batch comes first, any carry-state after it is replicated and
        mirrored into the outputs (before the loss).  ``tail_specs``:
        extra replicated outputs AFTER the loss (the TrainStats tree)."""
        def step(params, opt_state, *rest, lr=None):
            batch, carry = rest[0], rest[1:]
            param_specs = jax.tree_util.tree_map(lambda _: P(), params)
            state_specs = optimizer.state_partition_specs(params)
            carry_specs = [jax.tree_util.tree_map(lambda _: P(), r)
                           for r in carry]
            in_specs = (param_specs, state_specs,
                        jax.tree_util.tree_map(batch_spec, batch),
                        *carry_specs, P())
            out_specs = (param_specs, state_specs, *carry_specs, P(),
                         *tail_specs)
            lr_in = jnp.float32(optimizer.lr if lr is None else lr)
            return cc.shard_over(
                per_shard, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs
            )(params, opt_state, batch, *carry, lr_in)

        return jax.jit(step, donate_argnums=(0, 1) if donate else ())

    if collect_stats:
        from apex_tpu.observability import trainstats as ts

        stats_tail = (ts.stats_partition_specs(),)
        world = 1
        for a in dp_axes:
            world *= mesh.shape[a]

    if scaler is None:
        grad_fn = grad_accumulation(
            lambda p, b: jax.value_and_grad(loss_fn)(p, b), microbatches)

        def per_shard(params, opt_state, batch, lr):
            loss, grads = grad_fn(params, batch)
            new_p, new_s = optimizer.step(grads, opt_state, params, lr=lr)
            if not collect_stats:
                loss = cc.all_reduce(loss, dp_axes, op="mean")
                return new_p, new_s, loss
            # The ONE collective of the bare loss path, widened: a sum
            # over [loss, grad_sumsq, nonfinite_leaves] replaces the
            # scalar pmean (pmean IS psum + the same static division, so
            # the reported loss — and everything the optimizer consumed
            # upstream of it — is bit-identical to the bare step).
            red = cc.all_reduce(ts.pack_local_stats(loss, grads),
                                dp_axes, op="sum")
            loss, stats = ts.stats_from_reduced(red, world, params)
            return new_p, new_s, loss, stats

        return jit_shard_step(per_shard,
                              stats_tail if collect_stats else ())

    from apex_tpu.resilience.sentinel import sentinel_guarded_apply

    def per_shard_guarded(params, opt_state, batch, sent, lr):
        # Scale with the CURRENT step's scale (captured before the
        # sentinel update — the update may back off for the next step).
        scale_used = sent.scaler.scale

        def scaled_loss(p, b):
            return scaler.scale(loss_fn(p, b), sent.scaler)

        grad_fn = grad_accumulation(
            lambda p, b: jax.value_and_grad(scaled_loss)(p, b),
            microbatches)
        loss_s, grads = grad_fn(params, batch)
        new_p, new_s, new_sent = sentinel_guarded_apply(
            scaler, optimizer, grads, opt_state, params, sent,
            axes=dp_axes, lr=lr, grad_scale=scale_used)
        if not collect_stats:
            loss = cc.all_reduce(loss_s / scale_used, dp_axes, op="mean")
            return new_p, new_s, new_sent, loss
        # Same widened-reduction trick; the loss element enters already
        # unscaled so the psum+divide reproduces the bare pmean bitwise.
        # grad_norm is reported unscaled via grad_scale.
        red = cc.all_reduce(ts.pack_local_stats(loss_s / scale_used, grads),
                            dp_axes, op="sum")
        loss, stats = ts.stats_from_reduced(
            red, world, params, grad_scale=scale_used,
            loss_scale=scale_used, skipped_steps=new_sent.skipped_steps)
        return new_p, new_s, new_sent, loss, stats

    return jit_shard_step(per_shard_guarded,
                          stats_tail if collect_stats else ())
