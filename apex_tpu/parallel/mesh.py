"""Device-mesh construction — the SPMD analog of Megatron ``parallel_state``.

The reference (``apex/transformer/parallel_state.py:155``,
``initialize_model_parallel``) builds NCCL process groups for every
data/tensor/pipeline/model/embedding combination and stores them in module
globals with rank accessors (``:421-760``).  Under JAX SPMD there are no
process groups: a single :class:`jax.sharding.Mesh` with named axes carries
the whole decomposition, XLA inserts collectives from sharding annotations,
and "which group am I in" becomes ``jax.lax.axis_index(axis_name)`` inside
``shard_map``.

This module keeps the reference's *API shape* (initialize / accessors /
destroy) so users migrating from Apex find the same entry points, but the
state it manages is just a mesh + the virtual-pipeline bookkeeping the
interleaved schedule needs (reference ``parallel_state.py:521-545``).

Axis layout (innermost = fastest-varying device index = best ICI locality):

    (dcn, dp, pp, cp, tp)

``tp`` is innermost so tensor-parallel collectives (the most
bandwidth-hungry, fired inside every linear layer) ride adjacent-chip ICI
links; ``dp`` is outermost within a slice so data-parallel gradient
reduction uses whole-slice ICI; ``dcn`` is the *outer* data-parallel axis
spanning slices/hosts over the data-center network — the analog of the
reference's hybrid IB-vs-socket NCCL group split
(``parallel_state.py:83-153``, ``NUM_GPUS_PER_IB_BLOCK``).  ``dcn`` is
always present (size 1 single-slice), so code that reduces gradients over
``("dcn", "dp")`` is correct at any scale.  This mirrors the reference's
rank grid documentation (``parallel_state.py:186-200``) with the GPU
"ranks 8..15 = second DP replica" layout replaced by mesh-axis ordering.

Multi-process bring-up lives in :mod:`apex_tpu.parallel.launch`
(``jax.distributed.initialize`` — the ``apex.parallel.multiproc`` analog);
once initialized, ``jax.devices()`` spans all processes and this builder
lays the dcn axis across process boundaries.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "DCN_AXIS",
    "DATA_AXIS",
    "TENSOR_AXIS",
    "PIPELINE_AXIS",
    "CONTEXT_AXIS",
    "MeshSpec",
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "destroy_model_parallel",
    "get_mesh",
    "get_data_parallel_world_size",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_context_parallel_world_size",
    "get_virtual_pipeline_model_parallel_world_size",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "get_pipeline_model_parallel_split_rank",
]

# Canonical axis names.  Everything in apex_tpu refers to mesh axes by these.
DCN_AXIS = "dcn"
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"

_AXIS_ORDER = (DCN_AXIS, DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Static description of a parallel decomposition.

    Analog of the (tp, pp, vpp, split_rank) argument bundle of
    ``initialize_model_parallel`` (``apex/transformer/parallel_state.py:155``).
    """

    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    context_parallel_size: int = 1
    data_parallel_size: Optional[int] = None  # None = fill remaining devices
    dcn_data_parallel_size: int = 1           # outer (cross-slice) dp axis
    virtual_pipeline_model_parallel_size: Optional[int] = None
    pipeline_model_parallel_split_rank: Optional[int] = None

    def resolve_dp(self, n_devices: int) -> int:
        model = (
            self.tensor_model_parallel_size
            * self.pipeline_model_parallel_size
            * self.context_parallel_size
            * self.dcn_data_parallel_size
        )
        if n_devices % model != 0:
            raise ValueError(
                f"world size {n_devices} not divisible by "
                f"dcn*tp*pp*cp={model} "
                f"(dcn={self.dcn_data_parallel_size}, "
                f"tp={self.tensor_model_parallel_size}, "
                f"pp={self.pipeline_model_parallel_size}, "
                f"cp={self.context_parallel_size})"
            )
        dp = n_devices // model
        if self.data_parallel_size is not None and self.data_parallel_size != dp:
            raise ValueError(
                f"data_parallel_size={self.data_parallel_size} inconsistent with "
                f"{n_devices} devices / model-parallel size {model} (= {dp})"
            )
        return dp


class _State:
    mesh: Optional[Mesh] = None
    spec: Optional[MeshSpec] = None
    virtual_pipeline_rank: Optional[int] = None


_STATE = _State()


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    context_parallel_size: int = 1,
    dcn_data_parallel_size: Optional[int] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and register the global device mesh.

    Mirrors ``apex/transformer/parallel_state.py:155`` but returns a
    :class:`jax.sharding.Mesh` instead of creating NCCL groups.  The mesh can
    also be used directly (``with get_mesh():``) — registration exists so the
    Megatron-style accessors work without threading the mesh everywhere.

    ``devices`` defaults to ``jax.devices()``; pass an explicit list to build
    a sub-mesh (e.g. for tests) or to control device order.

    ``dcn_data_parallel_size``: outer data-parallel axis laid across
    process/slice boundaries (defaults to ``jax.process_count()`` when the
    job is multi-process and the axes divide, else 1).  ``jax.devices()``
    orders devices process-major, so a plain reshape puts the dcn axis
    exactly on the process boundary — cross-slice traffic is confined to
    the outermost axis (gradient all-reduce), everything else rides ICI.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if dcn_data_parallel_size is None:
        nproc = jax.process_count()
        model = (tensor_model_parallel_size * pipeline_model_parallel_size
                 * context_parallel_size)
        per_proc = len(devices) // max(nproc, 1)
        # Auto-lay dcn on the process boundary ONLY for the full
        # process-major jax.devices() list — for an explicit sub-list the
        # reshape could put a "slice" across two processes, silently
        # defeating the DCN-locality guarantee the axis exists for.
        is_full_list = devices == list(jax.devices())
        dcn_data_parallel_size = (
            nproc if nproc > 1 and is_full_list
            and per_proc * nproc == len(devices)
            and per_proc % model == 0 else 1
        )
    spec = MeshSpec(
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size,
        dcn_data_parallel_size=dcn_data_parallel_size,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank,
    )
    dp = spec.resolve_dp(len(devices))
    shape = (
        dcn_data_parallel_size,
        dp,
        pipeline_model_parallel_size,
        context_parallel_size,
        tensor_model_parallel_size,
    )
    if virtual_pipeline_model_parallel_size is not None:
        if pipeline_model_parallel_size < 2:
            raise ValueError(
                "virtual pipeline parallelism requires pipeline_model_parallel_size >= 2"
            )
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, axis_names=_AXIS_ORDER)
    _STATE.mesh = mesh
    _STATE.spec = spec
    _STATE.virtual_pipeline_rank = None
    return mesh


def model_parallel_is_initialized() -> bool:
    """Analog of ``parallel_state.model_parallel_is_initialized`` (``:423``)."""
    return _STATE.mesh is not None


def destroy_model_parallel() -> None:
    """Analog of ``parallel_state.destroy_model_parallel`` (``:761``)."""
    _STATE.mesh = None
    _STATE.spec = None
    _STATE.virtual_pipeline_rank = None


def get_mesh() -> Mesh:
    if _STATE.mesh is None:
        raise RuntimeError(
            "model parallel mesh is not initialized; call "
            "apex_tpu.parallel.initialize_model_parallel(...) first"
        )
    return _STATE.mesh


def _axis_size(axis: str) -> int:
    return get_mesh().shape[axis]


def get_data_parallel_world_size() -> int:
    """Analog of ``parallel_state.get_data_parallel_world_size`` (``:730``) —
    the *total* replica count, inner (ICI) × outer (DCN) axes."""
    return _axis_size(DATA_AXIS) * _axis_size(DCN_AXIS)


def get_dcn_data_parallel_world_size() -> int:
    return _axis_size(DCN_AXIS)


def get_tensor_model_parallel_world_size() -> int:
    """Analog of ``parallel_state.get_tensor_model_parallel_world_size`` (``:476``)."""
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    """Analog of ``parallel_state.get_pipeline_model_parallel_world_size`` (``:484``)."""
    return _axis_size(PIPELINE_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    """Analog of ``parallel_state.get_virtual_pipeline_model_parallel_world_size``
    (``:541``)."""
    if _STATE.spec is None:
        return None
    return _STATE.spec.virtual_pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    """Current model-chunk index during an interleaved-schedule step.

    Reference: ``parallel_state.get_virtual_pipeline_model_parallel_rank``
    (``:521``).  In SPMD this is *not* a device property — every device runs
    every chunk of its stage — so it is plain host-side schedule bookkeeping.
    """
    return _STATE.virtual_pipeline_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    """Reference: ``parallel_state.set_virtual_pipeline_model_parallel_rank``
    (``:531``)."""
    _STATE.virtual_pipeline_rank = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    """Encoder/decoder split stage for T5-style models.

    Reference: ``parallel_state.get_pipeline_model_parallel_split_rank``
    (``:512``).
    """
    if _STATE.spec is None:
        return None
    return _STATE.spec.pipeline_model_parallel_split_rank


def get_rank_info() -> str:
    """Human-readable mesh summary, analog of ``parallel_state.get_rank_info``
    (``:421-431``)."""
    if not model_parallel_is_initialized():
        return "mesh uninitialized"
    m = get_mesh()
    return (
        f"mesh(dcn={m.shape[DCN_AXIS]}, dp={m.shape[DATA_AXIS]}, "
        f"pp={m.shape[PIPELINE_AXIS]}, cp={m.shape[CONTEXT_AXIS]}, "
        f"tp={m.shape[TENSOR_AXIS]}) "
        f"process {jax.process_index()}/{jax.process_count()}"
    )
