"""Collective communication layer — the NCCL/UCC analog.

The reference routes every collective through ``torch.distributed`` with NCCL
(process-group plumbing in ``apex/transformer/parallel_state.py:83-153``, raw
p2p in ``apex/contrib/csrc/nccl_p2p/``).  On TPU the transport is the ICI mesh
(DCN across slices) and the API is ``jax.lax`` collectives bound to named mesh
axes; XLA schedules and overlaps them.  This module is the single place that
names those primitives so higher layers (tensor_parallel.mappings, pipeline
p2p, SyncBN, DDP) never spell ``jax.lax.psum`` themselves.

All functions here must run inside a ``shard_map``/``pmap`` context where
``axis_name`` is bound.  ``shard_over`` is the helper that enters that context
from the outside using the registered global mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.parallel import mesh as mesh_lib

__all__ = [
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "hierarchical_reduce_scatter",
    "hierarchical_all_gather",
    "ppermute",
    "ring_chunks",
    "all_to_all",
    "broadcast",
    "axis_index",
    "axis_size",
    "bound_axis_size",
    "send_recv_next",
    "send_recv_prev",
    "shard_over",
    "named_sharding",
]

AxisName = Union[str, Sequence[str]]


def axis_index(axis: AxisName):
    """Rank along a mesh axis (inside shard_map). Replaces
    ``torch.distributed.get_rank(group)``."""
    return lax.axis_index(axis)


def _axis_size(axis: AxisName) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    # jax < 0.4.38 has no lax.axis_size; psum of a unit constant folds to
    # the static size (the documented psum(1, axis) idiom)
    if isinstance(axis, (tuple, list)):
        size = 1
        for a in axis:
            size *= int(lax.psum(1, a))
        return size
    return int(lax.psum(1, axis))


def bound_axis_size(axis: Optional[AxisName]) -> int:
    """Size of ``axis`` if it is bound by an enclosing ``shard_map``/``pmap``,
    else 1.  Lets axis-parameterized modules degrade to their single-rank
    form when traced outside any mapped context (``axis=None`` or unbound)."""
    if axis is None:
        return 1
    try:
        return _axis_size(axis)
    except NameError:
        return 1


def axis_size(axis: AxisName) -> int:
    """World size along a mesh axis (inside shard_map)."""
    return _axis_size(axis)


def all_reduce(x, axis: AxisName, op: str = "sum"):
    """All-reduce over a mesh axis.

    Replaces ``torch.distributed.all_reduce`` on the TP/DP groups (e.g.
    ``apex/transformer/tensor_parallel/mappings.py:31`` ``_reduce``).
    """
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported all_reduce op: {op!r}")


def all_gather(x, axis: AxisName, *, concat_axis: int = 0, tiled: bool = True):
    """All-gather shards along ``concat_axis``.

    Replaces ``torch.distributed.all_gather`` / ``_all_gather_base`` (e.g.
    sequence-parallel gather ``apex/transformer/tensor_parallel/mappings.py:103``).
    ``tiled=True`` concatenates (the Megatron convention); ``tiled=False``
    stacks a new leading axis (the raw all_gather convention).
    """
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0):
    """Reduce-scatter: sum over the axis group, keep this rank's shard.

    Replaces ``torch.distributed.reduce_scatter_tensor`` (sequence-parallel
    reduce-scatter ``apex/transformer/tensor_parallel/mappings.py:122``).
    """
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def hierarchical_reduce_scatter(
    x,
    inner_axis: AxisName,
    outer_axis: Optional[str] = None,
    *,
    scatter_axis: int = 0,
    outer_reduce_dtype=None,
):
    """Two-tier reduce-scatter for the ICI/DCN fabric.

    Instead of treating ``(dcn, dp)`` as one flat reduction group (which
    interleaves 1/(dcn*dp)-sized exchanges over the slow cross-slice
    network), reduce-scatter over the intra-slice ``inner_axis`` (ICI)
    first, then all-reduce the 1/dp-sized shard across ``outer_axis``
    (DCN) — the hierarchical schedule of "Automatic Cross-Replica Sharding
    of Weight Update in Data-Parallel Training" (Xu et al.; the analog of
    the reference's IB-block vs socket NCCL group split,
    ``apex/transformer/parallel_state.py:83-153``).  The result is the
    fully-summed shard, *replicated* over ``outer_axis``.

    ``outer_reduce_dtype`` optionally casts the shard for the DCN hop
    (e.g. ``jnp.bfloat16`` halves cross-slice bytes) and casts back.
    The outer hop is skipped when ``outer_axis`` is ``None``, unbound, or
    size 1, so call sites are correct at any scale.
    """
    shard = lax.psum_scatter(
        x, inner_axis, scatter_dimension=scatter_axis, tiled=True
    )
    if outer_axis is not None and bound_axis_size(outer_axis) > 1:
        if outer_reduce_dtype is not None:
            orig = shard.dtype
            shard = lax.psum(
                jnp.asarray(shard, outer_reduce_dtype), outer_axis
            )
            shard = jnp.asarray(shard, orig)
        else:
            shard = lax.psum(shard, outer_axis)
    return shard


def hierarchical_all_gather(x, inner_axis: AxisName, *, concat_axis: int = 0,
                            tiled: bool = True):
    """Gather back shards produced by :func:`hierarchical_reduce_scatter`.

    Because the outer (DCN) tier all-*reduces* — every slice ends up with
    identical shards — the gather only ever runs over the intra-slice
    ``inner_axis``: zero DCN bytes on the parameter path.  Provided as a
    named pair so call sites state the intent (and stay correct if the
    outer tier ever becomes a scatter)."""
    return lax.all_gather(x, inner_axis, axis=concat_axis, tiled=tiled)


def ppermute(x, axis: AxisName, perm):
    """Point-to-point permutation — the p2p send/recv analog
    (``apex/transformer/pipeline_parallel/p2p_communication.py:48-166``).

    ``perm`` must be a valid partial permutation (each rank at most once
    as source and once as target) — jax does NOT validate this at trace
    time, and a mismatched ring deadlocks real ICI; analyzer rules
    APX104/APX202 (:mod:`apex_tpu.analysis`) check it statically."""
    return lax.ppermute(x, axis, perm)


def ring_chunks(x, axis: Union[AxisName, int], dim: int = 0):
    """View ``x`` with dimension ``dim`` split into the axis's per-rank
    chunks, chunk index leading: ``[..., n*c, ...] -> [n, ..., c, ...]``.

    Chunk ``i`` is rank ``i``'s shard of ``dim`` (the tiled all-gather /
    reduce-scatter layout), which is exactly the order ring-decomposed
    collectives walk one ``ppermute`` hop at a time — the collective-matmul
    rings (:mod:`apex_tpu.transformer.tensor_parallel.overlap`) index these
    chunks with ``lax.dynamic_index_in_dim`` at a traced rank offset.
    ``axis`` may be a bound mesh axis name or an explicit chunk count.
    """
    n = axis if isinstance(axis, int) else _axis_size(axis)
    dim = dim % x.ndim
    if x.shape[dim] % n:
        raise ValueError(
            f"dimension {dim} of size {x.shape[dim]} not divisible into "
            f"{n} ring chunks"
        )
    c = x.shape[dim] // n
    split = x.reshape(x.shape[:dim] + (n, c) + x.shape[dim + 1:])
    return jnp.moveaxis(split, dim, 0)


def send_recv_next(x, axis: AxisName):
    """Send to rank+1, receive from rank-1 along ``axis`` (ring, wrapping).

    The pipeline forward-direction transfer: stage i's activations arrive at
    stage i+1 (``p2p_communication.send_forward`` ``:445``).  The wrap-around
    edge (last→first) carries data the consumer must mask/ignore, matching the
    reference where first stage never reads a recv'd activation.
    """
    n = _axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(x, axis: AxisName):
    """Send to rank-1, receive from rank+1 (pipeline backward direction,
    ``p2p_communication.send_backward`` ``:469``)."""
    n = _axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    """All-to-all — used by DeepSpeed-Ulysses-style sequence parallelism and
    expert parallelism (absent in the reference; first-class here)."""
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def broadcast(x, axis: AxisName, root: int = 0):
    """Broadcast ``root``'s shard to every rank on the axis.

    Replaces ``torch.distributed.broadcast`` (e.g. tensor-parallel input-data
    broadcast ``apex/transformer/tensor_parallel/data.py:80``).  Implemented as
    a masked psum: ranks != root contribute zeros.
    """
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def shard_over(
    fn: Callable,
    *,
    mesh: Optional[Mesh] = None,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = False,
):
    """Wrap ``fn`` in a ``shard_map`` over the registered global mesh.

    The bridge from the outer (global-array) world into the per-shard world
    where the collectives above are legal.  Pipeline schedules and the
    distributed tests use this; most library code instead relies on sharding
    annotations and lets XLA infer collectives.

    Old-jax contract: if the wrapped function will be differentiated
    (``jax.grad`` *across* this boundary), no rank-0 inexact value may
    cross it — 0.4.x shard_map cannot name-check scalar residuals in the
    transposed program (``_SpecError``).  Keep such scalars ``(1,)``-shaped
    inside and squeeze outside; analyzer rule APX101
    (:mod:`apex_tpu.analysis`, ``lint_traced(fn, ...,
    differentiated=True)``) enforces this mechanically.
    """
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    # jax < 0.5: shard_map lives in jax.experimental and the replication
    # check is spelled check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def named_sharding(*spec, mesh: Optional[Mesh] = None) -> NamedSharding:
    """Shorthand for ``NamedSharding(get_mesh(), PartitionSpec(*spec))``."""
    if mesh is None:
        mesh = mesh_lib.get_mesh()
    return NamedSharding(mesh, P(*spec))
