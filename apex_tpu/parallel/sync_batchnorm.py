"""SyncBatchNorm — cross-replica batch normalization via mesh collectives.

Behavioral spec: ``apex/parallel/optimized_sync_batchnorm.py:9-85`` +
``optimized_sync_batchnorm_kernel.py:10-119`` over ``csrc/welford.cu``:

- local Welford mean/biased-var (+count), all-gather, ``welford_parallel``
  merge (``welford.cu:569``) → global mean, **biased** inv_std for
  normalization, **unbiased** var for running stats
  (``var = var_biased * count/(count-1)``, ``kernel.py:45-48``);
- running stats: ``running = running*(1-momentum) + momentum*current``
  (``kernel.py:53-57``) — note apex's ``momentum`` weights the *new* value;
- optional fused residual-add + ReLU epilogue (``fuse_relu`` + ``z`` input,
  ``batchnorm_forward_c_last`` ``welford.cu:652``) — the ``groupbn``
  BN-Add-ReLU capability;
- process sub-groups (``group_size``, ``apex/parallel/__init__.py:60-97``)
  map to ``axis_index_groups`` of the collective;
- backward all-reduces ``sum_dy``/``sum_dy_xmu`` (``kernel.py:95-113``) —
  here that falls out of autodiff through the psum'd statistics.

The merge math: with equal-count shards (always true for an evenly-sharded
global array), psum of (Σx, Σx², n) reproduces the count-weighted Welford
combine exactly.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

__all__ = ["SyncBatchNorm", "sync_batch_norm_stats"]


def sync_batch_norm_stats(
    x,
    reduce_axes: Tuple[int, ...],
    axis_name: Optional[Union[str, Sequence[str]]] = None,
    axis_index_groups=None,
):
    """Global (mean, biased_var, count) over batch+spatial dims and, when
    ``axis_name`` is bound, across replicas — the ``welford_mean_var`` +
    all-gather + ``welford_parallel`` pipeline as one fused reduction."""
    x32 = jnp.asarray(x, jnp.float32)
    local_count = 1
    for a in reduce_axes:
        local_count *= x.shape[a]
    s = jnp.sum(x32, axis=reduce_axes)
    sq = jnp.sum(jnp.square(x32), axis=reduce_axes)
    count = jnp.float32(local_count)
    if axis_name is not None:
        s = lax.psum(s, axis_name, axis_index_groups=axis_index_groups)
        sq = lax.psum(sq, axis_name, axis_index_groups=axis_index_groups)
        count = lax.psum(count, axis_name, axis_index_groups=axis_index_groups)
    mean = s / count
    var_biased = sq / count - jnp.square(mean)
    return mean, var_biased, count


if nn is not None:

    class SyncBatchNorm(nn.Module):
        """Flax module with the apex ``SyncBatchNorm`` surface
        (``apex/parallel/optimized_sync_batchnorm.py:9``).

        ``axis_name``: mesh axis (or tuple) to synchronize over — the process
        group — **for shard_map-style training loops**, where the module sees
        a per-replica shard.  Under pjit with a dp-sharded global batch leave
        it ``None``: the statistics are computed over the *global* array and
        the partitioner inserts the cross-replica reduction itself, i.e.
        pjit-BN is always SyncBN (the apex BN-vs-SyncBN distinction only
        exists in the per-shard world).  NHWC layout (the reference's
        optimized ``syncbn.welford_mean_var_c_last`` path).

        Call with ``use_running_average=False`` and
        ``mutable=["batch_stats"]`` during training.
        ``z``: optional residual added before the (optional) fused ReLU.
        """

        num_features: int
        eps: float = 1e-5
        momentum: float = 0.1
        affine: bool = True
        track_running_stats: bool = True
        axis_name: Optional[Union[str, Tuple[str, ...]]] = None
        axis_index_groups: Any = None
        fuse_relu: bool = False
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x, z=None, use_running_average: bool = False):
            C = self.num_features
            assert x.shape[-1] == C, (
                f"SyncBatchNorm is channel-last (NHWC); got trailing dim "
                f"{x.shape[-1]} != num_features {C}"
            )
            reduce_axes = tuple(range(x.ndim - 1))

            running_mean = self.variable(
                "batch_stats", "running_mean",
                lambda: jnp.zeros((C,), jnp.float32),
            )
            running_var = self.variable(
                "batch_stats", "running_var",
                lambda: jnp.ones((C,), jnp.float32),
            )

            if use_running_average and self.track_running_stats:
                mean = running_mean.value
                var_biased = running_var.value
            else:
                # track_running_stats=False always normalizes with batch
                # statistics (torch _BatchNorm semantics); during module init
                # the mesh axis is not bound (init runs outside
                # shard_map/pjit) so compute local stats only, like
                # flax.linen.BatchNorm
                axis = None if self.is_initializing() else self.axis_name
                mean, var_biased, count = sync_batch_norm_stats(
                    x, reduce_axes, axis, self.axis_index_groups
                )
                if self.track_running_stats and not self.is_initializing():
                    # unbiased var for running stats (kernel.py:45-48),
                    # biased inv_std for normalization
                    var_unbiased = (
                        var_biased * count / jnp.maximum(count - 1.0, 1.0)
                    )
                    running_mean.value = (
                        running_mean.value * (1.0 - self.momentum)
                        + self.momentum * mean
                    )
                    running_var.value = (
                        running_var.value * (1.0 - self.momentum)
                        + self.momentum * var_unbiased
                    )

            inv_std = lax.rsqrt(var_biased + self.eps)
            y = (jnp.asarray(x, jnp.float32) - mean) * inv_std
            if self.affine:
                weight = self.param(
                    "scale", nn.initializers.ones, (C,), self.param_dtype
                )
                bias = self.param(
                    "bias", nn.initializers.zeros, (C,), self.param_dtype
                )
                y = y * weight + bias
            if z is not None:
                y = y + jnp.asarray(z, jnp.float32)
            if self.fuse_relu:
                y = jax.nn.relu(y)
            return jnp.asarray(y, x.dtype)

else:  # pragma: no cover
    SyncBatchNorm = None
