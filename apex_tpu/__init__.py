"""apex_tpu — a TPU-native training-acceleration framework.

A from-scratch JAX/XLA/Pallas/pjit framework with the capability set of NVIDIA
Apex (reference: /root/reference, see SURVEY.md): mixed-precision policies with
dynamic loss scaling, fused multi-tensor optimizers, fused normalization /
softmax / dense / cross-entropy ops, data-parallel training over the ICI/DCN
mesh, and a Megatron-style tensor/sequence/pipeline-parallel runtime.

Where Apex monkey-patches torch (``apex/amp/amp.py:74-183``), apex_tpu provides
explicit functional APIs; where Apex hand-buckets NCCL all-reduce
(``apex/parallel/distributed.py:429``), apex_tpu declares shardings on a
``jax.sharding.Mesh``; where Apex writes CUDA (``csrc/``), apex_tpu relies on
XLA fusion and writes Pallas kernels only where profiling says XLA is not
enough.

Top-level layout (mirrors the reference export list ``apex/__init__.py:9``):

- :mod:`apex_tpu.amp`            — precision policies + loss scaling (O0-O3 analog)
- :mod:`apex_tpu.optimizers`     — fused optimizer family (Adam, LAMB, SGD, ...)
- :mod:`apex_tpu.normalization`  — fused LayerNorm / RMSNorm
- :mod:`apex_tpu.ops`            — fused functional ops (softmax, dense, xentropy, ...)
- :mod:`apex_tpu.parallel`       — mesh builder, collectives, DDP analog, SyncBN
- :mod:`apex_tpu.resilience`     — crash-safe checkpoint lifecycle, non-finite
  sentinel, preemption handling (the GradScaler/recoverable-state survival layer)
- :mod:`apex_tpu.analysis`       — static jaxpr/HLO graph linter mechanizing the
  mesh-correctness rules (no Apex analog; veScale-style consistency checking)
- :mod:`apex_tpu.transformer`    — tensor/sequence/pipeline-parallel runtime
- :mod:`apex_tpu.models`         — reference models (MLP, ResNet, GPT, BERT)
- :mod:`apex_tpu.contrib`        — optional extensions (group_norm, sparsity, ...)
- :mod:`apex_tpu.utils`          — logging, timers, tree utilities
"""

import logging as _logging

__version__ = "0.4.0"  # keep in sync with pyproject.toml

__all__ = [
    "amp",
    "optimizers",
    "normalization",
    "ops",
    "parallel",
    "resilience",
    "transformer",
    "models",
    "contrib",
    "utils",
]


class RankInfoFormatter(_logging.Formatter):
    """Per-process log formatter carrying mesh-rank info.

    Analog of the reference's ``RankInfoFormatter`` (``apex/__init__.py:31-43``)
    which prepends NCCL rank info; under SPMD JAX there is one process per host,
    so we carry ``jax.process_index`` instead of a device rank.
    """

    _cached = None

    def format(self, record):
        if RankInfoFormatter._cached is None:
            rank, world = 0, 1
            try:
                # Only read rank info if a backend already exists — calling
                # jax.process_index() would *initialize* the backend as a side
                # effect of logging, breaking later jax.distributed.initialize
                # or platform/flag configuration.
                from jax._src import xla_bridge

                if xla_bridge._backends:
                    import jax

                    rank, world = jax.process_index(), jax.process_count()
                    RankInfoFormatter._cached = (rank, world)
            except Exception:  # pragma: no cover - private API moved
                RankInfoFormatter._cached = (0, 1)
        else:
            rank, world = RankInfoFormatter._cached
        record.rank_info = f"[{rank}/{world}]"
        return super().format(record)


def _get_logger() -> _logging.Logger:
    logger = _logging.getLogger("apex_tpu")
    if not logger.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(
            RankInfoFormatter(
                "%(asctime)s %(rank_info)s %(name)s %(levelname)s: %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    return logger


logger = _get_logger()
