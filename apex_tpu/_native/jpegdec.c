/* jpegdec.c — scaled JPEG decode + fused crop/bilinear-resize.
 *
 * Host-side decode kernel for apex_tpu.data.image_folder — the native
 * analog of the reference recipe's DALI/worker decode stage
 * (examples/imagenet/main_amp.py:207-232 leans on DataLoader workers and
 * the README recommends DALI beyond that).  Two wins over the PIL path:
 *
 *   1. DCT-domain scaled decode: libjpeg(-turbo) can emit the image at
 *      M/8 scale (M=1..8) directly from the coefficients, so a 300px
 *      source headed for a 224px crop is never materialized at full
 *      resolution — the IDCT/upsample/color cost drops with the scale.
 *      The smallest M whose scaled crop still covers the requested
 *      output is chosen, so quality never drops below the resize target.
 *   2. The crop + bilinear resize is fused into the same pass over the
 *      decoded rows (separable weights precomputed per output column),
 *      replacing PIL's full-image resize-then-crop.
 *
 * Decoding stops (jpeg_abort_decompress) as soon as the last row of the
 * crop has been read, so bottom-of-image rows outside a training crop are
 * never IDCT'd.  All errors longjmp back and return nonzero — the Python
 * caller falls back to PIL; this file never exit()s or prints.
 *
 * Compiled lazily with the system cc (see apex_tpu/data/_jpeg_native.py,
 * same pattern as utils/flatten.py) and linked against the system
 * libjpeg; no build step at install time.
 */

#include <stddef.h>
#include <stdio.h> /* jpeglib.h needs size_t/FILE declared first */
#include <jpeglib.h>
#include <setjmp.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

struct err_mgr {
    struct jpeg_error_mgr pub;
    jmp_buf jmp;
};

static void err_exit(j_common_ptr cinfo) {
    struct err_mgr *e = (struct err_mgr *)cinfo->err;
    longjmp(e->jmp, 1);
}

static void err_silent(j_common_ptr cinfo, int msg_level) {
    /* swallow the text but keep the count: the default emit_message is
     * what increments num_warnings, which the truncation check reads */
    if (msg_level < 0)
        cinfo->err->num_warnings++;
}

/* Header-only parse: full-resolution (h, w).  rc 0 on success. */
int jpegdec_dims(const unsigned char *data, size_t len, int *h, int *w) {
    struct jpeg_decompress_struct cinfo;
    struct err_mgr jerr;

    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = err_exit;
    jerr.pub.emit_message = err_silent;
    if (setjmp(jerr.jmp)) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, (unsigned char *)data, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }
    *h = (int)cinfo.image_height;
    *w = (int)cinfo.image_width;
    jpeg_destroy_decompress(&cinfo);
    return 0;
}

/* Decode `data`, crop (cy, cx, ch, cw) given in FULL-RESOLUTION source
 * coordinates, bilinear-resize the crop to (out_h, out_w) RGB uint8 into
 * `out` (row-major HWC, caller-allocated out_h*out_w*3 bytes).  hflip
 * mirrors the output horizontally (folded into the column weights — free).
 *
 * rc: 0 ok; 1 decode error (corrupt/truncated); 2 unsupported colorspace
 * (e.g. CMYK — caller should fall back to PIL); 3 bad arguments.
 */
int jpegdec_decode_crop_resize(const unsigned char *data, size_t len,
                               int cy, int cx, int ch, int cw,
                               int out_h, int out_w, int hflip,
                               unsigned char *out) {
    struct jpeg_decompress_struct cinfo;
    struct err_mgr jerr;
    /* volatile: written between setjmp and longjmp, read in the error
     * path — without it the cleanup would free setjmp-time register
     * copies (C11 7.13.2.1p3) */
    unsigned char *volatile region = NULL; /* scaled rows covering crop */
    unsigned char *volatile scanline = NULL;
    int *volatile x0s = NULL;
    float *volatile fxs = NULL;

    if (ch <= 0 || cw <= 0 || out_h <= 0 || out_w <= 0)
        return 3;

    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = err_exit;
    jerr.pub.emit_message = err_silent;
    if (setjmp(jerr.jmp)) {
        jpeg_destroy_decompress(&cinfo);
        free(region);
        free(scanline);
        free(x0s);
        free(fxs);
        return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, (unsigned char *)data, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }

    int src_h = (int)cinfo.image_height;
    int src_w = (int)cinfo.image_width;
    if (cy < 0 || cx < 0 || cy + ch > src_h || cx + cw > src_w) {
        jpeg_destroy_decompress(&cinfo);
        return 3;
    }

    /* Smallest M/8 scale whose scaled crop still covers the output (no
     * DCT upscaling past full resolution: if the crop is smaller than
     * the output, decode it 1:1 and bilinear-upscale). */
    int m = 8;
    for (int cand = 1; cand <= 8; cand++) {
        if ((long)ch * cand / 8 >= out_h && (long)cw * cand / 8 >= out_w) {
            m = cand;
            break;
        }
    }
    cinfo.scale_num = (unsigned int)m;
    cinfo.scale_denom = 8;
    cinfo.out_color_space = JCS_RGB; /* gray->RGB handled by libjpeg */
    if (!jpeg_start_decompress(&cinfo)) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }
    if (cinfo.output_components != 3) {
        jpeg_abort_decompress(&cinfo);
        jpeg_destroy_decompress(&cinfo);
        return 2; /* CMYK etc. — PIL fallback */
    }

    int sw = (int)cinfo.output_width;
    int sh = (int)cinfo.output_height;
    /* Crop box mapped into scaled coordinates (exact, as doubles). */
    double sfy = (double)sh / (double)src_h;
    double sfx = (double)sw / (double)src_w;
    double scy = cy * sfy, sch = ch * sfy;
    double scx = cx * sfx, scw = cw * sfx;

    /* Scaled rows needed for bilinear sampling over the crop. */
    double y_lo = scy + 0.5 * sch / out_h - 0.5;
    double y_hi = scy + (out_h - 0.5) * sch / out_h - 0.5;
    int r0 = (int)y_lo;
    if (r0 < 0)
        r0 = 0;
    int r1 = (int)y_hi + 1;
    if (r1 > sh - 1)
        r1 = sh - 1;
    if (r1 < r0)
        r1 = r0;
    int n_rows = r1 - r0 + 1;

    region = malloc((size_t)n_rows * sw * 3);
    scanline = malloc((size_t)sw * 3);
    x0s = malloc(sizeof(int) * (size_t)out_w);
    fxs = malloc(sizeof(float) * (size_t)out_w);
    if (!region || !scanline || !x0s || !fxs) {
        jpeg_abort_decompress(&cinfo);
        jpeg_destroy_decompress(&cinfo);
        free(region);
        free(scanline);
        free(x0s);
        free(fxs);
        return 1;
    }

    /* Read scaled rows; discard above the crop, stop after its last row.
     * (Rows above still pay IDCT — correct for every libjpeg build; the
     * scaled decode is where the bulk of the win is.) */
    while ((int)cinfo.output_scanline <= r1) {
        int row = (int)cinfo.output_scanline;
        JSAMPROW dst = (row >= r0)
                           ? (JSAMPROW)(region + (size_t)(row - r0) * sw * 3)
                           : (JSAMPROW)scanline;
        if (jpeg_read_scanlines(&cinfo, &dst, 1) != 1)
            break;
    }
    /* A truncated stream either stalls read_scanlines (loop breaks short
     * of r1) or fakes an EOI with a JWRN_JPEG_EOF warning (swallowed by
     * err_silent) and pads gray — both must report failure, not return
     * interpolated garbage with rc 0. */
    int incomplete = ((int)cinfo.output_scanline <= r1
                      || cinfo.err->num_warnings != 0);
    jpeg_abort_decompress(&cinfo); /* skip rows below the crop */
    jpeg_destroy_decompress(&cinfo);
    if (incomplete) {
        free(region);
        free(scanline);
        free(x0s);
        free(fxs);
        return 1;
    }

    /* Separable bilinear: precompute column index+weight (hflip folds in
     * here), then one pass over output rows. */
    for (int j = 0; j < out_w; j++) {
        int jj = hflip ? (out_w - 1 - j) : j;
        double sx = scx + (jj + 0.5) * scw / out_w - 0.5;
        if (sx < 0)
            sx = 0;
        if (sx > sw - 1)
            sx = sw - 1;
        int x0 = (int)sx;
        if (x0 > sw - 2)
            x0 = sw - 2 >= 0 ? sw - 2 : 0;
        x0s[j] = x0;
        fxs[j] = (float)(sx - x0);
        if (sw == 1)
            fxs[j] = 0.0f;
    }
    for (int i = 0; i < out_h; i++) {
        double sy = scy + (i + 0.5) * sch / out_h - 0.5;
        if (sy < 0)
            sy = 0;
        if (sy > sh - 1)
            sy = sh - 1;
        int y0 = (int)sy - r0;
        if (y0 > n_rows - 2)
            y0 = n_rows - 2 >= 0 ? n_rows - 2 : 0;
        if (y0 < 0)
            y0 = 0;
        float fy = (float)(sy - (y0 + r0));
        if (fy < 0.0f || n_rows == 1)
            fy = 0.0f;
        const unsigned char *ra = region + (size_t)y0 * sw * 3;
        const unsigned char *rb =
            region + (size_t)(n_rows == 1 ? y0 : y0 + 1) * sw * 3;
        unsigned char *orow = out + (size_t)i * out_w * 3;
        for (int j = 0; j < out_w; j++) {
            int x0 = x0s[j];
            int x1 = (sw == 1) ? x0 : x0 + 1;
            float fx = fxs[j];
            const unsigned char *a0 = ra + (size_t)x0 * 3;
            const unsigned char *a1 = ra + (size_t)x1 * 3;
            const unsigned char *b0 = rb + (size_t)x0 * 3;
            const unsigned char *b1 = rb + (size_t)x1 * 3;
            for (int c = 0; c < 3; c++) {
                float top = a0[c] + fx * (a1[c] - a0[c]);
                float bot = b0[c] + fx * (b1[c] - b0[c]);
                float v = top + fy * (bot - top);
                orow[j * 3 + c] = (unsigned char)(v + 0.5f);
            }
        }
    }

    free(region);
    free(scanline);
    free(x0s);
    free(fxs);
    return 0;
}
