"""Host-side native helpers (C, compiled lazily with the system ``cc``).

``flatcopy.c`` provides the parallel flat gather/scatter used by
:mod:`apex_tpu.utils.flatten` — the host-memory analog of the reference's
``multi_tensor_apply`` flat-buffer staging.  No build step at install
time: :func:`apex_tpu.utils.flatten._build_and_load` compiles on first
use and falls back to numpy when no compiler is available.
"""
