/* flatcopy — host-side flatten/unflatten of tensor lists.
 *
 * Behavioral spec: the reference's apex_C extension
 * (csrc/flatten_unflatten.cpp:15-17 — flatten/unflatten over torch's
 * _flatten_dense_tensors), the one native module apex always builds.
 * On TPU the *device*-side use dissolves (XLA owns layout), but the
 * host-side use survives: assembling/splitting checkpoint and
 * host-transfer buffers without Python-loop copy overhead.
 *
 * Plain C + OpenMP, driven through ctypes (no pybind11 in this image).
 * Serial prefix pass for offsets, parallel memcpy over tensors.
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

void flat_gather(char *dst, void **srcs, const int64_t *sizes, int64_t n) {
    int64_t *offs = (int64_t *)malloc((size_t)n * sizeof(int64_t));
    int64_t acc = 0;
    for (int64_t i = 0; i < n; i++) { offs[i] = acc; acc += sizes[i]; }
    int64_t i;
#pragma omp parallel for schedule(static)
    for (i = 0; i < n; i++)
        memcpy(dst + offs[i], (const char *)srcs[i], (size_t)sizes[i]);
    free(offs);
}

void flat_scatter(const char *src, void **dsts, const int64_t *sizes,
                  int64_t n) {
    int64_t *offs = (int64_t *)malloc((size_t)n * sizeof(int64_t));
    int64_t acc = 0;
    for (int64_t i = 0; i < n; i++) { offs[i] = acc; acc += sizes[i]; }
    int64_t i;
#pragma omp parallel for schedule(static)
    for (i = 0; i < n; i++)
        memcpy((char *)dsts[i], src + offs[i], (size_t)sizes[i]);
    free(offs);
}
