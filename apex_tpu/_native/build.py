"""Shared lazy cc-compile-and-load for the native host kernels.

One implementation of the build contract both ``utils/flatten.py``
(``flatcopy.c``) and ``data/_jpeg_native.py`` (``jpegdec.c``) rely on:

- rebuild only when the source is newer than the ``.so`` (mtime);
- compile to a pid-suffixed temp name and ``os.replace`` — an atomic
  publish, so concurrent processes never load a half-written library,
  and the temp file is removed when the compile fails;
- any failure (no compiler, missing system lib, ...) returns ``None``
  and the caller keeps its pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

__all__ = ["build_and_load"]

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))


def build_and_load(src_name: str, so_name: str,
                   extra_flags: Sequence[str] = ()
                   ) -> Optional[ctypes.CDLL]:
    """Compile ``_native/<src_name>`` -> ``_native/<so_name>`` (if stale)
    and load it; ``None`` on any failure.  Callers add their own argtypes
    and caching (this function does a filesystem stat per call)."""
    src = os.path.join(_NATIVE_DIR, src_name)
    so = os.path.join(_NATIVE_DIR, so_name)
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        needs_build = os.path.exists(src) and (
            not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src))
        if needs_build:
            try:
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", src, "-o", tmp,
                     *extra_flags],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return ctypes.CDLL(so)
    except Exception:
        return None
