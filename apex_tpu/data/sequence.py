"""Packed-sequence LM streaming — pre-tokenized, length-packed batches.

The LM-side twin of :mod:`apex_tpu.data.packed`: tokenization (the LM
analog of JPEG decode) happens ONCE, offline; training then gathers
fixed-shape ``[B, seq_len]`` token batches out of a memory-mapped int32
shard — pure memcpy, no tokenizer on the training host — through the
same producer/prefetch machinery
(:class:`~apex_tpu.data._producer.ProducerLoader`), so the GPT trainers'
first real-data input path inherits every contract the image loaders
already prove: Megatron-sampler DP sharding, per-host ``dp_ranks``,
GLOBAL ``consumed_samples`` mid-epoch resume, preemption rewind,
``prefetch_to_device`` composition.

Packing scheme (the production pre-training layout — TorchTitan /
tf.data "packed examples"): documents are concatenated into one token
stream and reshaped into rows of ``seq_len`` with **no padding between
documents** — a row may hold several documents, and a document may span
rows.  Per-token **segment ids** (1-based per row, 0 = tail padding in
the final partial row only) mark the document boundaries so downstream
consumers can (a) mask the next-token loss at boundary crossings and (b)
build block-diagonal attention masks; with plain causal attention the
only cross-document leakage is attending back into the previous
document — the standard GPT pre-training trade.  See
:func:`segment_loss_mask` and
``transformer.testing.gpt_parallel_train.build_gpt_3d(packed_inputs=True)``.

Format (``<prefix>.tokens`` + ``<prefix>.segments`` + ``<prefix>.json``):

- ``.tokens``   — raw int32, shape [N, seq_len] (C-order);
- ``.segments`` — raw int32, shape [N, seq_len], 1-based document ids
  re-based per row, 0 = padding;
- ``.json``     — {"n", "seq_len", "n_docs", "version"} metadata.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Sequence, Tuple

import numpy as np

from apex_tpu.data._producer import ProducerLoader

__all__ = [
    "PackedSequenceDataset",
    "PackedSequenceLoader",
    "pack_token_documents",
    "segment_loss_mask",
    "synthetic_token_documents",
]


def pack_token_documents(docs: Iterable[Sequence[int]], out_prefix: str,
                         seq_len: int, *, eos_id=None,
                         drop_remainder: bool = False
                         ) -> "PackedSequenceDataset":
    """Pack pre-tokenized documents into a fixed-shape sequence shard.

    ``docs``: iterable of token id sequences (each one document, already
    tokenized — the offline stage).  ``eos_id`` (recommended) is appended
    to every document before packing, the usual document separator.
    Documents are concatenated and cut into rows of ``seq_len``; the
    final partial row is zero-padded with segment id 0 (or dropped with
    ``drop_remainder=True``).  Segment ids restart from 1 at each row so
    the id is a compact per-row document index, not a global one.

    One pass, bounded memory: each row is appended to the raw ``.tokens``
    / ``.segments`` files the moment it fills (the files are the same
    C-order bytes a ``[N, seq_len]`` memmap reads back), so packing a
    corpus never holds more than one document + one row in RAM.
    """
    if seq_len <= 1:
        raise ValueError(f"seq_len must be > 1, got {seq_len}")
    os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)

    cur_t = np.zeros((seq_len,), np.int32)
    cur_s = np.zeros((seq_len,), np.int32)
    fill = 0
    seg = 0  # per-row segment counter
    n = 0
    n_docs = 0
    with open(out_prefix + ".tokens", "wb") as tok_f, \
            open(out_prefix + ".segments", "wb") as seg_f:

        def flush_row():
            nonlocal n, fill
            tok_f.write(cur_t.tobytes())
            seg_f.write(cur_s.tobytes())
            cur_t[:] = 0
            cur_s[:] = 0
            fill = 0
            n += 1

        for doc in docs:
            doc = np.asarray(
                list(doc) + ([eos_id] if eos_id is not None else []),
                np.int32)
            if doc.size == 0:
                continue
            n_docs += 1
            seg += 1
            off = 0
            while off < doc.size:
                take = min(seq_len - fill, doc.size - off)
                cur_t[fill:fill + take] = doc[off:off + take]
                cur_s[fill:fill + take] = seg
                fill += take
                off += take
                if fill == seq_len:
                    flush_row()
                    # a document continuing into the next row keeps ONE
                    # logical identity but restarts the per-row counter
                    seg = 1 if off < doc.size else 0
        if fill and not drop_remainder:
            flush_row()
    if not n:
        for suffix in (".tokens", ".segments"):
            os.unlink(out_prefix + suffix)
        raise ValueError("no rows packed (empty docs?)")
    with open(out_prefix + ".json", "w") as f:
        json.dump({"n": n, "seq_len": seq_len, "n_docs": n_docs,
                   "version": 1}, f)
    return PackedSequenceDataset(out_prefix)


class PackedSequenceDataset:
    """Memory-mapped view over a packed sequence shard."""

    def __init__(self, prefix: str):
        with open(prefix + ".json") as f:
            meta = json.load(f)
        if meta.get("version") != 1:
            raise ValueError(
                f"unknown packed sequence format version: {meta}")
        self.seq_len = int(meta["seq_len"])
        self.n_docs = int(meta["n_docs"])
        self._n = int(meta["n"])
        shape = (self._n, self.seq_len)
        self.tokens = np.memmap(prefix + ".tokens", dtype=np.int32,
                                mode="r", shape=shape)
        self.segments = np.memmap(prefix + ".segments", dtype=np.int32,
                                  mode="r", shape=shape)

    def __len__(self) -> int:
        return self._n


class PackedSequenceLoader(ProducerLoader):
    """DP-sharded train iterator over a :class:`PackedSequenceDataset`.

    Yields ``(tokens int32 [B, seq_len], segments int32 [B, seq_len])``
    with ``B = local_batch * len(dp_ranks)`` and ``dp_ranks[i]``'s
    disjoint shard at rows ``[i*local : (i+1)*local]`` — the exact
    surface of the image loaders, so ``prefetch_to_device``, per-host
    sharding (``dp_ranks`` + ``dp_shard_batch(..., local_ranks=)``),
    ``DataService`` and ``consumed_samples`` checkpointing through
    ``resilience.CheckpointManager`` compose unchanged.  Feed the pair to
    ``build_gpt_3d(packed_inputs=True)``'s step or mask the loss with
    :func:`segment_loss_mask`.
    """

    def __init__(self, dataset: PackedSequenceDataset, local_batch: int,
                 data_parallel_size: int = 1, consumed_samples: int = 0,
                 seed: int = 0, prefetch: int = 2, dp_ranks=None):
        super().__init__(
            total_samples=len(dataset), local_batch=local_batch,
            data_parallel_size=data_parallel_size,
            consumed_samples=consumed_samples, seed=seed,
            prefetch=prefetch, dp_ranks=dp_ranks)
        self.dataset = dataset
        self.seq_len = dataset.seq_len

    def _gather(self, idx_per_rank) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.concatenate(idx_per_rank)
        # two fancy-index gathers out of the page cache — no tokenizer
        return (np.asarray(self.dataset.tokens[idx], np.int32),
                np.asarray(self.dataset.segments[idx], np.int32))


def segment_loss_mask(segments):
    """Next-token loss mask ``[b, s-1]`` for packed sequences: position
    ``t`` (predicting token ``t+1``) counts iff both tokens belong to the
    same document and neither is padding — the packed-stream analog of
    the reference data pipeline's pre-masked shifted labels.  Works on
    numpy or jax arrays (pure elementwise ops); jit-safe, fuses into the
    loss."""
    same = segments[:, 1:] == segments[:, :-1]
    real = segments[:, 1:] > 0
    return (same & real).astype("float32")


def synthetic_token_documents(n_docs: int, vocab: int, *,
                              mean_len: int = 64, seed: int = 0):
    """Deterministic synthetic pre-tokenized corpus (list of int lists) —
    the CI/bench stand-in for a real tokenized dataset."""
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n_docs):
        n = max(1, int(rng.poisson(mean_len)))
        # reserve 0 for padding and vocab-1 for an eos the caller may use
        docs.append(rng.randint(1, max(2, vocab - 1), size=n).tolist())
    return docs
