"""Shared producer-thread loader machinery.

One implementation of the bounded-queue background-producer pattern the
decode-free loaders share (:class:`~apex_tpu.data.packed.PackedLoader`
over image shards, :class:`~apex_tpu.data.sequence.PackedSequenceLoader`
over token shards): Megatron-sampler DP sharding, per-``__iter__``
iteration state (own stop flag, bounded queue, producer thread),
``consumed_samples`` mid-epoch resume with undelivered-batch rewind, the
single-live-iteration preemption contract, and producer-error relay into
the consuming train loop.  Subclasses provide only :meth:`_gather` (index
lists -> host batch) and the dataset length — the contracts pinned by
``tests/test_packed_data.py`` hold for every subclass by construction.

Per-host input sharding: like ``ImageFolderLoader``, ``dp_ranks``
restricts a loader to the dp shards this host's devices own
(``parallel.host_dp_ranks``); ``consumed_samples`` stays in GLOBAL
samples so one checkpointed integer resumes every host coherently.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Iterator, Optional, Sequence

__all__ = ["ProducerLoader", "make_dp_samplers", "reap_process"]


def reap_process(proc, timeout: float, what: str = "worker") -> None:
    """Bounded process teardown — join, then escalate terminate -> kill.
    The ONE reaping ladder shared by the process-pool decode backend,
    ``DataService.close``, and the service's GC/exit finalizer, so a
    wedged child (uninterruptible NFS/FUSE read) can never hang trainer
    shutdown, and the escalation policy cannot drift between sites."""
    proc.join(timeout=max(0.0, timeout))
    if not proc.is_alive():
        return
    logging.getLogger(__name__).warning(
        "%s %s did not exit in %.1fs; terminating",
        what, getattr(proc, "pid", "?"), timeout)
    proc.terminate()
    proc.join(timeout=2.0)
    if proc.is_alive():
        proc.kill()
        proc.join(timeout=2.0)


def make_dp_samplers(total_samples: int, local_batch: int,
                     data_parallel_size: int, consumed_samples: int,
                     dp_ranks: Optional[Sequence[int]]):
    """Validate ``dp_ranks`` and build one Megatron sampler per rank —
    the ONE definition of the per-host sharding surface, shared by
    :class:`ProducerLoader` and ``ImageFolderLoader`` so their
    validation (range, non-empty, no duplicates) cannot diverge.
    Returns ``(dp_ranks tuple, samplers list)``."""
    from apex_tpu.transformer._data import MegatronPretrainingRandomSampler

    if dp_ranks is None:
        dp_ranks = range(data_parallel_size)
    dp_ranks = tuple(dp_ranks)
    if not dp_ranks:
        raise ValueError("dp_ranks must name at least one dp rank")
    if len(set(dp_ranks)) != len(dp_ranks):
        raise ValueError(f"dp_ranks has duplicates: {dp_ranks} — a rank "
                         "decoded twice silently trains duplicated data")
    for r in dp_ranks:
        if not 0 <= r < data_parallel_size:
            raise ValueError(
                f"dp_ranks entry {r} outside [0, {data_parallel_size})")
    samplers = [
        MegatronPretrainingRandomSampler(
            total_samples=total_samples,
            consumed_samples=consumed_samples,
            local_minibatch_size=local_batch,
            data_parallel_rank=r,
            data_parallel_size=data_parallel_size,
        )
        for r in dp_ranks
    ]
    return dp_ranks, samplers


class _ProducerError:
    """Exception relay from the producer thread to the consuming iterator."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _Iteration:
    """Per-``__iter__`` state: its own stop flag, bounded queue, producer
    thread, and count of sampler-advanced-but-undelivered batches."""

    def __init__(self, prefetch: int):
        self.stop = threading.Event()
        self.queue: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self.thread: Optional[threading.Thread] = None
        self.mine = 0


class ProducerLoader:
    """Base DP-sharded producer-thread iterator (see module docstring).

    Subclass contract::

        class MyLoader(ProducerLoader):
            def _gather(self, idx_per_rank):  # index lists -> host batch
                ...

    The producer is a single background thread: per batch it draws one
    index list per dp rank from the shared samplers (under the lock) and
    gathers the batch; ``prefetch`` bounds the queue.  One live iteration
    per loader: starting a second tears down (and rewinds) the first.
    """

    def __init__(self, total_samples: int, local_batch: int,
                 data_parallel_size: int = 1, consumed_samples: int = 0,
                 seed: int = 0, prefetch: int = 2,
                 dp_ranks: Optional[Sequence[int]] = None):
        self.local_batch = local_batch
        self.dp = data_parallel_size
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self.dp_ranks, self.samplers = make_dp_samplers(
            total_samples, local_batch, data_parallel_size,
            consumed_samples, dp_ranks)
        self._lock = threading.Lock()
        self._active: list = []  # live _Iteration states (usually 0 or 1)

    # -- subclass surface ----------------------------------------------

    def _gather(self, idx_per_rank):
        """Per-rank index lists -> one host batch (numpy arrays)."""
        raise NotImplementedError

    # -- resume bookkeeping --------------------------------------------

    @property
    def consumed_samples(self) -> int:
        """GLOBAL samples in batches already yielded.  Producer threads
        run the samplers ``prefetch`` batches ahead; batches pulled but
        not delivered (queued, mid-gather, or discarded by an early
        ``close()``) are subtracted under the same lock the producers
        advance under, so a checkpoint taken between steps resumes at the
        first undelivered batch — exactly ImageFolderLoader's contract."""
        with self._lock:
            return (self.samplers[0].consumed_samples
                    - sum(st.mine for st in self._active)
                    * self.local_batch * self.dp)

    def rewind_batches(self, n: int) -> None:
        """Roll the samplers back ``n`` yielded batches (the
        ``DevicePrefetcher.close()`` resume surface)."""
        with self._lock:
            for s in self.samplers:
                s.consumed_samples -= n * self.local_batch * self.dp

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        """Stop every live iteration and rewind the samplers past any
        batches gathered but never delivered, so re-iterating (or
        resuming from ``consumed_samples``) replays exactly the
        undelivered data — ImageFolderLoader's abandoned-iteration
        contract."""
        with self._lock:
            states = list(self._active)
        for st in states:
            self._finish(st)

    def __enter__(self) -> "ProducerLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- producer -------------------------------------------------------

    def _produce(self, st: "_Iteration") -> None:
        its = [iter(s) for s in self.samplers]
        while not st.stop.is_set():
            try:
                with self._lock:
                    idx_per_rank = [next(it) for it in its]
                    st.mine += 1
                batch = self._gather(idx_per_rank)
            except StopIteration:
                # epoch end: sentinel wakes the consumer, which returns
                st.queue.put(None)
                return
            except BaseException as e:  # noqa: BLE001 — relayed, not eaten
                # a dead producer must fail the training loop, not wedge
                # it in queue.get() (ImageFolderLoader propagates decode
                # errors through future.result() the same way)
                st.queue.put(_ProducerError(e))
                return
            while not st.stop.is_set():
                try:
                    st.queue.put(batch, timeout=0.2)
                    break
                except queue.Full:
                    continue

    def _finish(self, st: "_Iteration") -> None:
        """Tear down one iteration: stop+join its producer, then rewind
        the samplers by its undelivered batches (``st.mine``)."""
        st.stop.set()
        # claim the thread under the lock: _finish can race itself (an
        # iterator finalizer vs an explicit stop) and only one caller
        # may join/drain — the loser must see None, not a torn teardown
        with self._lock:
            thread, st.thread = st.thread, None
        if thread is not None:
            # unblock a producer waiting on a full queue; drained batches
            # stay counted in st.mine (they were never delivered)
            try:
                while True:
                    st.queue.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=5.0)
            # wake a consumer still blocked in queue.get() (a preempted
            # iterator whose producer exited without a sentinel): drain
            # anything the producer managed to enqueue before stopping,
            # then leave one end-of-epoch sentinel
            try:
                while True:
                    st.queue.get_nowait()
            except queue.Empty:
                pass
            try:
                st.queue.put_nowait(None)
            except queue.Full:
                pass
            if thread.is_alive():
                # a producer stuck >5 s (cold memmap page-in on a slow
                # disk) is left daemonized but must be visible, not a
                # silently leaked thread holding the drained queue
                logging.getLogger(__name__).warning(
                    "%s: producer thread did not exit within 5 s of stop; "
                    "leaking it as a daemon (likely blocked in a gather)",
                    type(self).__name__)
        with self._lock:
            if st in self._active:
                self._active.remove(st)
            undelivered, st.mine = st.mine, 0
            if undelivered:
                for s in self.samplers:
                    s.consumed_samples -= (
                        undelivered * self.local_batch * self.dp)

    # -- consumer -------------------------------------------------------

    def __iter__(self) -> Iterator:
        # one epoch per __iter__ call, mirroring ImageFolderLoader: the
        # samplers hold position, so re-iterating starts the next epoch.
        # All iteration state is per-call so overlapping/abandoned
        # iterators never share a stop flag or queue — but the SAMPLERS
        # are shared, so two *live* producers would interleave duplicate
        # index streams while double-advancing consumed_samples.  Only
        # one live iteration is supported (as with ImageFolderLoader):
        # starting a new one first tears down any still-active prior
        # iteration (covers abandoned, un-GC'd generators) and rewinds
        # its undelivered batches.
        with self._lock:
            stale = list(self._active)
        for old in stale:
            self._finish(old)
        st = _Iteration(self.prefetch)
        with self._lock:
            self._active.append(st)
        st.thread = threading.Thread(
            target=self._produce, args=(st,), daemon=True)
        st.thread.start()
        try:
            while True:
                # poll-with-timeout rather than a bare blocking get: a
                # preempted iteration (stop set by a newer __iter__) must
                # terminate even if its wake-up sentinel was lost to a
                # racing put from a slow-to-exit producer
                try:
                    batch = st.queue.get(timeout=0.5)
                except queue.Empty:
                    if st.stop.is_set():
                        return
                    continue
                if batch is None:
                    return
                if isinstance(batch, _ProducerError):
                    raise batch.exc
                with self._lock:
                    # check-and-decrement must be one atomic section:
                    # _finish (a competing __iter__ or close()) sets stop,
                    # rewinds the samplers and zeroes st.mine under this
                    # same lock — a stop check outside it could pass just
                    # before the teardown, and the decrement after it
                    # would both deliver an already-rewound batch twice
                    # and drive st.mine to -1
                    if st.stop.is_set():
                        return
                    st.mine -= 1
                yield batch
        finally:
            self._finish(st)
