"""ImageFolder dataset + DP-sharded loader (PIL/numpy, no torch).

Behavioral spec: the reference imagenet example's input pipeline —
``examples/imagenet/main_amp.py:207-232`` (``datasets.ImageFolder`` with
``RandomResizedCrop`` + ``RandomHorizontalFlip`` train transforms,
``Resize``+``CenterCrop`` eval transforms, ``DistributedSampler`` for DP
sharding) and ``fast_collate`` (``:48-63``), which batches *uint8* tensors
and defers mean/std normalization to the accelerator
(``data_prefetcher``, ``:256-276``).

TPU-first differences:

- layout is NHWC (XLA's native conv layout on TPU), not NCHW;
- batches stay uint8 across the host->device hop (4x less PCIe/DCN
  traffic than fp32); :func:`normalize_on_device` runs inside the jitted
  train step, where XLA fuses it into the first conv — exactly the role
  of the reference's CUDA-stream prefetcher normalize;
- DP sharding reuses the Megatron samplers
  (:mod:`apex_tpu.transformer._data`) so ``consumed_samples`` checkpoint
  resume works for vision runs too (one sampler per dp rank, stacked into
  the global batch that ``dp_shard_batch`` lays onto the mesh);
  ``dp_ranks`` restricts a loader to the dp shards THIS host's devices
  own (``parallel.host_dp_ranks``) so a multi-process job decodes each
  image exactly once instead of every host decoding the global batch —
  the ``DataLoader``-per-process structure of the reference, with
  placement through ``dp_shard_batch(..., local_ranks=dp_ranks)``;
- decode parallelism is selectable (``backend=``): a **process pool**
  (the true ``DataLoader(num_workers=...)`` analog — sidesteps the GIL
  entirely, the production-rate default for JPEG-decode-bound hosts) or
  a **thread pool** (both decode paths release the GIL for most of their
  work; lower fixed cost, the fallback where spawning workers is
  unwanted).  Per-image decode prefers the native C kernel
  (``_native/jpegdec.c`` — DCT-scaled libjpeg decode fused with the
  crop + bilinear resize, ~1.5-2x a PIL worker per core, the role of
  the reference recipe's DALI stage) and falls back to PIL per-image;
  the decode core is a module-level pure function over an immutable
  :class:`_DecodeSpec`, so both backends run byte-identical code and the
  augmentation stream is backend-independent;
- batches are decoded ``prefetch`` steps ahead: the loader keeps the
  decode futures for the next batches in flight while the caller's train
  step runs on device, so host decode overlaps device compute — the role
  of the reference's ``DataLoader`` worker queue + ``data_prefetcher``
  double-buffering (``main_amp.py:207-232,256-276``) without a CUDA
  stream.  ``consumed_samples`` always reflects batches *yielded*, not
  batches decoding ahead, so checkpoint resume stays exact.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Iterator, NamedTuple, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "ImageFolder",
    "ImageFolderLoader",
    "center_crop_resize",
    "normalize_on_device",
    "random_resized_crop",
    "sample_crop_box",
    "synthetic_image_batches",
]

IMAGENET_MEAN = (0.485, 0.456, 0.406)  # main_amp.py:251-252
IMAGENET_STD = (0.229, 0.224, 0.225)

_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


class ImageFolder:
    """``root/class_x/img.jpg`` directory dataset.

    Classes are the sorted subdirectory names mapped to contiguous indices
    (torchvision's ``ImageFolder`` contract, which the reference trains
    on); samples are lexicographically ordered within a class so the
    index->sample mapping is deterministic across processes.
    """

    def __init__(self, root: str,
                 extensions: Sequence[str] = _EXTENSIONS):
        self.root = root
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise ValueError(f"no class subdirectories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples: list = []
        exts = tuple(e.lower() for e in extensions)
        for cls in self.classes:
            cdir = os.path.join(root, cls)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):
                    self.samples.append(
                        (os.path.join(cdir, fname), self.class_to_idx[cls]))
        if not self.samples:
            raise ValueError(f"no images found under {root!r}")

    def __len__(self) -> int:
        return len(self.samples)

    def load(self, index: int):
        """Decode one sample -> (PIL RGB image, label)."""
        from PIL import Image

        path, label = self.samples[index]
        with Image.open(path) as img:
            return img.convert("RGB"), label


def sample_crop_box(rng: np.random.RandomState, w: int, h: int,
                    scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)
                    ) -> Tuple[int, int, int, int]:
    """``RandomResizedCrop``'s box sampler -> ``(x0, y0, cw, ch)`` in
    source coordinates.  After 10 rejected draws it falls back to
    torchvision's ratio-clamped center crop (the whole image when its
    aspect ratio is inside ``ratio``, else the largest in-bounds region).
    Shared by the PIL and native decode paths so both consume the *same*
    RNG draw sequence — the augmentation stream is identical whichever
    path decodes."""
    area = w * h
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = rng.randint(0, w - cw + 1)
            y0 = rng.randint(0, h - ch + 1)
            return x0, y0, cw, ch
    in_ratio = w / h
    if in_ratio < min(ratio):
        cw = w
        ch = int(round(cw / min(ratio)))
    elif in_ratio > max(ratio):
        ch = h
        cw = int(round(ch * max(ratio)))
    else:
        cw, ch = w, h
    return (w - cw) // 2, (h - ch) // 2, cw, ch


def random_resized_crop(rng: np.random.RandomState, img, size: int,
                        scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                        flip: bool = True) -> np.ndarray:
    """``RandomResizedCrop(size)`` + ``RandomHorizontalFlip`` -> uint8
    HWC (the reference's train transform, ``main_amp.py:209-214``)."""
    from PIL import Image

    w, h = img.size
    x0, y0, cw, ch = sample_crop_box(rng, w, h, scale, ratio)
    img = img.crop((x0, y0, x0 + cw, y0 + ch))
    img = img.resize((size, size), Image.BILINEAR)
    out = np.asarray(img, np.uint8)
    if flip and rng.rand() < 0.5:
        out = out[:, ::-1]
    return out


def center_crop(img, crop: int):
    w, h = img.size
    x0 = (w - crop) // 2
    y0 = (h - crop) // 2
    return img.crop((x0, y0, x0 + crop, y0 + crop))


def _default_eval_resize(size: int) -> int:
    """The eval transform's short-side resize for a given crop size —
    256 for the canonical 224 (``main_amp.py:216-219``).  One definition
    shared by the PIL and native eval paths so they cannot skew."""
    return int(size * 256 / 224)


def eval_crop_box(w: int, h: int, size: int,
                  resize: Optional[int] = None) -> Tuple[int, int, int]:
    """Source-coordinate square ``(x0, y0, side)`` that the eval
    transform ``Resize(resize)`` + ``CenterCrop(size)`` keeps.  The
    native decode path crops this region and resizes straight to
    ``(size, size)``; :func:`center_crop_resize` realizes the same
    geometry through PIL's resize-then-crop."""
    resize = resize or _default_eval_resize(size)
    short = min(w, h)
    side = min(int(round(short * size / resize)), short)
    return (w - side) // 2, (h - side) // 2, side


def center_crop_resize(img, size: int, resize: Optional[int] = None
                       ) -> np.ndarray:
    """``Resize(resize)`` + ``CenterCrop(size)`` -> uint8 HWC (the
    reference's eval transform, ``main_amp.py:216-219``)."""
    from PIL import Image

    resize = resize or _default_eval_resize(size)
    w, h = img.size
    short = min(w, h)
    img = img.resize((int(round(w * resize / short)),
                      int(round(h * resize / short))), Image.BILINEAR)
    return np.asarray(center_crop(img, size), np.uint8)


def normalize_on_device(x_uint8, mean=IMAGENET_MEAN, std=IMAGENET_STD,
                        dtype=None):
    """uint8 NHWC -> normalized float, *inside* the jitted step (the
    reference prefetcher's GPU-side ``sub_(mean).div_(std)``,
    ``main_amp.py:268-276``; XLA fuses this into the consuming conv)."""
    import jax.numpy as jnp

    from apex_tpu.observability.spans import named_span

    dtype = dtype or jnp.float32
    with named_span("data/normalize"):
        x = x_uint8.astype(dtype) / jnp.asarray(255.0, dtype)
        mean = jnp.asarray(mean, dtype)
        std = jnp.asarray(std, dtype)
        return (x - mean) / std


# ---------------------------------------------------------------------------
# Decode core — module-level pure functions over an immutable spec, so the
# thread backend, the process backend (pickled to spawned workers), and the
# data-service loader processes all run byte-identical decode code.
# ---------------------------------------------------------------------------


class _DecodeSpec(NamedTuple):
    """Everything one decode needs, shipped once per worker process.

    ``dataset`` is the ImageFolder (or any duck-type exposing
    ``samples`` — the ``(path, label)`` list the native fast path and
    the samplers index — and ``load(i) -> (PIL image, label)``, the
    authoritative decode the PIL path calls, so custom datasets that
    override ``load`` keep working on every backend).  The process
    backend pickles it once per worker via the pool initializer, so a
    custom dataset must be picklable there."""

    dataset: object      # .samples + .load(i)
    image_size: int
    train: bool
    seed: int
    native: bool


def _decode_native_one(spec: _DecodeSpec, index: int,
                       rng: Optional[np.random.RandomState]
                       ) -> Optional[Tuple[np.ndarray, int]]:
    """One-call C decode+crop+resize (``_native/jpegdec.c``) — DCT
    scaled decode fused with the transform, ~2x a PIL worker on the
    same core.  Returns ``None`` (caller decodes via PIL) for
    non-JPEG files or any per-image failure.  Draws the crop box
    from the SAME :func:`sample_crop_box` stream as the PIL path, so
    augmentation determinism is path-independent."""
    from apex_tpu.data import _jpeg_native

    path, label = spec.dataset.samples[index]
    if not path.lower().endswith((".jpg", ".jpeg")):
        return None
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    dims = _jpeg_native.jpeg_dims(data)
    if dims is None:
        return None
    h, w = dims
    size = spec.image_size
    if rng is not None:  # train transform
        x0, y0, cw, ch = sample_crop_box(rng, w, h)
        flip = bool(rng.rand() < 0.5)
    else:  # eval: the region center_crop_resize would keep
        x0, y0, side = eval_crop_box(w, h, size)
        cw = ch = side
        flip = False
    arr = _jpeg_native.decode_crop_resize(
        data, y0, x0, ch, cw, size, size, hflip=flip)
    if arr is None:
        return None
    return arr, label


def _decode_one(spec: _DecodeSpec, index: int, consumed_marker: int
                ) -> Tuple[np.ndarray, int]:
    """Decode + transform one sample.  Pure in ``(spec, index, marker)``
    — the augmentation seed folds the sampler position captured at
    submission time, so the stream is identical at every prefetch depth
    and on every backend."""
    if spec.train:
        # fold the sample index + sampler position into the seed:
        # deterministic but different augmentation per sample and epoch.
        rng = np.random.RandomState(
            (spec.seed + consumed_marker + index) % (2 ** 31))
    else:
        rng = None
    if spec.native:
        # snapshot the RNG: a native failure *after* the crop draws
        # (e.g. truncated file) must hand PIL the same stream it
        # would have seen had the native path never run
        state = rng.get_state() if rng is not None else None
        out = _decode_native_one(spec, index, rng)
        if out is not None:
            return out
        if state is not None:
            rng.set_state(state)
    # the dataset's load() is authoritative (custom datasets override it)
    img, label = spec.dataset.load(index)
    if spec.train:
        arr = random_resized_crop(rng, img, spec.image_size)
    else:
        arr = center_crop_resize(img, spec.image_size)
    return arr, label


# Spawned decode workers hold the spec in a module global (set once by the
# pool initializer) so tasks ship only (index, marker), not the spec.
_WORKER_SPEC: Optional[_DecodeSpec] = None


def _process_worker_init(spec: _DecodeSpec) -> None:
    global _WORKER_SPEC
    _WORKER_SPEC = spec


def _process_decode_chunk(indices, marker: int):
    """Decode a chunk of samples in one task — amortizes the per-task
    submit/pickle round trip (per-image tasks spend a measurable
    fraction of a wide pool's budget on IPC, not decode).  The images
    are stacked into ONE uint8 array so the result pickles as a single
    contiguous buffer."""
    outs = [_decode_one(_WORKER_SPEC, i, marker) for i in indices]
    return (np.stack([o[0] for o in outs]),
            np.asarray([o[1] for o in outs], np.int32))


def _worker_warmup() -> bool:
    """Pull the decode imports into a worker and hold it briefly so the
    pool spawns its full width (ProcessPoolExecutor adds processes only
    while a backlog exists)."""
    import time

    import PIL.Image  # noqa: F401 — the import IS the warmup

    time.sleep(0.05)
    return True


class ImageFolderLoader:
    """DP-sharded training iterator over an :class:`ImageFolder`.

    Yields ``(images uint8 [B, size, size, 3], labels int32 [B])``
    batches where ``B = local_batch * len(dp_ranks)`` and row window
    ``[i*local : (i+1)*local]`` is ``dp_ranks[i]``'s disjoint shard (the
    ``DistributedSampler`` contract).  ``dp_ranks`` defaults to ALL dp
    ranks (single-host: the global batch — feed the tuple to
    ``parallel.dp_shard_batch``); a multi-process job passes
    ``parallel.host_dp_ranks(mesh)`` so each host decodes only its own
    shards and places them with
    ``dp_shard_batch(batch, mesh, local_ranks=dp_ranks)``.
    ``consumed_samples`` stays in GLOBAL samples on every host (each
    yielded batch advances it by ``local_batch * data_parallel_size``),
    so a single checkpointed integer resumes all hosts coherently.

    ``backend``: ``"process"`` (spawned worker processes — the true
    ``DataLoader(num_workers=...)`` analog, immune to the GIL; decode
    state ships once per worker via the pool initializer) or
    ``"thread"`` (in-process pool — lower fixed cost; both decode paths
    release the GIL for the codec work but contend for it in the numpy
    glue).  Epoch shuffling and mid-epoch resume come from
    :class:`~apex_tpu.transformer._data.MegatronPretrainingRandomSampler`.
    """

    def __init__(self, dataset: ImageFolder, local_batch: int,
                 data_parallel_size: int = 1, image_size: int = 224,
                 consumed_samples: int = 0, train: bool = True,
                 workers: int = 8, seed: int = 0, prefetch: int = 2,
                 native: Optional[bool] = None, backend: str = "thread",
                 dp_ranks: Optional[Sequence[int]] = None,
                 mp_start: str = "spawn"):
        from apex_tpu.transformer._data import (
            MegatronPretrainingRandomSampler,
        )

        self.dataset = dataset
        self.local_batch = local_batch
        self.dp = data_parallel_size
        self.image_size = image_size
        self.train = train
        self.seed = seed
        self.prefetch = max(0, prefetch)
        if backend not in ("thread", "process"):
            raise ValueError(
                f"backend must be 'thread' or 'process', got {backend!r}")
        self.backend = backend
        # native=None -> auto: the C decode kernel when it builds (cc +
        # libjpeg present), PIL otherwise; failures of either the build
        # or any single image fall back to PIL per-image.  An explicit
        # native=True warns when the kernel is unavailable so an A/B
        # comparison cannot silently run PIL on both sides.
        if native is None or native:
            from apex_tpu.data import _jpeg_native
            self._native = _jpeg_native.native_available()
            if native and not self._native:
                import warnings
                warnings.warn(
                    "ImageFolderLoader(native=True): native JPEG kernel "
                    "unavailable (no cc or libjpeg?); decoding via PIL")
        else:
            self._native = False
        self._spec = _DecodeSpec(
            dataset=dataset, image_size=image_size,
            train=train, seed=seed, native=self._native)
        self._workers = workers
        self._inflight = 0  # batches decoded/decoding ahead of the caller
        # Guards the sampler-advance + _inflight bookkeeping: under the
        # documented loader -> prefetch_to_device stack, the TRANSFER
        # thread drives this loader's generator while the trainer thread
        # reads consumed_samples for a checkpoint — an unlocked read
        # could tear between the sampler advance and the _inflight
        # increment and over-count by one undelivered batch.
        self._count_lock = threading.Lock()
        if backend == "process":
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            # spawn (not fork): the parent may hold live XLA/decode
            # threads, and a forked child inheriting their locks can
            # deadlock; spawned workers import only the light data
            # modules and receive the spec once via the initializer.
            self._pool = ProcessPoolExecutor(
                max_workers=workers,
                mp_context=mp.get_context(mp_start),
                initializer=_process_worker_init,
                initargs=(self._spec,))
        else:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=workers)
        from apex_tpu.data._producer import make_dp_samplers

        self.dp_ranks, self.samplers = make_dp_samplers(
            len(dataset), local_batch, data_parallel_size,
            consumed_samples, dp_ranks)

    @property
    def consumed_samples(self) -> int:
        """GLOBAL samples in batches already *yielded* to the caller.
        The samplers themselves run ``prefetch`` batches ahead; in-flight
        (decoding, not yet delivered) batches are subtracted so a
        checkpoint taken mid-epoch resumes at the first undelivered
        batch."""
        with self._count_lock:
            return (self.samplers[0].consumed_samples
                    - self._inflight * self.local_batch * self.dp)

    def rewind_batches(self, n: int) -> None:
        """Roll the samplers back ``n`` yielded batches — the resume
        surface :class:`~apex_tpu.data.prefetch.DevicePrefetcher` uses
        on ``close()`` so undelivered device-queued batches are replayed
        rather than lost."""
        with self._count_lock:
            for s in self.samplers:
                s.consumed_samples -= n * self.local_batch * self.dp

    def warm_up(self) -> "ImageFolderLoader":
        """Spin the decode pool to full width before the first batch.
        For the process backend this pays the worker spawn + import cost
        (~1-2 s for a wide pool) up front instead of inside step 1 — the
        ``DataLoader(persistent_workers=True)`` warm-start analog.  Cheap
        no-op-ish for threads.  Returns self (chainable)."""
        import concurrent.futures as cf

        if self.backend == "process":
            futs = [self._pool.submit(_worker_warmup)
                    for _ in range(self._workers)]
        else:
            futs = [self._pool.submit(bool) for _ in range(self._workers)]
        cf.wait(futs, timeout=120.0)
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Shut down the decode pool (idempotent).  Loaders are also
        context managers; without either, a thread pool's threads — or a
        process pool's workers — live for the rest of the process.

        Process workers are reaped with a BOUNDED wait: join up to
        ``timeout`` seconds, then escalate terminate -> kill (the
        DataService.close discipline) — a worker wedged in an
        uninterruptible NFS/FUSE read must not hang trainer shutdown
        (or a preemption-driven teardown) forever."""
        # snapshot the worker handles BEFORE shutdown (the executor's
        # management thread clears its process table as workers exit)
        procs = (list((getattr(self._pool, "_processes", None) or {})
                      .values())
                 if self.backend == "process" else [])
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self.backend != "process":
            return
        import time

        from apex_tpu.data._producer import reap_process

        deadline = time.monotonic() + timeout
        for p in procs:
            reap_process(p, deadline - time.monotonic(),
                         what="decode worker")

    def __enter__(self) -> "ImageFolderLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort backstop
        try:
            self._pool.shutdown(wait=False, cancel_futures=True)
        except Exception:
            pass

    def _submit_batch(self, indices, marker: int) -> list:
        """Fan one batch's decode out over the pool.  Threads get
        per-image tasks (fine-grained, no IPC); processes get ~2 chunks
        per worker (each task's result pickles as one contiguous stack —
        per-image IPC round trips cost a wide pool real throughput)."""
        if self.backend == "process":
            per = max(1, -(-len(indices) // (2 * self._workers)))
            return [self._pool.submit(
                        _process_decode_chunk, indices[o:o + per], marker)
                    for o in range(0, len(indices), per)]
        return [self._pool.submit(_decode_one, self._spec, i, marker)
                for i in indices]

    def _assemble(self, futs: list) -> Tuple[np.ndarray, np.ndarray]:
        if self.backend == "process":
            chunks = [f.result() for f in futs]
            return (np.concatenate([c[0] for c in chunks]),
                    np.concatenate([c[1] for c in chunks]))
        decoded = [f.result() for f in futs]
        return (np.stack([d[0] for d in decoded]),
                np.asarray([d[1] for d in decoded], np.int32))

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield batches, keeping ``prefetch`` future batches' decode
        work in flight: the next batches decode on the pool while the
        caller's train step occupies the device, and assembly at
        ``next()`` normally just collects already-finished futures."""
        sampler_it = zip(*self.samplers)
        pending: deque = deque()
        # this iterator's OWN in-flight count: two live iterators over one
        # loader must each rewind only their own undelivered batches
        mine = 0

        def submit_next() -> bool:
            nonlocal mine
            # sampler advance + marker + in-flight increment are ONE
            # atomic section against consumed_samples reads from the
            # trainer thread (the transfer thread runs this generator)
            with self._count_lock:
                per_rank = next(sampler_it, None)
                if per_rank is None:
                    return False
                # sampler position *after* drawing this batch — the seed
                # the synchronous (prefetch=0) loader would have used
                marker = self.samplers[0].consumed_samples
                self._inflight += 1
            indices = [i for rank_ids in per_rank for i in rank_ids]
            pending.append(self._submit_batch(indices, marker))
            mine += 1
            return True

        try:
            while True:
                # top up to prefetch batches beyond the one about to be
                # assembled; prefetch=0 degenerates to the synchronous
                # decode-at-next() behavior
                while len(pending) < self.prefetch + 1:
                    if not submit_next():
                        break
                if not pending:
                    break
                x, y = self._assemble(pending.popleft())
                mine -= 1
                with self._count_lock:
                    self._inflight -= 1
                yield x, y
        finally:
            # abandoned iterator (break / exception): the undelivered
            # batches will never be yielded — rewind the samplers so
            # consumed_samples and a fresh __iter__ restart from the
            # first undelivered batch.
            for f in (f for futs in pending for f in futs):
                f.cancel()
            if mine:
                with self._count_lock:
                    for s in self.samplers:
                        s.consumed_samples -= (
                            mine * self.local_batch * self.dp)
                    self._inflight -= mine


def synthetic_image_batches(batch_size: int, image_size: int,
                            num_classes: int, seed: int = 0
                            ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Shape-compatible synthetic stream (uint8, like the real loader) —
    the CI path and the ``--data``-less default of the examples."""
    rng = np.random.RandomState(seed)
    while True:
        x = rng.randint(0, 256, size=(batch_size, image_size, image_size, 3),
                        dtype=np.uint8)
        y = rng.randint(0, num_classes, size=(batch_size,)).astype(np.int32)
        yield x, y
