"""Data-service split: dedicated loader processes feeding the trainer.

The third scaling stage of the input pipeline (after in-process worker
pools and per-host ``dp_ranks`` sharding): move the WHOLE loader — the
sampler walk, the decode/gather pool, the batch assembly — into a
dedicated process, and hand the training process nothing but a local
queue to pop.  This is the tf.data-service / grain per-host split at
single-host scope: the trainer's Python thread spends zero time in
decode glue (no GIL contention with dispatch), the loader process can be
scheduled/priority-pinned independently, and an OOM or codec crash in
the loader surfaces as a clean relayed exception instead of taking the
training step down.

The service keeps the loader resume surface (``local_batch``/``dp``/
``consumed_samples``) so :func:`~apex_tpu.data.prefetch.
prefetch_to_device` composes unchanged on top::

    svc = DataService(make_loader, consumed_samples=restored)
    for batch in prefetch_to_device(svc, mesh):
        ...
    # checkpoint prefetcher.consumed_samples; on restore rebuild both

``factory`` must be picklable (a module-level function or
``functools.partial`` over picklable args): the child process calls
``factory(consumed_samples)`` to build the loader, then streams batches
continuously ACROSS epochs (re-iterating the loader at each epoch end —
the Megatron samplers advance through epochs by ``consumed_samples``),
so the service is an infinite stream like
``synthetic_image_batches``, not a one-epoch iterator.

``consumed_samples`` counts GLOBAL samples in batches delivered to the
consuming process — batches buffered in the queue (or in the child) are
NOT counted, so a checkpoint taken between steps resumes at the first
undelivered batch, exactly the loaders' contract.
"""

from __future__ import annotations

import logging
import queue as queue_mod
from typing import Callable, Optional

__all__ = ["DataService"]

logger = logging.getLogger(__name__)


def _shutdown_service(stop, proc) -> None:
    """Minimal teardown used by the GC/exit finalizer: signal, join,
    escalate.  Must exist because the service process is non-daemonic —
    multiprocessing's own atexit hook JOINS non-daemon children, so a
    service leaked without close() would deadlock interpreter exit;
    ``weakref.finalize`` callbacks run before that hook (atexit is LIFO
    and multiprocessing registers first, at import)."""
    from apex_tpu.data._producer import reap_process

    stop.set()
    reap_process(proc, 10.0, what="data-service process")


def _service_worker(factory: Callable, consumed_samples: int, q,
                    stop, parent_pid: int) -> None:
    """Loader-process main: build the loader, stream batches + their
    post-delivery consumed_samples forever; relay errors; honor stop.

    The service process is deliberately NON-daemonic (a daemonic process
    may not spawn children, which would forbid the documented
    ``ImageFolderLoader(backend="process")`` factory), so it watches for
    orphanhood itself: when the parent dies without a clean ``close()``
    (SIGKILL), the ppid changes and the worker exits instead of living
    on as a detached loader."""
    import os

    def orphaned() -> bool:
        return os.getppid() != parent_pid

    loader = None
    try:
        loader = factory(consumed_samples)
        meta = (int(loader.local_batch), int(loader.dp))
        q.put(("meta", meta))
        while not (stop.is_set() or orphaned()):
            delivered_any = False
            for batch in loader:
                delivered_any = True
                while not (stop.is_set() or orphaned()):
                    try:
                        q.put(("batch", batch), timeout=0.2)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set() or orphaned():
                    return
            if not delivered_any:
                # a loader that yields nothing would spin this loop hot
                q.put(("error", RuntimeError(
                    "DataService loader yielded no batches")))
                return
    except BaseException as e:  # noqa: BLE001 — relayed, not eaten
        # Pre-test picklability HERE: mp.Queue.put pickles later, in the
        # feeder thread — an unpicklable exception would be dropped
        # silently there, never raising at this put() call.
        import pickle

        try:
            pickle.dumps(e)
        except Exception:
            e = RuntimeError(repr(e))  # degrade to its repr
        q.put(("error", e))
    finally:
        close = getattr(loader, "close", None)
        if callable(close):
            close()


class DataService:
    """Run a loader in a dedicated process; iterate its batches here.

    ``factory(consumed_samples) -> loader`` builds the loader inside the
    service process (so the decode pool, memmaps and samplers never live
    in the trainer).  ``depth`` bounds the inter-process queue — the
    double-buffer window between loader and trainer.  ``start_method``
    defaults to ``spawn`` (a forked child inheriting XLA's threads can
    deadlock).

    The service exposes the loader resume surface (``local_batch``,
    ``dp``, ``consumed_samples``) read from a startup handshake, so
    ``prefetch_to_device`` and ``CheckpointManager`` compose exactly as
    with an in-process loader.
    """

    def __init__(self, factory: Callable, *, consumed_samples: int = 0,
                 depth: int = 4, start_method: str = "spawn"):
        import multiprocessing as mp
        import os

        self._ctx = mp.get_context(start_method)
        self._queue = self._ctx.Queue(maxsize=max(1, depth))
        self._stop = self._ctx.Event()
        self._consumed0 = consumed_samples
        self._delivered = 0
        self._meta: Optional[tuple] = None
        self._closed = False
        # NON-daemonic: a daemonic process may not have children, which
        # would forbid factories that build process-backend loaders (the
        # documented composition).  Orphan safety comes from the
        # worker's own ppid watchdog (see _service_worker).
        self._proc = self._ctx.Process(
            target=_service_worker,
            args=(factory, consumed_samples, self._queue, self._stop,
                  os.getpid()),
            daemon=False, name="apex-data-service")
        self._proc.start()
        import weakref

        self._finalizer = weakref.finalize(
            self, _shutdown_service, self._stop, self._proc)

    # -- handshake / resume surface ------------------------------------

    def _ensure_meta(self, timeout: float = 120.0) -> tuple:
        if self._meta is None:
            kind, payload = self._get(timeout)
            if kind == "error":
                raise payload
            if kind != "meta":
                raise RuntimeError(
                    f"DataService handshake got {kind!r} before meta")
            self._meta = payload
        return self._meta

    @property
    def local_batch(self) -> int:
        return self._ensure_meta()[0]

    @property
    def dp(self) -> int:
        return self._ensure_meta()[1]

    @property
    def consumed_samples(self) -> int:
        """GLOBAL samples in batches delivered to THIS process."""
        return (self._consumed0
                + self._delivered * self.local_batch * self.dp)

    # -- stream ---------------------------------------------------------

    def _get(self, timeout: float):
        import queue as q_mod

        deadline = None if timeout is None else timeout
        try:
            return self._queue.get(timeout=deadline)
        except q_mod.Empty:
            if not self._proc.is_alive():
                raise RuntimeError(
                    "DataService loader process died without relaying an "
                    f"error (exitcode {self._proc.exitcode})") from None
            raise

    def __iter__(self) -> "DataService":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        self._ensure_meta()
        while True:
            try:
                kind, payload = self._get(timeout=5.0)
            except queue_mod.Empty:
                continue  # slow loader; the process is alive, keep waiting
            if kind == "error":
                raise payload
            self._delivered += 1
            return payload

    # -- shutdown -------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the loader process (idempotent): signal, drain, join;
        escalate to terminate/kill if it does not exit in ``timeout``."""
        if self._closed:
            return
        self._closed = True
        self._finalizer.detach()  # close() supersedes the exit guard
        self._stop.set()
        # drain so a child blocked on a full queue can see the stop flag
        try:
            while True:
                self._queue.get_nowait()
        except Exception:
            pass
        from apex_tpu.data._producer import reap_process

        reap_process(self._proc, timeout, what="data-service process")
        self._queue.close()

    def __enter__(self) -> "DataService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort backstop
        try:
            self.close()
        except Exception:
            pass
