"""ctypes loader for the native JPEG decode kernel (``_native/jpegdec.c``).

Compiled lazily with the system ``cc`` and linked against the system
libjpeg (same build pattern as :mod:`apex_tpu.utils.flatten`); every entry
point degrades cleanly — :func:`native_available` is False when there is
no compiler or no libjpeg, and :func:`decode_crop_resize` returns ``None``
on any per-image decode failure (corrupt file, CMYK, ...) so the caller
can fall back to PIL for that image.

This is the decode stage of the input pipeline the reference recipe gets
from DataLoader workers + DALI (``examples/imagenet/main_amp.py:207-232``);
see ``jpegdec.c`` for what the kernel fuses.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["native_available", "jpeg_dims", "decode_crop_resize"]

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:  # lock-free fast path
        return _LIB
    with _LOCK:
        if _TRIED:
            return _LIB
        try:
            from apex_tpu._native.build import build_and_load

            lib = build_and_load("jpegdec.c", "libjpegdec.so", ["-ljpeg"])
            if lib is not None:
                # inside the except: a stale .so missing the symbols must
                # degrade to PIL, not raise out of the loader constructor
                lib.jpegdec_dims.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t,
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_int)]
                lib.jpegdec_dims.restype = ctypes.c_int
                lib.jpegdec_decode_crop_resize.argtypes = [
                    ctypes.c_char_p, ctypes.c_size_t,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_void_p]
                lib.jpegdec_decode_crop_resize.restype = ctypes.c_int
        except Exception:
            lib = None
        _LIB = lib
        _TRIED = True
        return _LIB


def native_available() -> bool:
    """True when the kernel compiled and loaded (cc + libjpeg present)."""
    return _build_and_load() is not None


def jpeg_dims(data: bytes) -> Optional[Tuple[int, int]]:
    """Header-only ``(height, width)`` of a JPEG byte string, or ``None``
    when the native kernel is unavailable or the header does not parse."""
    lib = _build_and_load()
    if lib is None:
        return None
    h = ctypes.c_int()
    w = ctypes.c_int()
    if lib.jpegdec_dims(data, len(data), ctypes.byref(h),
                        ctypes.byref(w)) != 0:
        return None
    return h.value, w.value


def decode_crop_resize(data: bytes, cy: int, cx: int, ch: int, cw: int,
                       out_h: int, out_w: int, hflip: bool = False
                       ) -> Optional[np.ndarray]:
    """Decode + crop (full-res source coords) + bilinear resize in one
    native call -> uint8 HWC ``(out_h, out_w, 3)``, or ``None`` on any
    failure (caller falls back to PIL).  The decode runs at the smallest
    M/8 DCT scale that still covers the output size."""
    lib = _build_and_load()
    if lib is None:
        return None
    out = np.empty((out_h, out_w, 3), np.uint8)
    rc = lib.jpegdec_decode_crop_resize(
        data, len(data), int(cy), int(cx), int(ch), int(cw),
        int(out_h), int(out_w), int(bool(hflip)),
        out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        return None
    return out
