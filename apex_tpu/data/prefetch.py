"""Device-transfer prefetch: overlap host->device copies with compute.

Behavioral spec: the reference's ``data_prefetcher``
(``examples/imagenet/main_amp.py:256-276``) — batch N+1's H2D copy runs
on a side CUDA stream while the model computes on batch N, so the copy
never sits on the step's critical path.

The TPU redesign needs no stream machinery: ``jax.device_put`` is
*asynchronous* — it returns immediately with arrays whose transfers are
in flight, and any computation consuming them is sequenced after the
copy by the runtime.  Keeping ``depth`` batches in a small queue
therefore issues batch N+k's transfer while step N runs; by the time
the train loop asks for the next batch, its bytes are already on the
chip (uint8, so 4x less traffic than fp32 — ``normalize_on_device``
upcasts inside the jitted step).

Composes with :class:`~apex_tpu.data.image_folder.ImageFolderLoader`'s
decode prefetch: decode overlaps on the thread pool, transfer overlaps
on the device queue, and the step loop only ever blocks if *both*
pipelines fall behind.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Optional

__all__ = ["prefetch_to_device"]


class DevicePrefetcher:
    """Iterator over device-placed batches; see :func:`prefetch_to_device`.

    ``consumed_samples`` (available when the wrapped source exposes its
    own ``consumed_samples`` — e.g. :class:`ImageFolderLoader`) is the
    checkpoint-correct resume point: the source's count *minus* the
    batches sitting undelivered in the device queue.  The source alone
    over-counts while the wrapper runs ahead, so checkpoint this
    wrapper's value, not the loader's, and re-wrap a fresh loader from
    it after restore.
    """

    def __init__(self, source, place: Optional[Callable], depth: int,
                 mesh=None):
        self._source = source
        self._it = iter(source)
        self._place = place  # None: resolved lazily at first __next__
        self._mesh = mesh
        self._depth = max(0, depth)
        self._queue: deque = deque()

    def _resolve_place(self) -> Callable:
        # Deferred to first use so `prefetch_to_device(it)` constructed
        # *before* initialize_model_parallel() still picks up dp sharding
        # once iteration starts.
        import jax

        from apex_tpu.parallel import distributed as dist
        from apex_tpu.parallel import mesh as mesh_lib

        if (self._mesh is not None
                or mesh_lib.model_parallel_is_initialized()):
            mesh = self._mesh
            return lambda b: dist.dp_shard_batch(b, mesh)
        return jax.device_put

    @property
    def in_flight(self) -> int:
        """Batches placed on device but not yet delivered to the caller."""
        return len(self._queue)

    @property
    def consumed_samples(self) -> int:
        src = getattr(self._source, "consumed_samples", None)
        if src is None:
            raise AttributeError(
                "the wrapped source has no consumed_samples; wrap an "
                "ImageFolderLoader (not a plain iterator) for resume "
                "bookkeeping")
        per_batch = self._source.local_batch * self._source.dp
        return src - self.in_flight * per_batch

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        if self._place is None:
            self._place = self._resolve_place()
        while len(self._queue) < self._depth + 1:
            nxt = next(self._it, None)
            if nxt is None:
                break
            self._queue.append(self._place(nxt))
        if not self._queue:
            raise StopIteration
        return self._queue.popleft()


def prefetch_to_device(iterator: Iterable, mesh=None, depth: int = 2,
                       place: Optional[Callable] = None) -> DevicePrefetcher:
    """Yield batches from ``iterator`` already placed on device,
    ``depth`` transfers ahead of the consumer.

    ``place`` maps a host batch to device arrays; the default shards the
    leading dim over the data-parallel axes via
    :func:`apex_tpu.parallel.dp_shard_batch` when a ``mesh`` is given
    (or one is initialized), else a plain ``jax.device_put``.

    ``depth=0`` degenerates to ``map(place, iterator)``.  For exact
    mid-epoch resume, checkpoint the returned wrapper's
    ``consumed_samples`` (NOT the loader's own, which runs ahead by the
    in-flight window) and rebuild loader + wrapper from it after
    restore.

    The default placement is resolved at *first iteration*, not at
    construction, so wrapping before ``initialize_model_parallel()``
    still shards over the mesh that exists when batches start flowing.
    """
    return DevicePrefetcher(iterator, place, depth, mesh=mesh)
