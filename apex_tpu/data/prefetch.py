"""Device-transfer prefetch: overlap host->device copies with compute.

Behavioral spec: the reference's ``data_prefetcher``
(``examples/imagenet/main_amp.py:256-276``) — batch N+1's H2D copy runs
on a side CUDA stream while the model computes on batch N, so the copy
never sits on the step's critical path.

The TPU redesign needs no stream machinery: ``jax.device_put`` is
*asynchronous* — it returns immediately with arrays whose transfers are
in flight, and any computation consuming them is sequenced after the
copy by the runtime.  Keeping ``depth`` batches in a small queue
therefore issues batch N+k's transfer while step N runs; by the time
the train loop asks for the next batch, its bytes are already on the
chip (uint8, so 4x less traffic than fp32 — ``normalize_on_device``
upcasts inside the jitted step).

Composes with :class:`~apex_tpu.data.image_folder.ImageFolderLoader`'s
decode prefetch: decode overlaps on the thread pool, transfer overlaps
on the device queue, and the step loop only ever blocks if *both*
pipelines fall behind.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator, Optional

__all__ = ["prefetch_to_device"]


def prefetch_to_device(iterator: Iterable, mesh=None, depth: int = 2,
                       place: Optional[Callable] = None) -> Iterator:
    """Yield batches from ``iterator`` already placed on device,
    ``depth`` transfers ahead of the consumer.

    ``place`` maps a host batch to device arrays; the default shards the
    leading dim over the data-parallel axes via
    :func:`apex_tpu.parallel.dp_shard_batch` when a ``mesh`` is given
    (or one is initialized), else a plain ``jax.device_put``.

    ``depth=0`` degenerates to ``map(place, iterator)``.  The wrapped
    iterator is advanced ``depth`` batches ahead — wrap the *device*
    side of a resumable loader, and checkpoint the loader's own
    ``consumed_samples`` only at step boundaries minus the in-flight
    window, or simply re-wrap after restore (the underlying loader
    rewinds abandoned in-flight batches itself).
    """
    import jax

    from apex_tpu.parallel import distributed as dist
    from apex_tpu.parallel import mesh as mesh_lib

    if place is None:
        if mesh is not None or mesh_lib.model_parallel_is_initialized():
            place = lambda b: dist.dp_shard_batch(b, mesh)  # noqa: E731
        else:
            place = jax.device_put

    it = iter(iterator)
    queue: deque = deque()
    while True:
        while len(queue) < max(0, depth) + 1:
            nxt = next(it, None)
            if nxt is None:
                break
            queue.append(place(nxt))
        if not queue:
            return
        yield queue.popleft()
