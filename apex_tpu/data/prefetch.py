"""Device-transfer prefetch: overlap host->device copies with compute.

Behavioral spec: the reference's ``data_prefetcher``
(``examples/imagenet/main_amp.py:256-276``) — batch N+1's H2D copy runs
on a side CUDA stream while the model computes on batch N, so the copy
never sits on the step's critical path.

The TPU redesign needs no stream machinery: ``jax.device_put`` is
*asynchronous* — it returns immediately with arrays whose transfers are
in flight, and any computation consuming them is sequenced after the
copy by the runtime.  What DOES sit on the critical path is the *host*
side of ``next(source)`` — decode/gather time the old single-queue
design paid inside the consumer's ``__next__``.  The double-buffered
form runs a dedicated transfer thread: it pulls host batches from the
source and issues their ``device_put``/``dp_shard_batch`` into a bounded
queue, so while step N computes, batch N+1's transfer is already in
flight *and* the source's own decode pool is filling batch N+2 — the
three pipeline layers (decode, H2D, compute) overlap pairwise, and the
consumer only blocks when ALL of them fall behind.

That residual block is the **stall** — the one number that says whether
the input pipeline feeds the chip.  Every ``__next__`` records it:
``data/stall_ms`` gauge (last step) and ``span_ms/data/next_wait``
histogram in the default :class:`~apex_tpu.observability.metrics.
MetricRegistry`, under a ``jax.profiler.TraceAnnotation`` so captured
traces show the wait as a range (docs/observability.md catalog).

Composition contract (enforced): wrap a **loader** (``ImageFolderLoader``
/ ``PackedLoader`` / ``PackedSequenceLoader`` / ``DataService``) directly
— nothing in between — and checkpoint the *wrapper's*
``consumed_samples``.  Wrapping another :class:`DevicePrefetcher` (or any
wrapper without the loader resume surface) raises immediately rather
than mis-counting ``local_batch * dp`` from the wrong layer.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional

__all__ = ["DevicePrefetcher", "prefetch_to_device"]


class _End:
    """Exhaustion sentinel — distinct from any source item, so a source
    legitimately yielding ``None`` is delivered, not dropped (the old
    ``next(it, None)`` conflation)."""


class _Error:
    """Exception relay from the transfer thread to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher:
    """Iterator over device-placed batches; see :func:`prefetch_to_device`.

    ``consumed_samples`` is the checkpoint-correct resume point: samples
    in batches already **delivered to the caller** — tracked directly as
    ``consumed_at_construction + delivered_batches * (local_batch * dp)``
    so a concurrent transfer thread can never skew it (the source's own
    count runs ahead by the in-flight window).  Checkpoint this wrapper's
    value, not the loader's, and re-wrap a fresh loader from it after
    restore.

    Resource contract: ``close()`` (or the context manager) stops the
    transfer thread, closes the source iterator, **closes the source
    loader** (pass-through — the decode pool does not live until
    ``__del__``), and rewinds the source's samplers past any batches
    pulled but never delivered (``rewind_batches``), so after ``close()``
    the source's ``consumed_samples`` agrees with the wrapper's.
    """

    def __init__(self, source, place: Optional[Callable], depth: int,
                 mesh=None, registry=None):
        if isinstance(source, DevicePrefetcher):
            raise TypeError(
                "prefetch_to_device(prefetch_to_device(...)): nested "
                "device prefetchers are unsupported — the wrapper reads "
                "local_batch/dp from its source for resume bookkeeping, "
                "which a second wrapper layer would mis-count.  Compose "
                "as loader -> prefetch_to_device, nothing in between.")
        self._source = source
        self._it = iter(source)
        self._place = place  # None: resolved lazily at first batch
        self._mesh = mesh
        self._depth = max(0, depth)
        self._registry = registry
        self._lock = threading.Lock()
        self._delivered = 0   # batches handed to the caller
        self._pulled = 0      # batches taken from the source iterator
        self._consumed0 = getattr(source, "consumed_samples", None)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exhausted = False
        self._closed = False

    # -- resume bookkeeping -------------------------------------------

    def _per_batch(self) -> int:
        try:
            return self._source.local_batch * self._source.dp
        except AttributeError:
            raise AttributeError(
                "the wrapped source has no local_batch/dp; wrap a loader "
                "(ImageFolderLoader/PackedLoader/PackedSequenceLoader/"
                "DataService) directly — composition order is "
                "loader -> prefetch_to_device, nothing in between") \
                from None

    @property
    def in_flight(self) -> int:
        """Batches pulled from the source but not yet delivered
        (queued on device or mid-placement).  When the source exposes
        ``consumed_samples``, derived as
        ``(source.consumed - wrapper.consumed) / per_batch`` — the
        source's count is updated inside its own yield, so deriving from
        it (rather than the wrapper's ``_pulled``, incremented a moment
        later) keeps ``source == wrapper + in_flight`` an identity at
        any instant, and survives a close() whose thread join timed
        out."""
        src = getattr(self._source, "consumed_samples", None)
        if src is not None:
            try:
                per = self._per_batch()
            except AttributeError:
                per = None
            if per:
                with self._lock:
                    mine = self._consumed0 + self._delivered * per
                return max(0, (src - mine) // per)
        with self._lock:
            return self._pulled - self._delivered

    @property
    def consumed_samples(self) -> int:
        if self._consumed0 is None:
            raise AttributeError(
                "the wrapped source has no consumed_samples; wrap a "
                "loader (not a plain iterator) for resume bookkeeping — "
                "composition order is loader -> prefetch_to_device, "
                "nothing in between")
        with self._lock:
            return self._consumed0 + self._delivered * self._per_batch()

    # -- placement -----------------------------------------------------

    def _resolve_place(self) -> Callable:
        # Deferred to first use so `prefetch_to_device(it)` constructed
        # *before* initialize_model_parallel() still picks up dp sharding
        # once iteration starts.
        import jax

        from apex_tpu.parallel import distributed as dist
        from apex_tpu.parallel import mesh as mesh_lib

        if (self._mesh is not None
                or mesh_lib.model_parallel_is_initialized()):
            mesh = self._mesh
            return lambda b: dist.dp_shard_batch(b, mesh)
        return jax.device_put

    # -- transfer thread ----------------------------------------------

    def _pull_and_place(self):
        """One source pull + device placement; returns the queue item."""
        try:
            item = next(self._it)
        except StopIteration:
            return _End()
        except BaseException as e:  # noqa: BLE001 — relayed, not eaten
            return _Error(e)
        with self._lock:
            self._pulled += 1
        try:
            return self._place(item)
        except BaseException as e:  # noqa: BLE001
            return _Error(e)

    def _run(self) -> None:
        while not self._stop.is_set():
            out = self._pull_and_place()
            final = isinstance(out, (_End, _Error))
            while not self._stop.is_set():
                try:
                    self._queue.put(out, timeout=0.2)
                    break
                except queue.Full:
                    continue
            if final:
                return

    # -- iterator ------------------------------------------------------

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        if self._exhausted or self._closed:
            raise StopIteration
        if self._place is None:
            self._place = self._resolve_place()
        if self._depth == 0:
            # degenerate synchronous mode: map(place, source)
            out = self._pull_and_place()
        else:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="apex-device-prefetch",
                    daemon=True)
                self._thread.start()
            out = self._get_with_stall()
        if isinstance(out, _End):
            self._exhausted = True
            raise StopIteration
        if isinstance(out, _Error):
            self._exhausted = True
            raise out.exc
        with self._lock:
            self._delivered += 1
        return out

    def _get_with_stall(self):
        """Blocking queue pop, measured: the time the consumer waits here
        is the pipeline's *stall* — the step-time cost of the input path
        after every overlap has done its work.  Poll-with-timeout rather
        than a bare blocking get (the ProducerLoader._finish discipline):
        a concurrent ``close()`` from a watchdog/preemption thread must
        wake a consumer already parked here, not leave it blocked
        forever on a queue nobody will fill."""
        import jax

        if self._registry is None:
            from apex_tpu.observability.metrics import default_registry

            self._registry = default_registry()
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("apex/data/next_wait"):
            while True:
                try:
                    out = self._queue.get(timeout=0.5)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        out = _End()
                        break
        stall_ms = (time.perf_counter() - t0) * 1e3
        self._registry.gauge("data/stall_ms").set(stall_ms)
        self._registry.histogram("span_ms/data/next_wait").observe(stall_ms)
        # Flight recorder (no-op unless armed): the same blocking wait,
        # as a timeline interval feeding the goodput ``data_stall``
        # bucket (docs/observability.md) — this is main-thread time
        # outside any step scope, so attribution stays disjoint.
        from apex_tpu.observability import timeline

        timeline.emit("data_stall", dur_s=stall_ms / 1e3)
        return out

    # -- shutdown ------------------------------------------------------

    def close(self, *, close_source: bool = True) -> None:
        """Stop the transfer thread, close the source iterator, rewind
        the source's samplers past undelivered in-flight batches (so its
        ``consumed_samples`` matches the wrapper's), and — the resource
        pass-through — close the source loader itself, releasing its
        decode pool.  Idempotent.

        ``close_source=False`` leaves the loader open (the multi-epoch
        loop shape: re-wrap the same loader for the next epoch)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            # unblock a producer waiting on a full queue
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5.0)
            self._thread = None
        # generator sources (the loaders' __iter__) rewind their OWN
        # prefetch window in their finally block when closed.  Guard:
        # for self-iterating sources (DataService, plain iterators with
        # close()), iter(source) IS the source — closing "the iterator"
        # there would close the source even under close_source=False.
        if self._it is not self._source:
            it_close = getattr(self._it, "close", None)
            if callable(it_close):
                try:
                    it_close()
                except Exception:
                    pass  # a producer stuck past the join timeout
        undelivered = self.in_flight
        rewind = getattr(self._source, "rewind_batches", None)
        if undelivered and callable(rewind):
            rewind(undelivered)
            with self._lock:
                self._pulled -= undelivered
        if close_source:
            src_close = getattr(self._source, "close", None)
            if callable(src_close):
                src_close()

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort backstop
        # does NOT close the source: a dropped (e.g. exhausted) wrapper
        # must not yank the decode pool out from under a loader the
        # caller re-wrapped for the next epoch — only an explicit
        # close() passes through
        try:
            self.close(close_source=False)
        except Exception:
            pass


def prefetch_to_device(iterator: Iterable, mesh=None, depth: int = 2,
                       place: Optional[Callable] = None,
                       registry=None) -> DevicePrefetcher:
    """Yield batches from ``iterator`` already placed on device, with a
    dedicated transfer thread keeping up to ``depth`` placed batches
    queued ahead of the consumer.

    ``place`` maps a host batch to device arrays; the default shards the
    leading dim over the data-parallel axes via
    :func:`apex_tpu.parallel.dp_shard_batch` when a ``mesh`` is given
    (or one is initialized), else a plain ``jax.device_put``.

    ``depth=0`` degenerates to ``map(place, iterator)`` (no thread).
    For exact mid-epoch resume, checkpoint the returned wrapper's
    ``consumed_samples`` (NOT the loader's own, which runs ahead by the
    in-flight window) and rebuild loader + wrapper from it after
    restore.  Composition order is enforced: wrap a loader directly —
    nesting two device prefetchers raises ``TypeError``.

    The default placement is resolved at *first iteration*, not at
    construction, so wrapping before ``initialize_model_parallel()``
    still shards over the mesh that exists when batches start flowing.

    Observability: each ``__next__`` records its blocking wait into the
    ``data/stall_ms`` gauge and the ``span_ms/data/next_wait`` histogram
    of ``registry`` (default: the process registry) — the in-run stall
    measurement ``bench.py input_pipeline`` cross-checks.
    """
    return DevicePrefetcher(iterator, place, depth, mesh=mesh,
                            registry=registry)
