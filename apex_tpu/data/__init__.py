"""Input pipelines.

The reference's examples consume ``torchvision.datasets.ImageFolder``
through a ``DataLoader`` with ``fast_collate`` and a CUDA-side
``data_prefetcher`` (``examples/imagenet/main_amp.py:48-63,207-232,256``).
This package is the TPU-native analog, layered for production rate:

- **decode** — :class:`ImageFolderLoader` with a selectable worker
  backend (``backend="process"`` — the true ``DataLoader(num_workers)``
  analog — or ``"thread"``), per-host ``dp_ranks`` index sharding, and
  uint8 batches normalized on-device inside the jitted step;
- **decode-free** — :mod:`apex_tpu.data.packed` packs the dataset once
  into a memory-mapped uint8 shard (the DALI/array_record role);
  :mod:`apex_tpu.data.sequence` is the LM twin: pre-tokenized,
  length-packed sequence shards with segment-id masks streamed into the
  GPT trainers;
- **transfer** — :func:`prefetch_to_device` double-buffers
  ``device_put``/``dp_shard_batch`` on a dedicated thread (batch N+1's
  transfer in flight while step N runs and decode fills N+2), recording
  the residual ``data/stall_ms``;
- **service** — :class:`DataService` moves the whole loader into a
  dedicated process feeding the trainer over a local queue (the
  tf.data-service split at single-host scope).

All layers carry GLOBAL ``consumed_samples`` for exact mid-epoch resume
through ``resilience.CheckpointManager``; see docs/data.md.
"""

from apex_tpu.data.image_folder import (
    ImageFolder,
    ImageFolderLoader,
    center_crop_resize,
    normalize_on_device,
    random_resized_crop,
    sample_crop_box,
    synthetic_image_batches,
)
from apex_tpu.data.packed import (
    PackedImageDataset,
    PackedLoader,
    pack_image_folder,
)
from apex_tpu.data.prefetch import DevicePrefetcher, prefetch_to_device
from apex_tpu.data.sequence import (
    PackedSequenceDataset,
    PackedSequenceLoader,
    pack_token_documents,
    segment_loss_mask,
    synthetic_token_documents,
)
from apex_tpu.data.service import DataService

__all__ = [
    "DataService",
    "DevicePrefetcher",
    "ImageFolder",
    "ImageFolderLoader",
    "PackedImageDataset",
    "PackedLoader",
    "PackedSequenceDataset",
    "PackedSequenceLoader",
    "center_crop_resize",
    "normalize_on_device",
    "pack_image_folder",
    "pack_token_documents",
    "prefetch_to_device",
    "random_resized_crop",
    "sample_crop_box",
    "segment_loss_mask",
    "synthetic_image_batches",
    "synthetic_token_documents",
]
