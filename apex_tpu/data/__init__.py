"""Input pipelines.

The reference's examples consume ``torchvision.datasets.ImageFolder``
through a ``DataLoader`` with ``fast_collate`` and a CUDA-side
``data_prefetcher`` (``examples/imagenet/main_amp.py:48-63,207-232,256``).
This package is the TPU-native analog: a pure PIL/numpy ImageFolder, DP
sharding through the Megatron samplers, threaded decode, and uint8 batches
normalized on-device inside the jitted step.

For hosts whose decode rate cannot feed the chip (the DALI situation),
:mod:`apex_tpu.data.packed` packs the dataset once into a memory-mapped
uint8 shard; training then gathers batches decode-free and augments
on-device.
"""

from apex_tpu.data.image_folder import (
    ImageFolder,
    ImageFolderLoader,
    center_crop_resize,
    normalize_on_device,
    random_resized_crop,
    sample_crop_box,
    synthetic_image_batches,
)
from apex_tpu.data.packed import (
    PackedImageDataset,
    PackedLoader,
    pack_image_folder,
)
from apex_tpu.data.prefetch import prefetch_to_device

__all__ = [
    "ImageFolder",
    "ImageFolderLoader",
    "PackedImageDataset",
    "PackedLoader",
    "pack_image_folder",
    "center_crop_resize",
    "normalize_on_device",
    "prefetch_to_device",
    "random_resized_crop",
    "sample_crop_box",
    "synthetic_image_batches",
]
