"""Packed (decode-free) image input path — pack once, memcpy at train time.

Role in the reference lineage: the apex imagenet recipe's answer to an
input-bound loader is more DataLoader workers and ultimately DALI
(``examples/imagenet/main_amp.py:207-232``; the example README points at
DALI when JPEG decode can't keep up).  Both scale *decode* horizontally.
On a TPU-VM class host the idiomatic fix is to move decode out of the
training job entirely: preprocess the dataset once into a fixed-shape
array shard (tf.data/grain's array_record pattern), then the per-step
host work is a fancy-index gather out of a memory-mapped uint8 array —
pure memcpy, no codec — and the *augmentation* runs on-device inside the
jitted train step where it fuses with the input normalize.

Measured context (bench_input_pipeline): one PIL/native-JPEG worker
decodes ~110 img/s, so a 1-CPU host can never feed the ~8.8k img/s the
single-chip RN50 step consumes; the packed path's gather costs
~150 KB/image of memcpy (~1.3 GB/s at chip rate) which the same host
sustains.

Format (``<prefix>.data`` + ``<prefix>.labels.npy`` + ``<prefix>.json``):

- ``.data``  — raw uint8, shape [N, side, side, 3] (NHWC, C-order), the
  storage layout a memmap gather turns into a training batch with one
  copy;
- ``.labels.npy`` — int32 [N];
- ``.json`` — {"n", "side", "classes", "version"} metadata.

Records are stored at ``side`` (default 232 — slightly larger than the
224 train crop) so the on-device random crop (:func:`random_crop_flip`)
retains translation augmentation; RandomResizedCrop's scale/aspect
jitter is intentionally traded away (decode-free means fixed-shape
records — the same trade DALI's fused ``decode_random_crop`` pipelines
make when fed pre-resized shards).

The producer/prefetch machinery (bounded queue, per-iteration state,
preemption + rewind contracts) lives in
:mod:`apex_tpu.data._producer` and is shared with the LM-side
:class:`~apex_tpu.data.sequence.PackedSequenceLoader`.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from apex_tpu.data._producer import ProducerLoader
from apex_tpu.data.image_folder import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ImageFolder,
    center_crop_resize,
    normalize_on_device,
)

__all__ = [
    "PackedImageDataset",
    "PackedLoader",
    "center_crop",
    "pack_image_folder",
    "random_crop_flip",
]


def pack_image_folder(root_or_dataset, out_prefix: str, side: int = 232,
                      workers: int = 8, resize: Optional[int] = None
                      ) -> "PackedImageDataset":
    """Decode an ImageFolder tree once into a packed array shard.

    Each image is center-crop-resized to ``side``x``side`` uint8 (the
    deterministic eval transform — augmentation happens on-device at
    train time) and appended to ``<out_prefix>.data``.  ``resize``
    forwards to :func:`center_crop_resize` (default: the reference's
    256/224-proportional pre-resize for ``side``); an **eval** shard
    packed at ``side == image_size`` is therefore pixel-identical to the
    online JPEG eval transform.  Decode fans out over ``workers`` PIL
    threads; packing is a one-time cost, so the online loader's native
    JPEG fast path is not plumbed through here.
    """
    from concurrent.futures import ThreadPoolExecutor

    ds = (root_or_dataset if isinstance(root_or_dataset, ImageFolder)
          else ImageFolder(root_or_dataset))
    n = len(ds)
    if n == 0:
        raise ValueError("empty dataset")
    os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)
    # raw file (not .npy): the loader memmaps with an explicit shape from
    # the sidecar json, and raw bytes keep the format trivially
    # inspectable/appendable for sharded packers.
    mm = np.memmap(out_prefix + ".data", dtype=np.uint8, mode="w+",
                   shape=(n, side, side, 3))
    labels = np.empty((n,), np.int32)

    def one(i: int) -> None:
        img, label = ds.load(i)
        mm[i] = center_crop_resize(img, side, resize)
        labels[i] = label

    with ThreadPoolExecutor(max_workers=workers) as pool:
        list(pool.map(one, range(n)))
    mm.flush()
    del mm
    np.save(out_prefix + ".labels.npy", labels)
    with open(out_prefix + ".json", "w") as f:
        json.dump({"n": n, "side": side, "classes": ds.classes,
                   "version": 1}, f)
    return PackedImageDataset(out_prefix)


class PackedImageDataset:
    """Memory-mapped view over a packed shard (see module docstring)."""

    def __init__(self, prefix: str):
        with open(prefix + ".json") as f:
            meta = json.load(f)
        if meta.get("version") != 1:
            raise ValueError(f"unknown packed format version: {meta}")
        self.side = int(meta["side"])
        self.classes = list(meta["classes"])
        self._n = int(meta["n"])
        self.images = np.memmap(prefix + ".data", dtype=np.uint8, mode="r",
                                shape=(self._n, self.side, self.side, 3))
        self.labels = np.load(prefix + ".labels.npy")
        if self.labels.shape != (self._n,):
            raise ValueError(
                f"labels shape {self.labels.shape} != ({self._n},)")

    def __len__(self) -> int:
        return self._n


class PackedLoader(ProducerLoader):
    """DP-sharded train iterator over a :class:`PackedImageDataset`.

    Same surface and contracts as
    :class:`~apex_tpu.data.image_folder.ImageFolderLoader` — yields
    ``(uint8 [B, side, side, 3], int32 [B])`` with
    ``B = local_batch * len(dp_ranks)`` and ``dp_ranks[i]``'s shard at
    rows ``[i*local : (i+1)*local]``, Megatron-sampler epoch shuffling,
    GLOBAL ``consumed_samples`` mid-epoch resume, context-manager
    ``close()``, per-host ``dp_ranks`` input sharding — so
    ``prefetch_to_device`` and the examples compose unchanged.  The
    producer is a single background thread
    (:class:`~apex_tpu.data._producer.ProducerLoader`): per batch it
    fancy-indexes the memmap (gather-memcpy, no codec), which one core
    sustains at chip rate; ``prefetch`` bounds the queue.

    Batches are full ``side``-sized records; run
    :func:`random_crop_flip` (train) or :func:`center_crop` (eval)
    on-device inside the jitted step.
    """

    def __init__(self, dataset: PackedImageDataset, local_batch: int,
                 data_parallel_size: int = 1, consumed_samples: int = 0,
                 seed: int = 0, prefetch: int = 2, dp_ranks=None):
        super().__init__(
            total_samples=len(dataset), local_batch=local_batch,
            data_parallel_size=data_parallel_size,
            consumed_samples=consumed_samples, seed=seed,
            prefetch=prefetch, dp_ranks=dp_ranks)
        self.dataset = dataset

    def _gather(self, idx_per_rank) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.concatenate(idx_per_rank)
        # single fancy-index: one gather-memcpy out of the page cache
        return (self.dataset.images[idx],
                self.dataset.labels[idx].astype(np.int32))


# ---------------------------------------------------------------------------
# On-device augmentation (jittable; fuses into the train step)
# ---------------------------------------------------------------------------

def random_crop_flip(images_u8, key, out_size: int,
                     mean=IMAGENET_MEAN, std=IMAGENET_STD,
                     dtype=None):
    """Per-example random crop + horizontal flip + normalize, on device.

    ``images_u8``: uint8 [B, S, S, 3] from :class:`PackedLoader`;
    returns normalized [B, out_size, out_size, 3] in ``dtype`` (default
    fp32).  Designed to sit first in the jitted train step: XLA fuses
    the u8->f32 convert, crop gather, flip select and normalize into the
    input of the first conv — the device-side role the reference's
    ``data_prefetcher`` normalize plays on a CUDA stream
    (``examples/imagenet/main_amp.py:256-276``), plus the crop/flip that
    its host-side transforms did before the codec trade (module
    docstring).
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu.observability.spans import named_span

    b, s = images_u8.shape[0], images_u8.shape[1]
    margin = s - out_size
    if margin < 0:
        raise ValueError(f"out_size {out_size} > stored side {s}")
    with named_span("data/augment"):
        k_h, k_w, k_f = jax.random.split(key, 3)
        off_h = jax.random.randint(k_h, (b,), 0, margin + 1)
        off_w = jax.random.randint(k_w, (b,), 0, margin + 1)
        flip = jax.random.bernoulli(k_f, 0.5, (b,))

        def one(img, oh, ow, fl):
            crop = jax.lax.dynamic_slice(img, (oh, ow, 0),
                                         (out_size, out_size, 3))
            return jnp.where(fl, crop[:, ::-1, :], crop)

        cropped = jax.vmap(one)(images_u8, off_h, off_w, flip)
        # same arithmetic as the online path so --packed is not a numerics
        # A/B confounder
        return normalize_on_device(cropped, mean, std, dtype)


def center_crop(images_u8, out_size: int, mean=IMAGENET_MEAN,
                std=IMAGENET_STD, dtype=None):
    """Deterministic eval transform: center crop + normalize, on device."""
    s = images_u8.shape[1]
    off = (s - out_size) // 2
    if off < 0:
        raise ValueError(f"out_size {out_size} > stored side {s}")
    crop = images_u8[:, off:off + out_size, off:off + out_size, :]
    return normalize_on_device(crop, mean, std, dtype)
