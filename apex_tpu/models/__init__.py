"""apex_tpu.models — reference models for the example/benchmark workloads.

Mirrors the reference's app layer (``examples/imagenet``, ``examples/simple``,
``apex/transformer/testing/standalone_{gpt,bert}.py``): a ResNet family for
the imagenet O2 slice, and standalone GPT/BERT for the transformer runtime.
"""

from apex_tpu.models.resnet import ResNet, ResNet18, ResNet50, ResNet101  # noqa: F401
