"""ResNet (NHWC, TPU-native) — the ``examples/imagenet`` workload model.

The reference trains torchvision ResNet-50 under amp O2 + apex DDP +
optional SyncBatchNorm (``examples/imagenet/main_amp.py:107-160``).  This is
the equivalent model family built TPU-first:

- NHWC layout throughout (TPU conv layout; the reference's
  ``--channels-last`` fast path is the default here);
- :class:`apex_tpu.parallel.SyncBatchNorm` as the norm layer, with
  ``axis_name=None`` degrading to plain BN for single-replica runs —
  the ``convert_syncbn_model`` decision (``apex/parallel/__init__.py:14-58``)
  becomes a constructor flag;
- the Bottleneck block fuses BN+ReLU epilogues (``fuse_relu=True``) and the
  residual add into the last BN (``z=residual``) — the capability of
  ``apex/contrib/bottleneck`` / ``groupbn`` BN-Add-ReLU expressed as module
  composition that XLA fuses.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["ResNet", "ResNet18", "ResNet50", "ResNet101"]


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(SyncBatchNorm, axis_name=self.axis_name)

        y = conv(self.features, (3, 3), (self.strides, self.strides))(x)
        y = bn(self.features, fuse_relu=True)(y, use_running_average=not train)
        y = conv(self.features, (3, 3))(y)
        if residual.shape != y.shape:
            residual = conv(self.features, (1, 1), (self.strides, self.strides),
                            name="conv_proj")(x)
            residual = bn(self.features, name="bn_proj")(
                residual, use_running_average=not train
            )
        # BN + residual-add + ReLU fused epilogue
        return bn(self.features, fuse_relu=True)(
            y, z=residual, use_running_average=not train
        )


class BottleneckBlock(nn.Module):
    features: int  # bottleneck width; output is 4*features
    strides: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(SyncBatchNorm, axis_name=self.axis_name)
        out_feats = self.features * 4

        y = conv(self.features, (1, 1))(x)
        y = bn(self.features, fuse_relu=True)(y, use_running_average=not train)
        y = conv(self.features, (3, 3), (self.strides, self.strides))(y)
        y = bn(self.features, fuse_relu=True)(y, use_running_average=not train)
        y = conv(out_feats, (1, 1))(y)
        if residual.shape != y.shape:
            residual = conv(out_feats, (1, 1), (self.strides, self.strides),
                            name="conv_proj")(x)
            residual = bn(out_feats, name="bn_proj")(
                residual, use_running_average=not train
            )
        return bn(out_feats, fuse_relu=True)(
            y, z=residual, use_running_average=not train
        )


class ResNet(nn.Module):
    """Generic ResNet; ``stage_sizes`` and ``block_cls`` select the variant.

    ``axis_name="dp"`` enables cross-replica SyncBatchNorm (the
    ``--sync_bn`` flag of ``examples/imagenet/main_amp.py:42,131``).
    """

    stage_sizes: Sequence[int]
    block_cls: Any
    num_classes: int = 1000
    num_filters: int = 64
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = SyncBatchNorm(
            self.num_filters, axis_name=self.axis_name, fuse_relu=True,
            name="bn_init",
        )(x, use_running_average=not train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    axis_name=self.axis_name,
                    dtype=self.dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            jnp.asarray(x, jnp.float32)
        )
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
