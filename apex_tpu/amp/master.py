"""FP32 master weights for half-precision training (the O2 mechanism).

Reference: amp lazily builds fp32 master copies of every fp16 param and
rewires the optimizer to step on the masters, then copies master→model after
each step (``apex/amp/_process_optimizer.py:28-159``,
``lazy_init_with_master_weights``; copy-back ``:349-364`` via
``multi_tensor_scale``).  The legacy path is ``FP16_Optimizer``
(``apex/fp16_utils/fp16_optimizer.py:13``) with ``prep_param_lists``
(``fp16util.py:92``).

JAX redesign: masters are just another pytree.  The train step computes grads
w.r.t. the half *model* params, unscales them to fp32, steps the optimizer on
the fp32 *master* params, and re-derives the model params by casting.  XLA
fuses the cast into the update; with buffer donation the half params are
updated in place, so the memory cost is the same as the reference's
(half model + fp32 master + fp32 optimizer state).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

__all__ = ["MasterWeights", "make_master", "master_to_model"]


class MasterWeights(NamedTuple):
    """fp32 master params paired with the dtype to derive model params in.

    Registered as a pytree with ``model_dtype`` as static aux data so the
    whole structure can be carried through jit (a dtype is not an array
    leaf).
    """

    params: Any  # fp32 pytree
    model_dtype: Any


jax.tree_util.register_pytree_node(
    MasterWeights,
    lambda mw: ((mw.params,), jnp.dtype(mw.model_dtype)),
    lambda aux, children: MasterWeights(params=children[0], model_dtype=aux),
)


def make_master(model_params) -> MasterWeights:
    """Create fp32 masters from (possibly half) model params.

    Analog of ``prep_param_lists`` (``apex/fp16_utils/fp16util.py:92-135``):
    every float leaf gets an fp32 clone; the model dtype is remembered for the
    copy-back direction.
    """
    leaves = jax.tree_util.tree_leaves(model_params)
    float_leaves = [
        x for x in leaves if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    model_dtype = (
        jnp.asarray(float_leaves[0]).dtype if float_leaves else jnp.float32
    )
    masters = jax.tree_util.tree_map(
        lambda x: (
            jnp.asarray(x, jnp.float32)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else x
        ),
        model_params,
    )
    return MasterWeights(params=masters, model_dtype=model_dtype)


def master_to_model(master: MasterWeights):
    """Derive model params from masters (``_master_params_to_model_params``,
    ``apex/amp/_process_optimizer.py:14-25``)."""
    return jax.tree_util.tree_map(
        lambda x: (
            jnp.asarray(x, master.model_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else x
        ),
        master.params,
    )
