"""amp frontend — the ``amp.initialize`` analog, functional style.

``amp.initialize(model, optimizer, opt_level=...)``
(``apex/amp/frontend.py:197``) returns mutated model+optimizer.  The
functional equivalent bundles the pieces a train step needs — policy, scaler,
master weights — into an :class:`AmpState` the user threads through jit.

Typical use::

    amp_conf, amp_state = amp.initialize(params, opt_level="O2",
                                         half_dtype=jnp.float16)

    @jax.jit
    def train_step(amp_state, batch):
        model_params = amp.master_to_model(amp_state.master)  # half params
        def loss_fn(p):
            out = model.apply(amp_conf.policy.cast_to_compute(p), batch)
            return loss(out)
        scaled = lambda p: amp.scale_loss(loss_fn(p), amp_state.scaler)
        grads = jax.grad(scaled)(model_params)
        finite = amp.all_finite(grads)
        grads32 = amp_conf.loss_scaler.unscale(grads, amp_state.scaler)
        ... optimizer step on amp_state.master.params with grads32,
            predicated on `finite` ...
        new_scaler = amp_conf.loss_scaler.update(amp_state.scaler, finite)

State-dict helpers mirror ``amp.state_dict/load_state_dict``
(``apex/amp/frontend.py:365-404``) for checkpoint parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Union

import jax.numpy as jnp

from apex_tpu.amp.master import MasterWeights, make_master
from apex_tpu.amp.policy import Policy, policy as make_policy
from apex_tpu.amp.scaler import (
    DynamicLossScale,
    LossScaleState,
    NoOpLossScale,
    StaticLossScale,
)

__all__ = ["AmpConfig", "AmpState", "initialize", "state_dict", "load_state_dict"]


@dataclasses.dataclass(frozen=True)
class AmpConfig:
    """Static (non-pytree) side of amp: the policy and scaler algorithm."""

    policy: Policy
    loss_scaler: Union[DynamicLossScale, StaticLossScale, NoOpLossScale]


class AmpState(NamedTuple):
    """Dynamic (pytree) side: scaler counters and optional master weights."""

    scaler: LossScaleState
    master: Optional[MasterWeights]


def initialize(
    params=None,
    opt_level: str = "O1",
    half_dtype=jnp.bfloat16,
    *,
    loss_scale: Union[str, float, None] = None,
    num_losses: int = 1,
    **policy_overrides,
):
    """Build amp config+state from an opt level.

    Mirrors ``amp.initialize`` keyword semantics
    (``apex/amp/frontend.py:197-264``): ``loss_scale`` overrides the preset
    ("dynamic" or a float); ``num_losses > 1`` gives each loss its own
    scaler state (the reference's per-loss ``LossScaler`` list,
    ``_initialize.py:229-233``) — ``AmpState.scaler`` is then a tuple,
    index it per loss for ``scale_loss``/``update``; other :class:`Policy`
    fields can be overridden by keyword.  Returns ``(AmpConfig,
    AmpState)``; if ``params`` is given and the policy uses master weights,
    ``AmpState.master`` holds fp32 masters and the caller should derive
    model params via :func:`apex_tpu.amp.master_to_model`.
    """
    if num_losses < 1:
        raise ValueError(f"num_losses must be >= 1, got {num_losses}")
    pol = make_policy(opt_level, half_dtype)
    if loss_scale is not None:
        pol = pol.with_options(loss_scale=loss_scale)
    if policy_overrides:
        pol = pol.with_options(**policy_overrides)

    if pol.loss_scale == "dynamic":
        scaler_algo: Any = DynamicLossScale()
    elif pol.loss_scale is None:
        scaler_algo = NoOpLossScale()
    else:
        scaler_algo = StaticLossScale(float(pol.loss_scale))

    master = None
    if params is not None and pol.master_weights:
        master = make_master(pol.cast_to_param(params))

    scaler_state = (scaler_algo.init() if num_losses == 1
                    else tuple(scaler_algo.init()
                               for _ in range(num_losses)))
    return AmpConfig(policy=pol, loss_scaler=scaler_algo), AmpState(
        scaler=scaler_state, master=master
    )


def _one_state_dict(s: LossScaleState) -> dict:
    return {
        "loss_scale": s.scale,
        "growth_tracker": s.growth_tracker,
        "hysteresis_tracker": s.hysteresis_tracker,
        "found_inf": s.found_inf,
    }


def _one_load(sd: dict) -> LossScaleState:
    return LossScaleState(
        scale=jnp.float32(sd["loss_scale"]),
        growth_tracker=jnp.int32(sd["growth_tracker"]),
        hysteresis_tracker=jnp.int32(sd["hysteresis_tracker"]),
        found_inf=jnp.asarray(sd["found_inf"]),
    )


def state_dict(state: AmpState):
    """Checkpointable scaler state (``amp.state_dict``,
    ``apex/amp/frontend.py:365-375``); a list of dicts when
    ``num_losses > 1`` (the reference serializes its scaler list the same
    way)."""
    if not isinstance(state.scaler, LossScaleState):  # per-loss tuple
        return [_one_state_dict(s) for s in state.scaler]
    return _one_state_dict(state.scaler)


def load_state_dict(state: AmpState, sd) -> AmpState:
    """Restore scaler state (``amp.load_state_dict``,
    ``apex/amp/frontend.py:377-404``).

    Scaler-count mismatches (checkpoint saved with a different
    ``num_losses``) follow the reference's resume semantics
    (``apex/amp/frontend.py:394``): load the overlapping prefix and warn —
    extra saved scalers are dropped, missing ones keep their fresh state —
    rather than refusing the checkpoint."""
    saved = list(sd) if isinstance(sd, (list, tuple)) else [sd]
    current = (list(state.scaler)
               if not isinstance(state.scaler, LossScaleState)
               else [state.scaler])
    if len(saved) != len(current):
        import warnings

        warnings.warn(
            f"amp.load_state_dict: checkpoint has {len(saved)} loss "
            f"scaler(s) but state expects {len(current)} (saved with a "
            "different num_losses); loading the overlapping prefix "
            "(reference behavior, apex/amp/frontend.py:394)")
    loaded = [_one_load(d) for d in saved[: len(current)]]
    loaded += current[len(loaded):]
    if not isinstance(state.scaler, LossScaleState):
        return state._replace(scaler=tuple(loaded))
    return state._replace(scaler=loaded[0])
