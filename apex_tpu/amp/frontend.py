"""amp frontend — the ``amp.initialize`` analog, functional style.

``amp.initialize(model, optimizer, opt_level=...)``
(``apex/amp/frontend.py:197``) returns mutated model+optimizer.  The
functional equivalent bundles the pieces a train step needs — policy, scaler,
master weights — into an :class:`AmpState` the user threads through jit.

Typical use::

    amp_conf, amp_state = amp.initialize(params, opt_level="O2",
                                         half_dtype=jnp.float16)

    @jax.jit
    def train_step(amp_state, batch):
        model_params = amp.master_to_model(amp_state.master)  # half params
        def loss_fn(p):
            out = model.apply(amp_conf.policy.cast_to_compute(p), batch)
            return loss(out)
        scaled = lambda p: amp.scale_loss(loss_fn(p), amp_state.scaler)
        grads = jax.grad(scaled)(model_params)
        finite = amp.all_finite(grads)
        grads32 = amp_conf.loss_scaler.unscale(grads, amp_state.scaler)
        ... optimizer step on amp_state.master.params with grads32,
            predicated on `finite` ...
        new_scaler = amp_conf.loss_scaler.update(amp_state.scaler, finite)

State-dict helpers mirror ``amp.state_dict/load_state_dict``
(``apex/amp/frontend.py:365-404``) for checkpoint parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Union

import jax.numpy as jnp

from apex_tpu.amp.master import MasterWeights, make_master
from apex_tpu.amp.policy import Policy, policy as make_policy
from apex_tpu.amp.scaler import (
    DynamicLossScale,
    LossScaleState,
    NoOpLossScale,
    StaticLossScale,
)

__all__ = ["AmpConfig", "AmpState", "initialize", "state_dict", "load_state_dict"]


@dataclasses.dataclass(frozen=True)
class AmpConfig:
    """Static (non-pytree) side of amp: the policy and scaler algorithm."""

    policy: Policy
    loss_scaler: Union[DynamicLossScale, StaticLossScale, NoOpLossScale]


class AmpState(NamedTuple):
    """Dynamic (pytree) side: scaler counters and optional master weights."""

    scaler: LossScaleState
    master: Optional[MasterWeights]


def initialize(
    params=None,
    opt_level: str = "O1",
    half_dtype=jnp.bfloat16,
    *,
    loss_scale: Union[str, float, None] = None,
    **policy_overrides,
):
    """Build amp config+state from an opt level.

    Mirrors ``amp.initialize`` keyword semantics
    (``apex/amp/frontend.py:197-264``): ``loss_scale`` overrides the preset
    ("dynamic" or a float); other :class:`Policy` fields can be overridden by
    keyword.  Returns ``(AmpConfig, AmpState)``; if ``params`` is given and
    the policy uses master weights, ``AmpState.master`` holds fp32 masters
    and the caller should derive model params via
    :func:`apex_tpu.amp.master_to_model`.
    """
    pol = make_policy(opt_level, half_dtype)
    if loss_scale is not None:
        pol = pol.with_options(loss_scale=loss_scale)
    if policy_overrides:
        pol = pol.with_options(**policy_overrides)

    if pol.loss_scale == "dynamic":
        scaler_algo: Any = DynamicLossScale()
    elif pol.loss_scale is None:
        scaler_algo = NoOpLossScale()
    else:
        scaler_algo = StaticLossScale(float(pol.loss_scale))

    master = None
    if params is not None and pol.master_weights:
        master = make_master(pol.cast_to_param(params))

    return AmpConfig(policy=pol, loss_scaler=scaler_algo), AmpState(
        scaler=scaler_algo.init(), master=master
    )


def state_dict(state: AmpState) -> dict:
    """Checkpointable scaler state (``amp.state_dict``,
    ``apex/amp/frontend.py:365-375``)."""
    return {
        "loss_scale": state.scaler.scale,
        "growth_tracker": state.scaler.growth_tracker,
        "hysteresis_tracker": state.scaler.hysteresis_tracker,
        "found_inf": state.scaler.found_inf,
    }


def load_state_dict(state: AmpState, sd: dict) -> AmpState:
    """Restore scaler state (``amp.load_state_dict``,
    ``apex/amp/frontend.py:377-404``)."""
    scaler = LossScaleState(
        scale=jnp.float32(sd["loss_scale"]),
        growth_tracker=jnp.int32(sd["growth_tracker"]),
        hysteresis_tracker=jnp.int32(sd["hysteresis_tracker"]),
        found_inf=jnp.asarray(sd["found_inf"]),
    )
    return state._replace(scaler=scaler)
