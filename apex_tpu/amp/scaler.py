"""Loss scaling — dynamic/static scalers as pure jit-safe state machines.

Reference semantics (``apex/amp/scaler.py``):

- ``LossScaler`` holds ``_loss_scale``; dynamic mode starts at 2**16
  (``scaler.py:38``), multiplies by 2 every 2000 overflow-free steps
  (``_scale_seq_len``, ``:42``), halves on overflow with ``_min_loss_scale``
  clamp (``update_scale`` ``:197-217``).
- Overflow detection is fused into the unscale kernel via a ``noop_flag``
  buffer (``csrc/multi_tensor_scale_kernel.cu``); the python fallback checks
  isnan/isinf (``scaler.py:16-30``).
- The hysteresis variant tolerates N consecutive overflows before backing off
  (``csrc/update_scale_hysteresis.cu:5-40``, test
  ``tests/L0/run_amp/test_update_scale_hysteresis.py``).
- On overflow the step is *skipped* (``apex/amp/handle.py:128-154`` patches
  ``optimizer.step`` to a no-op for that iteration).

The TPU redesign: scaler state is an immutable :class:`LossScaleState` pytree
threaded through the jitted train step; the overflow branch is a ``lax.cond``
(SURVEY.md §7(b)) so there is **no device→host sync per iteration** — the
reference pays one ``.item()`` round-trip every step (``scaler.py:200``).
Skip-step is ``jnp.where`` on the parameter update, which XLA turns into a
predicated update.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = [
    "LossScaleState",
    "DynamicLossScale",
    "StaticLossScale",
    "NoOpLossScale",
    "all_finite",
    "scale_loss",
]


class LossScaleState(NamedTuple):
    """Device-resident scaler state (all jnp scalars, jit-carried).

    ``scale``              — current loss scale (fp32).
    ``growth_tracker``     — consecutive overflow-free steps
                             (``_unskipped`` in ``apex/amp/scaler.py:44``).
    ``hysteresis_tracker`` — remaining tolerated overflows before backoff
                             (``csrc/update_scale_hysteresis.cu:12-24``).
    ``found_inf``          — whether the *last* step overflowed (for skip-step
                             predication and inspection).
    """

    scale: jnp.ndarray
    growth_tracker: jnp.ndarray
    hysteresis_tracker: jnp.ndarray
    found_inf: jnp.ndarray


def all_finite(tree) -> jnp.ndarray:
    """Fused overflow check over a whole gradient pytree.

    The analog of the ``noop_flag`` the multi-tensor kernels set on any
    non-finite value (``csrc/multi_tensor_scale_kernel.cu:54-120``): one
    scalar bool, computed inside jit, no host sync.
    """
    leaves = [
        x
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.asarray(True)
    finites = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finites).all()


@dataclasses.dataclass(frozen=True)
class DynamicLossScale:
    """Dynamic loss scaling with growth interval and hysteresis.

    Defaults mirror the reference: ``init_scale=2**16``
    (``apex/amp/scaler.py:38``), ``growth_interval=2000`` (``:42``),
    ``growth_factor=2``, ``backoff_factor=0.5`` (``update_scale``
    ``:205-216``), ``min_loss_scale`` clamp (``frontend.py:32-40``),
    ``hysteresis=1`` (plain scaler; set >1 for the
    ``update_scale_hysteresis`` behavior ``csrc/update_scale_hysteresis.cu``).
    """

    init_scale: float = 2.0**16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    hysteresis: int = 1
    min_scale: float = 1.0
    max_scale: float = 2.0**24

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.float32(self.init_scale),
            growth_tracker=jnp.int32(0),
            hysteresis_tracker=jnp.int32(self.hysteresis),
            found_inf=jnp.asarray(False),
        )

    def scale(self, loss, state: LossScaleState):
        """``loss * scale`` — yielded value of ``amp.scale_loss``
        (``apex/amp/handle.py:113`` does ``loss.float()*loss_scale``)."""
        return jnp.asarray(loss, jnp.float32) * state.scale

    def unscale(self, grads, state: LossScaleState):
        """Multiply grads by ``1/scale`` in fp32 — ``LossScaler.unscale``
        (``apex/amp/scaler.py:94-119``).  Returns fp32 grads (master-grad
        semantics of the O2 path)."""
        inv = 1.0 / state.scale
        return jax.tree_util.tree_map(
            lambda g: jnp.asarray(g, jnp.float32) * inv, grads
        )

    def update(self, state: LossScaleState, grads_finite) -> LossScaleState:
        """Pure-functional ``update_scale`` (``apex/amp/scaler.py:197-217``)
        with hysteresis (``csrc/update_scale_hysteresis.cu:5-60``), as
        branchless jnp.where (fully fused by XLA, no host sync):

        - overflow: decrement hysteresis; if exhausted, ``scale *= backoff``
          (clamped to ``min_scale``) and reset hysteresis+growth.
        - clean step: increment growth tracker; at ``growth_interval``,
          ``scale *= growth_factor`` (clamped to ``max_scale``) and reset.
        """
        grads_finite = jnp.asarray(grads_finite)

        hyst_after = jnp.maximum(state.hysteresis_tracker - 1, 0)
        do_backoff = jnp.logical_and(~grads_finite, hyst_after == 0)
        grew = state.growth_tracker + 1
        do_grow = jnp.logical_and(grads_finite, grew >= self.growth_interval)

        new_scale = jnp.where(
            do_backoff,
            jnp.maximum(state.scale * self.backoff_factor, self.min_scale),
            jnp.where(
                do_grow,
                jnp.minimum(state.scale * self.growth_factor, self.max_scale),
                state.scale,
            ),
        )
        new_growth = jnp.where(grads_finite, jnp.where(do_grow, 0, grew), 0)
        new_hyst = jnp.where(
            grads_finite,
            jnp.int32(self.hysteresis),
            jnp.where(do_backoff, jnp.int32(self.hysteresis), hyst_after),
        )
        return LossScaleState(
            scale=new_scale,
            growth_tracker=new_growth,
            hysteresis_tracker=new_hyst,
            found_inf=~grads_finite,
        )

    def adjust(self, params_new, params_old, state: LossScaleState):
        """Predicated skip-step: keep old params when the step overflowed.

        The reference patches ``optimizer.step`` to a skip
        (``apex/amp/handle.py:128-154``); under jit a ``jnp.where`` select is
        cheaper than a branch and keeps the program static.
        """
        keep_new = ~state.found_inf
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(keep_new, n, o), params_new, params_old
        )


@dataclasses.dataclass(frozen=True)
class StaticLossScale:
    """Fixed loss scale (``loss_scale=<float>`` in ``amp.initialize``,
    ``apex/amp/frontend.py:27-45``)."""

    loss_scale: float = 1.0

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.float32(self.loss_scale),
            growth_tracker=jnp.int32(0),
            hysteresis_tracker=jnp.int32(1),
            found_inf=jnp.asarray(False),
        )

    def scale(self, loss, state: LossScaleState):
        return jnp.asarray(loss, jnp.float32) * state.scale

    def unscale(self, grads, state: LossScaleState):
        inv = 1.0 / state.scale
        return jax.tree_util.tree_map(
            lambda g: jnp.asarray(g, jnp.float32) * inv, grads
        )

    def update(self, state: LossScaleState, grads_finite) -> LossScaleState:
        return state._replace(found_inf=~jnp.asarray(grads_finite))

    def adjust(self, params_new, params_old, state: LossScaleState):
        keep_new = ~state.found_inf
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(keep_new, n, o), params_new, params_old
        )


class NoOpLossScale(StaticLossScale):
    """Identity scaler for O0/bf16 paths (scale == 1, never skips)."""

    def __init__(self):
        super().__init__(loss_scale=1.0)

    def update(self, state: LossScaleState, grads_finite) -> LossScaleState:
        return state

    def adjust(self, params_new, params_old, state: LossScaleState):
        return params_new


def scale_loss(loss, state: LossScaleState):
    """Functional stand-in for the ``with amp.scale_loss(...)`` context
    (``apex/amp/handle.py:17``): returns the scaled loss to differentiate.
    Unscaling/update happen explicitly on the resulting grads."""
    return jnp.asarray(loss, jnp.float32) * state.scale
