"""apex_tpu.amp — mixed-precision policies and loss scaling.

TPU-native replacement for ``apex/amp`` (reference entry
``apex/amp/frontend.py:197`` ``amp.initialize``).  Apex works by mutating a
torch model in place: casting parameters, monkey-patching ``torch.*``
functions with cast wrappers (``apex/amp/amp.py:74-183``), and patching
optimizer ``step`` for master-weight copies
(``apex/amp/_process_optimizer.py:321``).  None of that has a JAX analog —
and none of it is needed: JAX programs are functional, so mixed precision is
expressed as an explicit :class:`Policy` that the user applies at three
well-defined points (params at init, inputs at the top of ``apply``, loss at
the end), plus a :class:`GradScaler`-style state threaded through the train
step.  This is the deliberate API divergence documented in SURVEY.md §7(d).

The O0–O3 opt levels (``apex/amp/frontend.py:104-193``) map to:

========  =======================  =========================================
ref       apex_tpu policy          meaning on TPU
========  =======================  =========================================
``O0``    ``policy("O0")``         pure fp32 (accuracy baseline)
``O1``    ``policy("O1")``         fp32 params, bf16 compute at op boundaries
``O2``    ``policy("O2")``         bf16 params + fp32 master weights,
                                   norms in fp32, dynamic loss scale
``O3``    ``policy("O3")``         pure bf16 ("speed of light")
========  =======================  =========================================

bf16 on TPU has fp32's exponent range, so loss scaling is rarely *needed* —
but fp16 policies (``half_dtype=jnp.float16``) are fully supported for
parity, and :class:`DynamicLossScale` reproduces the reference scaler
semantics (init 2^16, x2 every 2000 good steps, /2 on overflow, hysteresis;
``apex/amp/scaler.py:33-217``, ``csrc/update_scale_hysteresis.cu:5``)
entirely inside jit via ``lax.cond`` — no device-to-host sync per step,
unlike the reference's ``_overflow_buf.item()`` (``scaler.py:200``).
"""

from apex_tpu.amp.policy import (  # noqa: F401
    Policy,
    policy,
    O0,
    O1,
    O2,
    O3,
    cast_to_compute,
    cast_to_param,
    cast_to_output,
    cast_floating,
)
from apex_tpu.amp.scaler import (  # noqa: F401
    LossScaleState,
    DynamicLossScale,
    StaticLossScale,
    NoOpLossScale,
    all_finite,
    scale_loss,
)
from apex_tpu.amp.fp8 import (  # noqa: F401
    E4M3,
    E5M2,
    Fp8Dense,
    Fp8Meta,
    fp8_quantize,
    update_meta,
)
from apex_tpu.amp.master import (  # noqa: F401
    MasterWeights,
    make_master,
    master_to_model,
)
from apex_tpu.amp.frontend import (  # noqa: F401
    AmpConfig,
    AmpState,
    initialize,
    state_dict,
    load_state_dict,
)
