"""Precision policies — the functional O0-O3 analog.

Reference semantics being reproduced (``apex/amp/frontend.py:104-193``):

- ``O0``: everything fp32.
- ``O1``: params fp32; a *cast list* decides which ops run in half precision
  (GEMMs/convs on the fp16 list ``apex/amp/lists/torch_overrides.py:7-28``,
  reductions/norms/losses on the fp32 list ``:30-68``).  JAX cannot
  monkey-patch ``jnp.*`` (and should not); instead the policy is applied at
  module boundaries: ``cast_to_compute`` on inputs of matmul-heavy modules,
  with norm/softmax/loss modules keeping fp32 internally — which is exactly
  what the cast lists achieve in practice.
- ``O2``: params cast to half except norms (``BN_convert_float``
  ``apex/fp16_utils/fp16util.py:22``), fp32 master weights held by the
  optimizer, dynamic loss scaling.
- ``O3``: params and compute all half, no master weights.

On TPU the default half dtype is bfloat16 (MXU-native); fp16 is supported for
reference parity (and needs the loss scaler to be meaningful).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

__all__ = [
    "Policy",
    "policy",
    "O0",
    "O1",
    "O2",
    "O3",
    "cast_floating",
    "cast_to_compute",
    "cast_to_param",
    "cast_to_output",
]

DTypeLike = Any


# Parameter-collection names treated as "norm" params for the
# keep_batchnorm_fp32 exemption.  Matched as case-insensitive substrings of
# any key on the leaf's pytree path — covers flax's ``batch_stats``
# collection and conventional module names (``LayerNorm_0``, ``bn1``, ...).
NORM_PATH_PATTERNS = (
    "batchnorm",
    "batch_stats",
    "layernorm",
    "layer_norm",
    "rmsnorm",
    "rms_norm",
    "groupnorm",
    "group_norm",
    "_bn",
    "bn_",
    "norm",
)


def _path_is_norm(path) -> bool:
    for entry in path:
        name = getattr(entry, "key", getattr(entry, "name", None))
        if isinstance(name, str):
            low = name.lower()
            if any(pat in low for pat in NORM_PATH_PATTERNS):
                return True
    return False


def cast_floating(tree, dtype: DTypeLike, *, except_norms_to: DTypeLike = None):
    """Cast every floating-point leaf of a pytree to ``dtype``.

    Non-float leaves (int labels, bool masks, PRNG keys) pass through, the
    same way the reference's input caster only touches float tensors
    (``apex/amp/_initialize.py:53-63`` casts only ``is_floating_point``).

    ``except_norms_to``: if set, leaves whose pytree path mentions a norm
    module (see :data:`NORM_PATH_PATTERNS`) are cast to that dtype instead —
    the ``keep_batchnorm_fp32`` / ``BN_convert_float`` exemption
    (``apex/fp16_utils/fp16util.py:22-33``).
    """

    def _cast(path, x):
        target = dtype
        if except_norms_to is not None and _path_is_norm(path):
            target = except_norms_to
        if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
            x.dtype, jnp.floating
        ):
            return x.astype(target)
        if isinstance(x, float):
            return jnp.asarray(x, target)
        return x

    return jax.tree_util.tree_map_with_path(_cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """A mixed-precision policy: where each dtype is used.

    Functional analog of amp ``Properties`` (``apex/amp/frontend.py:9-101``):
    ``cast_model_type``→``param_dtype``, ``opt_level`` compute behavior→
    ``compute_dtype``, ``keep_batchnorm_fp32``→``norm_dtype``,
    ``master_weights``→``master_weights``, ``loss_scale``→``loss_scale``.
    """

    name: str
    param_dtype: DTypeLike
    compute_dtype: DTypeLike
    output_dtype: DTypeLike
    norm_dtype: DTypeLike  # dtype for norm params/statistics (keep_batchnorm_fp32)
    master_weights: bool
    loss_scale: Union[str, float, None]  # "dynamic", a static float, or None

    # -- casting helpers ---------------------------------------------------
    def cast_to_compute(self, tree):
        return cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        """Cast params to ``param_dtype``, keeping norm-module params at
        ``norm_dtype`` (the ``keep_batchnorm_fp32`` O2 behavior,
        ``apex/amp/frontend.py:126-146`` + ``fp16util.py:22``)."""
        if jnp.dtype(self.norm_dtype) != jnp.dtype(self.param_dtype):
            return cast_floating(
                tree, self.param_dtype, except_norms_to=self.norm_dtype
            )
        return cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return cast_floating(tree, self.output_dtype)

    def with_options(self, **kw) -> "Policy":
        """Override fields, mirroring ``amp.initialize``'s keyword overrides
        (``apex/amp/frontend.py:197-264`` ``cast_model_type=``, ``loss_scale=``...)."""
        return dataclasses.replace(self, **kw)

    @property
    def uses_half_params(self) -> bool:
        return jnp.dtype(self.param_dtype) != jnp.dtype(jnp.float32)


def _make(name: str, half) -> Policy:
    f32 = jnp.float32
    overrides = {
        "O0": dict(param_dtype=f32, compute_dtype=f32, output_dtype=f32,
                   norm_dtype=f32, master_weights=False, loss_scale=None),
        "O1": dict(param_dtype=f32, compute_dtype=half, output_dtype=f32,
                   norm_dtype=f32, master_weights=False,
                   loss_scale="dynamic" if half == jnp.float16 else None),
        "O2": dict(param_dtype=half, compute_dtype=half, output_dtype=f32,
                   norm_dtype=f32, master_weights=True, loss_scale="dynamic"),
        "O3": dict(param_dtype=half, compute_dtype=half, output_dtype=half,
                   norm_dtype=half, master_weights=False, loss_scale=1.0),
    }[name]
    return Policy(name=name, **overrides)


def policy(opt_level: str = "O1", half_dtype: DTypeLike = jnp.bfloat16) -> Policy:
    """Construct a policy from an Apex-style opt level.

    ``half_dtype=jnp.bfloat16`` (default, MXU-native) or ``jnp.float16``
    (reference-parity; activates dynamic loss scaling in O1).
    Reference preset table: ``apex/amp/frontend.py:104-193``.
    """
    if opt_level not in ("O0", "O1", "O2", "O3"):
        raise ValueError(
            f"unknown opt_level {opt_level!r}; expected one of O0, O1, O2, O3 "
            "(reference: apex/amp/frontend.py:104)"
        )
    return _make(opt_level, jnp.dtype(half_dtype).type)


# Default bf16 presets, importable directly.
O0 = policy("O0")
O1 = policy("O1")
O2 = policy("O2")
O3 = policy("O3")


def cast_to_compute(tree, p: Policy):
    return p.cast_to_compute(tree)


def cast_to_param(tree, p: Policy):
    return p.cast_to_param(tree)


def cast_to_output(tree, p: Policy):
    return p.cast_to_output(tree)
