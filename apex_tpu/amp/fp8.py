"""FP8 training primitives with delayed scaling.

Parity-frontier: the reference's fp8 support is the amax-sharing process
groups ``parallel_state`` builds for TransformerEngine interop
(``apex/transformer/parallel_state.py`` amax groups, SURVEY §2.2 row 24) —
apex itself defers the math to TE.  This module supplies the TPU-native
math TE would: per-tensor **delayed scaling** (scale from a rolling amax
history), e4m3 forward / e5m2 gradient quantization, and the
model-parallel amax reduction that is the reference's amax group.

Semantics (TransformerEngine delayed-scaling recipe):

- each quantized tensor carries ``Fp8Meta``: ``amax_history [H]`` and the
  current ``scale``;
- quantize: ``q = cast(clip(x * scale, ±fp8_max))`` with
  ``scale = fp8_max / (amax_hist_max * margin)`` derived from *previous*
  steps (delayed — no extra pass over the data);
- the *current* step's amax rolls into the history; under tensor/sequence
  parallelism the amax is ``pmax``-reduced over the model-parallel axis
  first (the amax-group all-reduce);
- **gradients use just-in-time (current) scaling** to e5m2: the cotangent
  magnitude is set by the loss scaler and can jump 2^16x step to step, so
  a delayed scale would saturate the clip silently (finite values — the
  scaler's ``all_finite`` would never trip); the per-step amax pass over
  the cotangent buys robustness (TE's "current scaling" option).

TPU note: matmuls compute in ``preferred_element_type`` after an upcast
from fp8 — on chips without fp8 MXU paths this is a numerics/storage
capability (fp8-width activations/grads for collectives and checkpoints),
not a FLOP win; the API is laid out so XLA lowers straight to fp8 GEMMs
where hardware supports them.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

try:  # flax is the module-layer convention in this framework
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

__all__ = ["Fp8Meta", "Fp8Dense", "fp8_quantize", "fp8_matmul_t",
           "update_meta", "E4M3", "E5M2"]

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
_MARGIN = 1.0


class Fp8Meta(NamedTuple):
    """Delayed-scaling state for one quantized tensor."""

    amax_history: jnp.ndarray  # [H] fp32
    scale: jnp.ndarray         # scalar fp32

    @classmethod
    def init(cls, history_len: int = 16) -> "Fp8Meta":
        return cls(amax_history=jnp.zeros((history_len,), jnp.float32),
                   scale=jnp.float32(1.0))


def _fp8_max(dtype) -> float:
    return float(jnp.finfo(dtype).max)


def _quantize(v, scale, dtype):
    """``cast(clip(v * scale, ±fp8_max))`` — the one copy of the core
    quantization expression (fwd, bwd, and the public API all route here)."""
    lim = _fp8_max(dtype)
    return jnp.clip(v.astype(jnp.float32) * scale, -lim, lim).astype(dtype)


def fp8_quantize(x, meta: Fp8Meta, dtype=E4M3):
    """Quantize with the *delayed* scale; returns ``(q, amax_now)``."""
    amax_now = jnp.max(jnp.abs(x)).astype(jnp.float32)
    return _quantize(x, meta.scale, dtype), amax_now


def update_meta(meta: Fp8Meta, amax_now, dtype=E4M3,
                axis: Optional[str] = None) -> Fp8Meta:
    """Roll the amax history and refresh the scale.

    ``axis``: model-parallel mesh axis to ``pmax`` the amax over before it
    enters the history — the reference's amax-sharing group
    (``parallel_state`` amax groups) as one collective.

    The update is pure bookkeeping, never a gradient path: the input is
    ``stop_gradient``-ed so the ``pmax`` (which has no differentiation
    rule) sees a symbolic-zero tangent when the surrounding train step is
    differentiated with the new metas as aux outputs.
    """
    amax_now = jax.lax.stop_gradient(
        jnp.asarray(amax_now, jnp.float32).reshape(()))
    if axis is not None:
        amax_now = jax.lax.pmax(amax_now, axis)
    hist = jnp.concatenate([amax_now[None],
                            meta.amax_history[:-1]])
    amax = jnp.max(hist)
    scale = jnp.where(amax > 0,
                      _fp8_max(dtype) / (amax * _MARGIN),
                      meta.scale)
    return Fp8Meta(amax_history=hist, scale=scale)


def _jit_e5m2_f32(g):
    """Quantize a cotangent to e5m2 with a just-in-time scale and return it
    upcast to fp32 (see module docstring: delayed scales are unsafe for
    gradients under dynamic loss scaling)."""
    g_amax = jnp.max(jnp.abs(g)).astype(jnp.float32)
    g_scale = jnp.where(g_amax > 0, _fp8_max(E5M2) / g_amax, 1.0)
    return _quantize(g, g_scale, E5M2).astype(jnp.float32) / g_scale


@jax.custom_vjp
def fp8_matmul_t(x, w, xm, wm):
    """``y = x @ w.T`` computed through fp8 with delayed scaling.

    Torch weight layout (``w: [out, in]``) — the GEMM core the
    tensor-parallel linears (:class:`ColumnParallelLinear` /
    :class:`RowParallelLinear`) route through when their ``fp8`` flag is
    set.  Forward quantizes both operands to e4m3 with the *delayed* scales
    carried in ``xm``/``wm`` (:class:`Fp8Meta`); backward quantizes the
    cotangent to e5m2 just-in-time.  Pure w.r.t. the metas — callers roll
    them forward with :func:`update_meta` (amax ``pmax``-shared over the
    model-parallel axis, the reference's amax group:
    ``apex/transformer/parallel_state.py:280-291``).
    """
    x2d = x.reshape(-1, x.shape[-1])
    xq = _quantize(x2d, xm.scale, E4M3).astype(jnp.float32)
    wq = _quantize(w, wm.scale, E4M3).astype(jnp.float32)
    y = (xq @ wq.T) / (xm.scale * wm.scale)
    return y.reshape(*x.shape[:-1], w.shape[0]).astype(x.dtype)


def _fp8_matmul_t_fwd(x, w, xm, wm):
    return fp8_matmul_t(x, w, xm, wm), (x, w, xm, wm)


def _fp8_matmul_t_bwd(res, g):
    x, w, xm, wm = res
    g32 = _jit_e5m2_f32(g.reshape(-1, g.shape[-1]))  # [N, out]
    wq = _quantize(w, wm.scale, E4M3).astype(jnp.float32)
    xq = _quantize(x.reshape(-1, x.shape[-1]), xm.scale, E4M3).astype(
        jnp.float32)
    dx = (g32 @ wq) / wm.scale     # [N, in]
    dw = (g32.T @ xq) / xm.scale   # [out, in]
    return (dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype),
            None, None)


fp8_matmul_t.defvjp(_fp8_matmul_t_fwd, _fp8_matmul_t_bwd)


if nn is not None:

    class Fp8Dense(nn.Module):
        """Dense layer computing through fp8 with delayed scaling.

        Meta state lives in the mutable ``"fp8_meta"`` collection — run
        ``apply(..., mutable=["fp8_meta"])`` during training and carry the
        returned collection forward (checkpointable like any state).  The
        gradient path quantizes the incoming cotangent to e5m2 with a
        just-in-time scale (see module docstring — robust under dynamic loss
        scaling).
        """

        features: int
        use_bias: bool = True
        history_len: int = 16
        axis: Optional[str] = None  # model-parallel amax-sharing axis
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            in_features = x.shape[-1]
            kernel = self.param("kernel", nn.initializers.lecun_normal(),
                                (in_features, self.features), self.param_dtype)
            bias = (self.param("bias", nn.initializers.zeros,
                               (self.features,), self.param_dtype)
                    if self.use_bias else None)

            init = lambda: Fp8Meta.init(self.history_len)  # noqa: E731
            metas = self.variable("fp8_meta", "metas",
                                  lambda: {"x": init(), "w": init()})
            m = metas.value
            axis = self.axis

            lead = x.shape[:-1]
            x2d = x.reshape(-1, in_features)
            # One fp8 GEMM core for the whole framework: fp8_matmul_t takes
            # the torch layout [out, in]; the flax kernel is [in, out], and
            # XLA folds the transpose into the GEMM's dimension numbers.
            y = fp8_matmul_t(x2d, kernel.T, m["x"], m["w"])

            # Delayed-scaling bookkeeping (outside the vjp: pure state; the
            # single amax pass per tensor lives here — the core quantizes
            # with the stored scales only).  Rolls only when the caller made
            # the collection mutable: inference apply() runs with frozen
            # scales (delayed-scaling eval semantics).
            if not self.is_initializing() and self.is_mutable_collection(
                    "fp8_meta"):
                x_amax = jnp.max(jnp.abs(x2d)).astype(jnp.float32)
                w_amax = jnp.max(jnp.abs(kernel)).astype(jnp.float32)
                metas.value = {
                    "x": update_meta(m["x"], x_amax, E4M3, axis),
                    "w": update_meta(m["w"], w_amax, E4M3, axis),
                }

            y = y.reshape(*lead, self.features)
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y

else:  # pragma: no cover
    class Fp8Dense:  # type: ignore[no-redef]
        """Placeholder that fails loudly when flax is unavailable."""

        def __init__(self, *args, **kwargs):
            raise ImportError(
                "Fp8Dense requires flax (the Flax module layer is optional "
                "for the rest of apex_tpu.amp.fp8); install flax to use it."
            )
