"""Scale-mask-softmax family — the megatron fused softmax analog.

Behavioral spec: ``apex/transformer/functional/fused_softmax.py`` (autograd
wrappers ``:21,71,106,133``, dispatcher ``FusedScaleMaskSoftmax:164``) over
the warp-level kernels in ``csrc/megatron/scaled_*_softmax*.cu``.

Semantics preserved:

- forward: ``softmax(scale * x + mask)`` with the mask applied *after*
  scaling, causal (upper-triangular) or additive padding mask variants;
  math in fp32, result cast back to the input dtype (the kernels compute
  ``acc_t = float`` internally);
- backward saves only the softmax *output*:
  ``dx = scale * y * (dy - sum(dy*y))`` — expressed as a custom_vjp so the
  activation-memory profile matches the fused kernels (the default jax vjp
  of the composed forward would save the inputs as well);
- ``generic_scaled_masked_softmax`` — the no-shape-limit variant
  (``csrc/megatron/generic_scaled_masked_softmax.cu``);
- :class:`FusedScaleMaskSoftmax` keeps the dispatcher API (mask type,
  ``softmax_in_fp32``, ``mask_func``, scale validation) but needs no
  ``is_kernel_available`` shape gate — there is no 16384-key or
  seq-multiple-of-4 limit, any shape compiles (``fused_softmax.py:222-246``
  becomes vacuous on TPU; kept as a method returning True for API parity).

Masks: the reference's padding mask is a *bool* tensor where True means
"mask out" (filled with -10000 by ``mask_func``); reproduced here.
"""

from __future__ import annotations

import enum
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "AttnMaskType",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
    "FusedScaleMaskSoftmax",
]


class AttnMaskType(enum.Enum):
    """``apex/transformer/enums.py`` AttnMaskType."""

    padding = 1
    causal = 2


_MASK_FILL = -10000.0  # reference mask fill value (attention_mask_func)


def _softmax_fwd_f32(x32):
    m = jnp.max(x32, axis=-1, keepdims=True)
    e = jnp.exp(x32 - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)


def _softmax_bwd_from_y(y, dy, scale):
    y32 = jnp.asarray(y, jnp.float32)
    dy32 = jnp.asarray(dy, jnp.float32)
    inner = dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)
    return scale * y32 * inner


# --- scaled softmax (no mask) ---------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_softmax(x, scale: float = 1.0):
    """``ScaledSoftmax`` (``fused_softmax.py:133``): softmax(scale*x)."""
    y = _softmax_fwd_f32(jnp.asarray(x, jnp.float32) * scale)
    return jnp.asarray(y, x.dtype)


def _ss_fwd(x, scale):
    y = scaled_softmax(x, scale)
    return y, (y,)


def _ss_bwd(scale, res, dy):
    (y,) = res
    return (jnp.asarray(_softmax_bwd_from_y(y, dy, scale), y.dtype),)


scaled_softmax.defvjp(_ss_fwd, _ss_bwd)


# --- scaled masked softmax (padding mask) ----------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """``ScaledMaskedSoftmax`` (``fused_softmax.py:71``):
    softmax(mask_fill(scale*x)).  ``mask`` is bool, True = masked out,
    broadcastable to x ([b, 1, sq, sk] against [b, np, sq, sk])."""
    x32 = jnp.asarray(x, jnp.float32) * scale
    if mask is not None:
        x32 = jnp.where(mask, _MASK_FILL, x32)
    y = _softmax_fwd_f32(x32)
    return jnp.asarray(y, x.dtype)


def _sms_fwd(x, mask, scale):
    y = scaled_masked_softmax(x, mask, scale)
    return y, (y,)


def _sms_bwd(scale, res, dy):
    (y,) = res
    # masked positions have y==0 so their grad is 0 automatically
    return (jnp.asarray(_softmax_bwd_from_y(y, dy, scale), y.dtype), None)


scaled_masked_softmax.defvjp(_sms_fwd, _sms_bwd)


# --- causal -----------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """``ScaledUpperTriangMaskedSoftmax`` (``fused_softmax.py:21``): causal
    mask built in-kernel (``scaled_upper_triang_masked_softmax.h``).
    x: [..., sq, sk] with sq == sk (attn_batches leading)."""
    sq, sk = x.shape[-2], x.shape[-1]
    x32 = jnp.asarray(x, jnp.float32) * scale
    causal = jnp.tril(jnp.ones((sq, sk), bool))
    x32 = jnp.where(causal, x32, _MASK_FILL)
    y = _softmax_fwd_f32(x32)
    # kernel zeroes the strictly-upper triangle exactly
    y = jnp.where(causal, y, 0.0)
    return jnp.asarray(y, x.dtype)


def _sutms_fwd(x, scale):
    y = scaled_upper_triang_masked_softmax(x, scale)
    return y, (y,)


def _sutms_bwd(scale, res, dy):
    (y,) = res
    return (jnp.asarray(_softmax_bwd_from_y(y, dy, scale), y.dtype),)


scaled_upper_triang_masked_softmax.defvjp(_sutms_fwd, _sutms_bwd)


def generic_scaled_masked_softmax(x, mask, scale: float = 1.0):
    """No-shape-limit variant (``csrc/megatron/generic_scaled_masked_softmax.cu``)
    — on TPU identical to :func:`scaled_masked_softmax`."""
    return scaled_masked_softmax(x, mask, scale)


# --- dispatcher module ------------------------------------------------------


class FusedScaleMaskSoftmax:
    """Dispatcher with the reference constructor surface
    (``fused_softmax.py:164-213``).

    On TPU every shape takes the fused path; ``softmax_in_fp32`` and the
    float16 flags only affect the *non-scaled* fallback dtype behavior the
    reference has (``forward_torch_softmax`` ``:253-270``), which we keep for
    numerical parity of the ``softmax_in_fp32=False`` configuration.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError(
                "both fp16 and bf16 flags cannot be active at the same time."
            )
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if not (scale is None or softmax_in_fp32):
            raise RuntimeError("softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """Always True on TPU — no warp-kernel shape limits
        (cf. ``fused_softmax.py:222-246``)."""
        return self.scaled_masked_softmax_fusion

    def __call__(self, x, mask):
        assert x.ndim == 4, "expected [b, np, sq, sk]"
        scale = self.scale if self.scale is not None else 1.0
        if self.scaled_masked_softmax_fusion:
            if self.attn_mask_type == AttnMaskType.causal:
                b, np_, sq, sk = x.shape
                assert sq == sk, "causal mask requires sq == sk"
                y = scaled_upper_triang_masked_softmax(
                    x.reshape(b * np_, sq, sk), scale
                )
                return y.reshape(b, np_, sq, sk)
            return scaled_masked_softmax(x, mask, scale)
        # unfused fallback with reference dtype behavior
        if self.input_in_float16 and self.softmax_in_fp32:
            x = jnp.asarray(x, jnp.float32)
        if self.scale is not None:
            x = x * self.scale
        if mask is not None and self.mask_func is not None:
            x = self.mask_func(x, mask)
        elif mask is not None:
            x = jnp.where(mask, _MASK_FILL, x)
        probs = jax.nn.softmax(x, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            # cast back to the declared input half dtype
            # (fused_softmax.py:263-266 .half() vs .bfloat16())
            probs = jnp.asarray(
                probs, jnp.float16 if self.input_in_fp16 else jnp.bfloat16
            )
        return probs
