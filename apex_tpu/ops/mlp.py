"""Whole-MLP fusion — the ``mlp_cuda`` analog.

Behavioral spec: ``apex/mlp/mlp.py`` (``MlpFunction:11``, ``MLP:33``) over
``csrc/mlp_cuda.cu`` (``mlp_gemm`` chain with fused bias + relu/sigmoid
epilogues ``:59-147``).  The reference fuses an entire N-layer perceptron —
every GEMM, bias add and activation, forward and backward — into one C++
call to avoid framework overhead between layers.

Under jit the Python loop below unrolls into a single XLA computation, so the
reference's whole point (no per-layer dispatch) holds by construction.  API
parity: ``mlp_sizes`` list, ``bias`` flag, ``activation`` in
{'none', 'relu', 'sigmoid'} (``apex/mlp/mlp.py:36-46``), torch weight layout
[out, in].
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

__all__ = ["mlp_forward", "MLP"]

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_forward(x, weights: Sequence, biases: Sequence, activation: str = "relu"):
    """Functional N-layer MLP.

    The activation is applied after *every* layer including the last — the
    reference applies its epilogue per GEMM (``mlp_cuda.cu:1332-1350``), and
    its own test builds the torch reference as Linear+ReLU pairs for every
    layer (``tests/L0/run_mlp/test_mlp.py:28-36``).
    """
    if activation not in _ACTIVATIONS:
        raise ValueError(
            f"activation must be one of {sorted(_ACTIVATIONS)} "
            "(parity with apex/mlp/mlp.py:43)"
        )
    act = _ACTIVATIONS[activation]
    h = x
    for i, w in enumerate(weights):
        h = jnp.dot(h, w.T, preferred_element_type=h.dtype)
        if biases:
            h = h + biases[i]
        h = act(h)
    return h


if nn is not None:

    class MLP(nn.Module):
        """Module analog of ``apex.mlp.MLP`` (``apex/mlp/mlp.py:33``).

        ``mlp_sizes``: [in, hidden..., out]."""

        mlp_sizes: Sequence[int]
        use_bias: bool = True
        activation: str = "relu"
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            weights, biases = [], []
            for i in range(len(self.mlp_sizes) - 1):
                w = self.param(
                    f"weight_{i}",
                    nn.initializers.lecun_normal(),
                    (self.mlp_sizes[i + 1], self.mlp_sizes[i]),
                    self.param_dtype,
                )
                weights.append(jnp.asarray(w, x.dtype))
                if self.use_bias:
                    b = self.param(
                        f"bias_{i}", nn.initializers.zeros,
                        (self.mlp_sizes[i + 1],), self.param_dtype,
                    )
                    biases.append(jnp.asarray(b, x.dtype))
            return mlp_forward(x, weights, biases, self.activation)

else:  # pragma: no cover
    MLP = None
