"""Pallas row-norm kernels — TPU-native FusedLayerNorm fast path.

The XLA-fused :mod:`apex_tpu.normalization` path is usually optimal (row
reductions fuse with neighbours), but for odd widths or when the norm is the
only op between two big GEMMs a hand-tiled kernel keeps rows resident in
VMEM across the two reduction passes — the same motivation as the
persistent "FastLayerNorm" in ``apex/contrib/csrc/layer_norm``
(``ln_fwd_cuda_kernel.cu``) which exists because the generic
``csrc/layer_norm_cuda_kernel.cu`` was not fast enough at large hidden
sizes.

The Pallas kernel computes the forward; the backward is wired via
``custom_vjp`` to the analytic gradients of
:mod:`apex_tpu.normalization.fused_layer_norm` (recomputing statistics —
the memory-efficient trade), because the backward is bandwidth-bound either
way and XLA fuses it well.

Usage: ``pallas_layer_norm(x, w, b)`` with ``x: [rows, hidden]``; rows are
tiled in blocks of ``block_rows``; hidden must be a multiple of 128 (lane
width) — callers should fall back to the jnp path otherwise (the
``is_available`` predicate mirrors ``is_kernel_available``,
``apex/transformer/functional/fused_softmax.py:222``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

__all__ = ["pallas_layer_norm", "pallas_rms_norm", "is_available"]


def is_available(hidden: int) -> bool:
    """Shape gate for the Pallas path (lane-width aligned)."""
    return PALLAS_AVAILABLE and hidden % 128 == 0


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    y = y * w_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_layer_norm(
    x,
    weight,
    bias,
    eps: float = 1e-5,
    block_rows: int = 256,
    interpret: bool = False,
):
    """LayerNorm as a Pallas forward kernel + analytic custom backward;
    x: [..., hidden] (leading dims flattened to rows)."""
    return _pallas_ln_fwd_call(x, weight, bias, eps, block_rows, interpret)


def _pallas_ln_bwd(eps, block_rows, interpret, res, dy):
    from apex_tpu.normalization import fused_layer_norm_affine

    x, weight, bias = res
    shape = (x.shape[-1],)
    return jax.vjp(
        lambda x_, w_, b_: fused_layer_norm_affine(x_, w_, b_, shape, eps),
        x, weight, bias,
    )[1](dy)


pallas_layer_norm.defvjp(
    lambda x, w, b, eps, block_rows, interpret: (
        _pallas_ln_fwd_call(x, w, b, eps, block_rows, interpret),
        (x, w, b),
    ),
    _pallas_ln_bwd,
)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def _pallas_ln_fwd_call(x, weight, bias, eps, block_rows, interpret):
    orig_shape = x.shape
    hidden = orig_shape[-1]
    rows = math.prod(orig_shape[:-1]) if len(orig_shape) > 1 else 1
    x2 = x.reshape(rows, hidden)
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
        interpret=interpret,
    )(x2, weight, bias)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def pallas_rms_norm(
    x,
    weight,
    eps: float = 1e-5,
    block_rows: int = 256,
    interpret: bool = False,
):
    """RMSNorm as a Pallas forward kernel + analytic custom backward."""
    return _pallas_rms_fwd_call(x, weight, eps, block_rows, interpret)


def _pallas_rms_bwd(eps, block_rows, interpret, res, dy):
    from apex_tpu.normalization import fused_rms_norm_affine

    x, weight = res
    shape = (x.shape[-1],)
    return jax.vjp(
        lambda x_, w_: fused_rms_norm_affine(x_, w_, shape, eps), x, weight
    )[1](dy)


pallas_rms_norm.defvjp(
    lambda x, w, eps, block_rows, interpret: (
        _pallas_rms_fwd_call(x, w, eps, block_rows, interpret),
        (x, w),
    ),
    _pallas_rms_bwd,
)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows", "interpret"))
def _pallas_rms_fwd_call(x, weight, eps, block_rows, interpret):
    orig_shape = x.shape
    hidden = orig_shape[-1]
    rows = math.prod(orig_shape[:-1]) if len(orig_shape) > 1 else 1
    x2 = x.reshape(rows, hidden)
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(orig_shape)
