"""Flash attention — Pallas TPU kernels with a custom VJP.

Capability parity target: ``apex/contrib/fmha`` (fixed-shape fp16 fused MHA,
seqlens ≤512, varlen via cu_seqlens, dropout —
``apex/contrib/csrc/fmha/fmha_api.cpp``) and the fused softmax-attention
core of ``apex/contrib/multihead_attn`` — rebuilt as a *blockwise
online-softmax* kernel family with none of the shape limits (any seqlen,
any head dim that tiles to the MXU, fp32/bf16).

Design (the standard flash decomposition, mapped to TPU):

- forward: grid ``(batch*heads, q_blocks, k_blocks)`` with the k-block index
  innermost; the running row-max ``m``, row-sum ``l`` and output accumulator
  live in VMEM scratch that persists across the k sweep, so K/V *stream*
  through VMEM one block at a time (Pallas double-buffers the HBM→VMEM
  copies against the MXU work) and VMEM holds O(block) state regardless of
  sequence length — the softmax never materialises the ``[sq, sk]`` score
  matrix (the reason apex's fused softmax caps at 16384 keys disappears).
- saves ``(out, lse)`` only — the activation-memory profile of the fused
  kernels (``fmha`` saves the same) rather than O(s²) probabilities.
- backward: one kernel recomputes scores per (q-block, k-block) pair to form
  ``dq`` (k innermost, dq in scratch), a second forms ``dk/dv`` over the
  transposed blocking (q innermost), both seeded with
  ``delta = rowsum(do * o)`` computed in plain XLA.
- **causal block skipping**: fully-masked (q-block, k-block) pairs are
  skipped with ``pl.when`` (no MXU work) and their K/V block index maps are
  clamped to the last live block so Pallas elides the HBM→VMEM copy —
  the ~2× FLOP saving of a production causal kernel.
- **segment masking / varlen**: optional per-token integer segment ids
  (must be ≥ 0) mask attention across segment boundaries — the TPU-native
  form of fmha's ``cu_seqlens`` packed-varlen API (a packed batch is one
  row with increasing segment ids; padding = any position whose id differs).
  Non-multiple-of-block sequence lengths are handled by padding to the
  block grid with sentinel segment ids, so any length compiles.
- **attention dropout**: counter-based (seed, batch·head, row, col) hash →
  keep mask, regenerated bit-identically in the backward kernels, so no
  dropout mask is ever materialised in HBM.  Matches the reference's
  "dropout after softmax" semantics: the row normaliser ``l`` accumulates
  *undropped* probabilities.
- ``q_offset``/``kv_offset`` place a q/k shard at its global sequence
  position so causal masking stays correct when the sequence is sharded —
  the hook ring attention (context parallelism,
  :mod:`apex_tpu.transformer.context_parallel`) builds on.  The backward
  entry points (:func:`dq_chunk`, :func:`dkv_chunk`) are exposed for the
  same reason: ring backward re-drives them per visiting chunk with the
  *global* lse.
- fully-masked q rows (reachable with offset combinations or segment ids)
  produce **zero** output and ``lse = -1e30``: the running max is clamped
  before the exp so masked-out scores can never contribute unit mass
  (the ``exp(NEG_INF - NEG_INF) = 1`` failure mode).
- ``interpret=True`` is selected automatically off-TPU so the same code runs
  in the CPU test mesh.

Layouts: ``q, k, v: [batch, heads, seq, head_dim]`` (BHSD).  ``lse`` rides
as ``[b, h, s, 1]`` inside kernels (trailing singleton keeps the TPU
(sublane, lane) tiling rule satisfied for any block) and is squeezed at the
API boundary.  Segment ids ride as ``[b, s, 1]`` for the same reason.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "dq_chunk",
    "dkv_chunk",
]

# Block-size defaults, overridable per-process for hardware sweeps
# (examples/tune_flash_blocks.py runs each grid point in a subprocess).
import os as _os


def _env_block(name: str, default: int):
    """(value, applied): ``applied`` is True only when the env var held a
    valid positive int — an ignored/invalid value must NOT also suppress
    the tuned-file lookup downstream."""
    raw = _os.environ.get(name)
    if raw is None:
        return default, False
    try:
        val = int(raw)
        if val <= 0:
            raise ValueError(f"must be positive, got {val}")
        return val, True
    except ValueError as e:
        import warnings

        warnings.warn(f"ignoring {name}={raw!r} ({e}); "
                      f"using default {default}")
        return default, False


DEFAULT_BLOCK_Q, _Q_FROM_ENV = _env_block("APEX_TPU_FLASH_BLOCK_Q", 256)
DEFAULT_BLOCK_K, _K_FROM_ENV = _env_block("APEX_TPU_FLASH_BLOCK_K", 512)
_ENV_SET = (_Q_FROM_ENV, _K_FROM_ENV)
_TUNED_CACHE: "tuple | None" = None


def _tuned_blocks():
    """(block_q, block_k) from ``bench_results/flash_blocks_tuned.json``
    (written by ``examples/tune_flash_blocks.py`` when a TPU sweep at the
    flagship seq finds a winner), or ``(None, None)``.

    Read lazily at first kernel call (never at import: the gate needs a
    live backend) and adopted ONLY when the record's ``device_kind``
    matches the attached device — a winner swept on one TPU generation
    must not leak onto another with a different VMEM budget."""
    global _TUNED_CACHE
    if _TUNED_CACHE is None:
        from apex_tpu.utils.tuning import load_tuned_record

        q = k = None
        rec = load_tuned_record("flash_blocks_tuned.json", jax)
        if rec is not None:
            try:
                q, k = int(rec["block_q"]), int(rec["block_k"])
                if q <= 0 or k <= 0:
                    q = k = None
            except (KeyError, TypeError, ValueError):
                q = k = None
        _TUNED_CACHE = (q, k)
    return _TUNED_CACHE


def resolve_default_blocks(block_q=None, block_k=None):
    """Fill unset block sizes.  Precedence per dimension: explicit arg >
    ``APEX_TPU_FLASH_BLOCK_Q/K`` env > hardware-matched tuned file >
    built-in 256/512."""
    if block_q is None:
        tuned = None if _ENV_SET[0] else _tuned_blocks()[0]
        block_q = tuned or DEFAULT_BLOCK_Q
    if block_k is None:
        tuned = None if _ENV_SET[1] else _tuned_blocks()[1]
        block_k = tuned or DEFAULT_BLOCK_K
    return block_q, block_k
NEG_INF = -1e30
_LANES = 128   # TPU lane count: minor-dim tile
_SUBLANES = 8  # fp32 sublane tile


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _scratch(shape, dtype=jnp.float32):
    return pltpu.VMEM(shape, dtype)


def _flash_compiler_params():
    """All three kernels iterate grid (batch*heads, outer-block, inner-block)
    and accumulate scratch only over the *innermost* dim — dims 0/1 are
    independent, so tell Mosaic: it may split them across cores (megacore
    on v4/v5p) and reorder for pipelining; the innermost stays sequential
    (init-at-0 / finalize-at-last scratch carry)."""
    # jax >= 0.7 spells it CompilerParams; earlier releases TPUCompilerParams
    params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return params_cls(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pick_blocks(sq, sk, block_q, block_k):
    """Block sizes + padded lengths.  Blocks shrink to the (tile-aligned)
    sequence length; sequences pad up to a whole number of blocks, so
    non-power-of-two lengths never degrade to ``block = s`` VMEM blowups."""
    bq = min(block_q, _round_up(sq, _SUBLANES))
    bk = min(block_k, _round_up(sk, _LANES))
    return bq, bk, _round_up(sq, bq), _round_up(sk, bk)


def _pad_dim2(x, target):
    s = x.shape[2]
    if s == target:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, target - s), (0, 0)))


def _prep_segments(seg_q, seg_k, b, sq, sk, sq_p, sk_p, need):
    """Pad/create ``[b, s, 1]`` int32 segment-id arrays.  Pad sentinels
    differ on the q (-1) and k (-2) side so padded q rows attend nothing
    and real rows never attend padded keys."""
    if not need:
        return None, None
    if seg_q is None:
        seg_q = jnp.zeros((b, sq), jnp.int32)
    if seg_k is None:
        seg_k = jnp.zeros((b, sk), jnp.int32)
    seg_q = jnp.pad(seg_q.astype(jnp.int32), ((0, 0), (0, sq_p - sq)),
                    constant_values=-1)
    seg_k = jnp.pad(seg_k.astype(jnp.int32), ((0, 0), (0, sk_p - sk)),
                    constant_values=-2)
    return seg_q[..., None], seg_k[..., None]


# ---------------------------------------------------------------------------
# dropout: counter-based keep mask, regenerated identically in fwd and bwd
# ---------------------------------------------------------------------------


def _mix32(x):
    """murmur3 finalizer — full-avalanche 32-bit mix."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _keep_mask(seed, bh, rows, cols, rate):
    """Boolean keep mask over global (row, col) coordinates.

    Pure uint32 arithmetic (no pltpu PRNG) so the identical mask is
    produced on TPU and in interpret mode, and the backward kernels can
    regenerate it from the same (seed, coords) regardless of grid order.
    """
    h = _mix32(seed.astype(jnp.uint32) ^ jnp.uint32(0x9E3779B9))
    h = _mix32(h + jnp.uint32(bh))
    h = _mix32(h + rows.astype(jnp.uint32))  # (bq, 1)
    h = _mix32(h + cols.astype(jnp.uint32))  # (bq, bk)
    thresh = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return h >= thresh


def _coords(iq, jk, bq, bk, q_offset, kv_offset):
    rows = (q_offset + iq * bq
            + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0))
    cols = (kv_offset + jk * bk
            + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
    return rows, cols


def _block_mask(iq, jk, bq, bk, causal, q_offset, kv_offset,
                seg_q, seg_k):
    """Combined causal+segment mask for one (q-block, k-block), or None."""
    mask = None
    if causal:
        rows, cols = _coords(iq, jk, bq, bk, q_offset, kv_offset)
        mask = rows >= cols
    if seg_q is not None:
        sm = seg_q[:, None] == seg_k[None, :]
        mask = sm if mask is None else mask & sm
    return mask


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, scale, causal, q_offset, kv_offset, has_segments,
                dropout_rate):
    i = 3
    q_ref, k_ref, v_ref = refs[:3]
    seg_q_ref = seg_k_ref = seed_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = refs[i], refs[i + 1]
        i += 2
    if dropout_rate > 0.0:
        seed_ref = refs[i]
        i += 1
    o_ref, lse_ref, m_sc, l_sc, acc_sc = refs[i:i + 5]

    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    def _body():
        # Matmuls run in the INPUT dtype with fp32 accumulation: a
        # bf16xbf16->f32 MXU pass is ~2x the fp32 rate, and upcasting
        # the operands first forfeits that (r4 finding; the softmax/
        # rescale math stays fp32 below).  fp32 inputs are unaffected.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        seg_q = seg_q_ref[0, :, 0] if has_segments else None
        seg_k = seg_k_ref[0, :, 0] if has_segments else None
        mask = _block_mask(iq, jk, bq, bk, causal, q_offset, kv_offset,
                           seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m = m_sc[:, 0]
        l = l_sc[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        # Guard the all-masked row: with m_new == NEG_INF, exp(s - m_new)
        # would be exp(0) = 1 per masked entry (phantom mean(V) mass);
        # exp(s - 0) = exp(NEG_INF) = 0 is what we want.
        m_safe = jnp.where(m_new <= NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
        l_new = l * alpha + jnp.sum(p, axis=1)
        if dropout_rate > 0.0:
            rows, cols = _coords(iq, jk, bq, bk, q_offset, kv_offset)
            keep = _keep_mask(seed_ref[0], bh, rows, cols, dropout_rate)
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            p_acc = p
        # p quantized to V's dtype for the PV matmul (the fmha/flash
        # convention — the reference kernel holds P in fp16)
        acc_new = acc_sc[...] * alpha[:, None] + jax.lax.dot_general(
            p_acc.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[...] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new[:, None], l_sc.shape)
        acc_sc[...] = acc_new

    if causal:
        # Causal block skipping: a block whose max row < min col is fully
        # masked — no MXU work (its K/V copy is also elided via the index
        # map clamp in _fwd_call).
        run = (q_offset + (iq + 1) * bq - 1) >= (kv_offset + jk * bk)
        pl.when(run)(_body)
    else:
        _body()

    @pl.when(jk == num_kb - 1)
    def _finalize():
        l_fin = l_sc[:, 0]
        m_fin = m_sc[:, 0]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0, 0] = (acc_sc[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(l_fin == 0.0, NEG_INF,
                                  m_fin + jnp.log(l_safe))[:, None]


# ---------------------------------------------------------------------------
# backward: dq (k innermost) and dk/dv (q innermost)
# ---------------------------------------------------------------------------


def _bwd_p(s, lse, mask):
    """exp(s - lse) with the fully-masked-row guard (lse == NEG_INF)."""
    lse_safe = jnp.where(lse <= NEG_INF * 0.5, 0.0, lse)
    p = jnp.exp(s - lse_safe[:, None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    return p


def _dq_kernel(*refs, scale, causal, q_offset, kv_offset, has_segments,
               dropout_rate):
    i = 6
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    seg_q_ref = seg_k_ref = seed_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = refs[i], refs[i + 1]
        i += 2
    if dropout_rate > 0.0:
        seed_ref = refs[i]
        i += 1
    dq_ref, dq_sc = refs[i:i + 2]

    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    def _body():
        # input-dtype matmuls, fp32 accumulate (see _fwd_kernel note)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        seg_q = seg_q_ref[0, :, 0] if has_segments else None
        seg_k = seg_k_ref[0, :, 0] if has_segments else None
        mask = _block_mask(iq, jk, bq, bk, causal, q_offset, kv_offset,
                           seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = _bwd_p(s, lse, mask)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            rows, cols = _coords(iq, jk, bq, bk, q_offset, kv_offset)
            keep = _keep_mask(seed_ref[0], bh, rows, cols, dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta[:, None]) * scale
        dq_sc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        run = (q_offset + (iq + 1) * bq - 1) >= (kv_offset + jk * bk)
        pl.when(run)(_body)
    else:
        _body()

    @pl.when(jk == num_kb - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, q_offset, kv_offset, has_segments,
                dropout_rate):
    i = 6
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    seg_q_ref = seg_k_ref = seed_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = refs[i], refs[i + 1]
        i += 2
    if dropout_rate > 0.0:
        seed_ref = refs[i]
        i += 1
    dk_ref, dv_ref, dk_sc, dv_sc = refs[i:i + 4]

    bk = k_ref.shape[2]
    bq = q_ref.shape[2]
    bh = pl.program_id(0)
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    num_qb = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    def _body():
        # input-dtype matmuls, fp32 accumulate (see _fwd_kernel note)
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        seg_q = seg_q_ref[0, :, 0] if has_segments else None
        seg_k = seg_k_ref[0, :, 0] if has_segments else None
        mask = _block_mask(iq, jk, bq, bk, causal, q_offset, kv_offset,
                           seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = _bwd_p(s, lse, mask)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            rows, cols = _coords(iq, jk, bq, bk, q_offset, kv_offset)
            keep = _keep_mask(seed_ref[0], bh, rows, cols, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_drop = p
        dv_sc[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_sc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        run = (q_offset + (iq + 1) * bq - 1) >= (kv_offset + jk * bk)
        pl.when(run)(_body)
    else:
        _body()

    @pl.when(iq == num_qb - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _causal_jmax(i, bq, bk, q_offset, kv_offset, num_kb):
    """Last k-block index with any live (unmasked) column for q-block i."""
    jmax = (q_offset + (i + 1) * bq - 1 - kv_offset) // bk
    return jnp.clip(jmax, 0, num_kb - 1)


def _causal_imin(j, bq, bk, q_offset, kv_offset, num_qb):
    """First q-block index with any live row for k-block j."""
    imin = -((-(kv_offset + j * bk - q_offset - bq + 1)) // bq)
    return jnp.clip(imin, 0, num_qb - 1)


def _specs_fwd(h, bq, bk, d, causal, q_offset, kv_offset, num_kb):
    """Block specs for the (bh, i, j) grid (k innermost).  Under causal the
    k/v (and seg_k) index maps clamp j into the live range so skipped
    blocks re-reference the previous block and Pallas elides the copy."""

    def q_idx(bh_, i, j):
        return (bh_ // h, bh_ % h, i, 0)

    def k_idx(bh_, i, j):
        if causal:
            j = jnp.minimum(j, _causal_jmax(i, bq, bk, q_offset, kv_offset,
                                            num_kb))
        return (bh_ // h, bh_ % h, j, 0)

    def segq_idx(bh_, i, j):
        return (bh_ // h, i, 0)

    def segk_idx(bh_, i, j):
        if causal:
            j = jnp.minimum(j, _causal_jmax(i, bq, bk, q_offset, kv_offset,
                                            num_kb))
        return (bh_ // h, j, 0)

    return {
        "q": pl.BlockSpec((1, 1, bq, d), q_idx),
        "k": pl.BlockSpec((1, 1, bk, d), k_idx),
        "q_lse": pl.BlockSpec((1, 1, bq, 1), q_idx),
        "seg_q": pl.BlockSpec((1, bq, 1), segq_idx),
        "seg_k": pl.BlockSpec((1, bk, 1), segk_idx),
        "seed": pl.BlockSpec(memory_space=pltpu.SMEM),
    }


def _specs_dkv(h, bq, bk, d, causal, q_offset, kv_offset, num_qb):
    """Block specs for the transposed (bh, j, i) grid (q innermost)."""

    def q_idx(bh_, j, i):
        if causal:
            i = jnp.maximum(i, _causal_imin(j, bq, bk, q_offset, kv_offset,
                                            num_qb))
        return (bh_ // h, bh_ % h, i, 0)

    def k_idx(bh_, j, i):
        return (bh_ // h, bh_ % h, j, 0)

    def segq_idx(bh_, j, i):
        if causal:
            i = jnp.maximum(i, _causal_imin(j, bq, bk, q_offset, kv_offset,
                                            num_qb))
        return (bh_ // h, i, 0)

    def segk_idx(bh_, j, i):
        return (bh_ // h, j, 0)

    return {
        "q": pl.BlockSpec((1, 1, bq, d), q_idx),
        "k": pl.BlockSpec((1, 1, bk, d), k_idx),
        "q_lse": pl.BlockSpec((1, 1, bq, 1), q_idx),
        "seg_q": pl.BlockSpec((1, bq, 1), segq_idx),
        "seg_k": pl.BlockSpec((1, bk, 1), segk_idx),
        "seed": pl.BlockSpec(memory_space=pltpu.SMEM),
    }


def _resolve(scale, d):
    return (1.0 / (d ** 0.5)) if scale is None else scale


def _seed_array(dropout_seed):
    if dropout_seed is None:
        # Reachable only via the chunk entry points / vjp residuals, whose
        # public callers have already validated (rate > 0) => seed given.
        raise ValueError(
            "dropout_rate > 0 requires an explicit dropout_seed (vary it "
            "per training step; a silent constant seed would drop the same "
            "attention entries forever)")
    return jnp.asarray(dropout_seed, jnp.int32).reshape((1,))


def _fwd_call(q, k, v, seg_q, seg_k, seed, causal, scale, block_q, block_k,
              q_offset, kv_offset, dropout_rate):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk, sq_p, sk_p = _pick_blocks(sq, sk, block_q, block_k)
    seg_q, seg_k = _prep_segments(
        seg_q, seg_k, b, sq, sk, sq_p, sk_p,
        need=(seg_q is not None or seg_k is not None
              or sq_p != sq or sk_p != sk))
    has_segments = seg_q is not None
    qp, kp, vp = _pad_dim2(q, sq_p), _pad_dim2(k, sk_p), _pad_dim2(v, sk_p)
    num_kb = sk_p // bk
    sp = _specs_fwd(h, bq, bk, d, causal, q_offset, kv_offset, num_kb)

    kernel = functools.partial(
        _fwd_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, has_segments=has_segments,
        dropout_rate=dropout_rate,
    )
    in_specs = [sp["q"], sp["k"], sp["k"]]
    args = [qp, kp, vp]
    if has_segments:
        in_specs += [sp["seg_q"], sp["seg_k"]]
        args += [seg_q, seg_k]
    if dropout_rate > 0.0:
        in_specs += [sp["seed"]]
        args += [_seed_array(seed)]

    out, lse4 = pl.pallas_call(
        kernel,
        grid=(b * h, sq_p // bq, num_kb),
        in_specs=in_specs,
        out_specs=[sp["q"], sp["q_lse"]],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((bq, _LANES)),
            _scratch((bq, _LANES)),
            _scratch((bq, d)),
        ],
        compiler_params=_flash_compiler_params(),
        interpret=_interpret(),
    )(*args)
    return out[:, :, :sq], lse4[:, :, :sq, 0]


def dq_chunk(q, k, v, do, lse, delta, *, causal, scale=None,
             block_q=None, block_k=None,
             q_offset=0, kv_offset=0, segment_ids_q=None,
             segment_ids_kv=None, dropout_rate=0.0, dropout_seed=None):
    """dq contribution of one K/V chunk given the *global* ``lse``/``delta``.

    The flash-backward identity: each (q-block, k-block) pair's gradient
    depends on other blocks only through (lse, delta), so ring backward can
    re-drive this per visiting chunk.
    """
    block_q, block_k = resolve_default_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk, sq_p, sk_p = _pick_blocks(sq, sk, block_q, block_k)
    seg_q, seg_k = _prep_segments(
        segment_ids_q, segment_ids_kv, b, sq, sk, sq_p, sk_p,
        need=(segment_ids_q is not None or segment_ids_kv is not None
              or sq_p != sq or sk_p != sk))
    has_segments = seg_q is not None
    num_kb = sk_p // bk
    sp = _specs_fwd(h, bq, bk, d, causal, q_offset, kv_offset, num_kb)

    kernel = functools.partial(
        _dq_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, has_segments=has_segments,
        dropout_rate=dropout_rate,
    )
    in_specs = [sp["q"], sp["k"], sp["k"], sp["q"], sp["q_lse"],
                sp["q_lse"]]
    args = [_pad_dim2(q, sq_p), _pad_dim2(k, sk_p), _pad_dim2(v, sk_p),
            _pad_dim2(do, sq_p),
            _pad_dim2(lse[..., None], sq_p),
            _pad_dim2(delta[..., None], sq_p)]
    if has_segments:
        in_specs += [sp["seg_q"], sp["seg_k"]]
        args += [seg_q, seg_k]
    if dropout_rate > 0.0:
        in_specs += [sp["seed"]]
        args += [_seed_array(dropout_seed)]

    dq = pl.pallas_call(
        kernel,
        grid=(b * h, sq_p // bq, num_kb),
        in_specs=in_specs,
        out_specs=sp["q"],
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        scratch_shapes=[_scratch((bq, d))],
        compiler_params=_flash_compiler_params(),
        interpret=_interpret(),
    )(*args)
    return dq[:, :, :sq]


def dkv_chunk(q, k, v, do, lse, delta, *, causal, scale=None,
              block_q=None, block_k=None,
              q_offset=0, kv_offset=0, segment_ids_q=None,
              segment_ids_kv=None, dropout_rate=0.0, dropout_seed=None):
    """(dk, dv) of one K/V chunk given the global ``lse``/``delta``."""
    block_q, block_k = resolve_default_blocks(block_q, block_k)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk, sq_p, sk_p = _pick_blocks(sq, sk, block_q, block_k)
    seg_q, seg_k = _prep_segments(
        segment_ids_q, segment_ids_kv, b, sq, sk, sq_p, sk_p,
        need=(segment_ids_q is not None or segment_ids_kv is not None
              or sq_p != sq or sk_p != sk))
    has_segments = seg_q is not None
    num_qb = sq_p // bq
    sp = _specs_dkv(h, bq, bk, d, causal, q_offset, kv_offset, num_qb)

    kernel = functools.partial(
        _dkv_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset, has_segments=has_segments,
        dropout_rate=dropout_rate,
    )
    in_specs = [sp["q"], sp["k"], sp["k"], sp["q"], sp["q_lse"],
                sp["q_lse"]]
    args = [_pad_dim2(q, sq_p), _pad_dim2(k, sk_p), _pad_dim2(v, sk_p),
            _pad_dim2(do, sq_p),
            _pad_dim2(lse[..., None], sq_p),
            _pad_dim2(delta[..., None], sq_p)]
    if has_segments:
        in_specs += [sp["seg_q"], sp["seg_k"]]
        args += [seg_q, seg_k]
    if dropout_rate > 0.0:
        in_specs += [sp["seed"]]
        args += [_seed_array(dropout_seed)]

    dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h, sk_p // bk, num_qb),
        in_specs=in_specs,
        out_specs=[sp["k"], sp["k"]],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk_p, d), v.dtype),
        ],
        scratch_shapes=[_scratch((bk, d)), _scratch((bk, d))],
        compiler_params=_flash_compiler_params(),
        interpret=_interpret(),
    )(*args)
    return dk[:, :, :sk], dv[:, :, :sk]


# ---------------------------------------------------------------------------
# custom VJP + public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash_core(q, k, v, seg_q, seg_k, seed,
                causal, scale, block_q, block_k, q_offset, kv_offset,
                dropout_rate):
    return _fwd_call(q, k, v, seg_q, seg_k, seed, causal, scale, block_q,
                     block_k, q_offset, kv_offset, dropout_rate)


def _flash_vjp_fwd(q, k, v, seg_q, seg_k, seed, causal, scale, block_q,
                   block_k, q_offset, kv_offset, dropout_rate):
    out, lse = _fwd_call(q, k, v, seg_q, seg_k, seed, causal, scale,
                         block_q, block_k, q_offset, kv_offset,
                         dropout_rate)
    return (out, lse), (q, k, v, seg_q, seg_k, seed, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, q_offset, kv_offset,
                   dropout_rate, res, cts):
    q, k, v, seg_q, seg_k, seed, out, lse = res
    do, _ = cts
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    kw = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              q_offset=q_offset, kv_offset=kv_offset,
              segment_ids_q=seg_q, segment_ids_kv=seg_k,
              dropout_rate=dropout_rate, dropout_seed=seed)
    dq = dq_chunk(q, k, v, do, lse, delta, **kw)
    dk, dv = dkv_chunk(q, k, v, do, lse, delta, **kw)
    return dq, dk, dv, None, None, None


_flash_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_with_lse(
    q, k, v,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    *,
    segment_ids_q=None,
    segment_ids_kv=None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
):
    """Attention returning ``(out, lse)``.

    ``segment_ids_q/kv`` (int ≥ 0, ``[b, s]``) mask attention across
    segment boundaries — packed-varlen (fmha cu_seqlens) and padding masks
    in one mechanism.  ``dropout_rate``/``dropout_seed`` apply attention
    dropout after softmax (seed may be a traced scalar; vary it per step).

    NB: the VJP propagates the cotangent of ``out`` only; ``lse`` is a
    by-product for sharded-softmax composition (ring attention defines its
    own VJP at the ring level for exactly that reason).
    """
    block_q, block_k = resolve_default_blocks(block_q, block_k)
    seed = _seed_array(dropout_seed) if dropout_rate > 0.0 else None
    return _flash_core(q, k, v, segment_ids_q, segment_ids_kv, seed,
                       causal, scale, block_q, block_k, q_offset, kv_offset,
                       float(dropout_rate))


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    *,
                    segment_ids_q=None,
                    segment_ids_kv=None,
                    dropout_rate: float = 0.0,
                    dropout_seed=None):
    """``softmax(q k^T * scale [+ masks]) v`` without materialising the
    score matrix.  ``q,k,v: [batch, heads, seq, head_dim]``."""
    out, _ = flash_attention_with_lse(
        q, k, v, causal, scale, block_q, block_k, 0, 0,
        segment_ids_q=segment_ids_q, segment_ids_kv=segment_ids_kv,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed)
    return out
