"""Flash attention — Pallas TPU kernels with a custom VJP.

Capability parity target: ``apex/contrib/fmha`` (fixed-shape fp16 fused MHA,
seqlens ≤512, ``apex/contrib/csrc/fmha/fmha_api.cpp``) and the fused
softmax-attention core of ``apex/contrib/multihead_attn`` — rebuilt as a
*blockwise online-softmax* kernel family with none of the shape limits
(any seqlen, any head dim that tiles to the MXU, fp32/bf16).

Design (the standard flash decomposition, mapped to TPU):

- forward: grid ``(batch*heads, q_blocks, k_blocks)`` with the k-block index
  innermost; the running row-max ``m``, row-sum ``l`` and output accumulator
  live in VMEM scratch that persists across the k sweep, so K/V *stream*
  through VMEM one block at a time (Pallas double-buffers the HBM→VMEM
  copies against the MXU work) and VMEM holds O(block) state regardless of
  sequence length — the softmax never materialises the ``[sq, sk]`` score
  matrix (the reason apex's fused softmax caps at 16384 keys disappears).
- saves ``(out, lse)`` only — the activation-memory profile of the fused
  kernels (``fmha`` saves the same) rather than O(s²) probabilities.
- backward: one kernel recomputes scores per (q-block, k-block) pair to form
  ``dq`` (k innermost, dq in scratch), a second forms ``dk/dv`` over the
  transposed blocking (q innermost), both seeded with
  ``delta = rowsum(do * o)`` computed in plain XLA.
- ``q_offset``/``kv_offset`` place a q/k shard at its global sequence
  position so causal masking stays correct when the sequence is sharded —
  the hook ring attention (context parallelism,
  :mod:`apex_tpu.transformer.context_parallel`) builds on.  The backward
  entry points (:func:`dq_chunk`, :func:`dkv_chunk`) are exposed for the
  same reason: ring backward re-drives them per visiting chunk with the
  *global* lse.
- ``interpret=True`` is selected automatically off-TPU so the same code runs
  in the CPU test mesh.

Layouts: ``q, k, v: [batch, heads, seq, head_dim]`` (BHSD).  ``lse`` rides
as ``[b, h, s, 1]`` inside kernels (trailing singleton keeps the TPU
(sublane, lane) tiling rule satisfied for any block) and is squeezed at the
API boundary.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "dq_chunk",
    "dkv_chunk",
]

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30
_LANES = 128  # scratch minor dim (TPU lane count)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _scratch(shape, dtype=jnp.float32):
    return pltpu.VMEM(shape, dtype)


def _pick_block(s, block):
    while block > 8 and s % block != 0:
        block //= 2
    if s % block != 0:
        block = s
    return block


def _causal_mask(s, rows0, cols0, bq, bk):
    rows = rows0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = cols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                scale, causal, q_offset, kv_offset):
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[2]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    q = q_ref[0, 0].astype(jnp.float32) * scale
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if causal:
        s = _causal_mask(s, q_offset + iq * bq, kv_offset + jk * bk, bq, bk)

    m = m_sc[:, 0]
    l = l_sc[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1)
    acc_new = acc_sc[...] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_sc[...] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
    l_sc[...] = jnp.broadcast_to(l_new[:, None], l_sc.shape)
    acc_sc[...] = acc_new

    @pl.when(jk == num_kb - 1)
    def _finalize():
        l_fin = l_sc[:, 0]
        m_fin = m_sc[:, 0]
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0, 0] = (acc_sc[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(l_fin == 0.0, NEG_INF,
                                  m_fin + jnp.log(l_safe))[:, None]


# ---------------------------------------------------------------------------
# backward: dq (k innermost) and dk/dv (q innermost)
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_sc, *, scale, causal, q_offset, kv_offset):
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[2]
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = _causal_mask(s, q_offset + iq * bq, kv_offset + jk * bk, bq, bk)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None]) * scale
    dq_sc[...] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(jk == num_kb - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal,
                q_offset, kv_offset):
    bk, d = k_ref.shape[2], k_ref.shape[3]
    bq = q_ref.shape[2]
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    num_qb = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = _causal_mask(s, q_offset + iq * bq, kv_offset + jk * bk, bq, bk)
    p = jnp.exp(s - lse[:, None])
    dv_sc[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta[:, None]) * scale
    dk_sc[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(iq == num_qb - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _q_spec(h, block, d):
    """q/do/o blocked on the q grid dim (dim 1), constant over dim 2."""
    return pl.BlockSpec((1, 1, block, d),
                        lambda bh, i, j: (bh // h, bh % h, i, 0))


def _k_spec(h, block, d):
    """k/v blocked on the k grid dim (dim 2)."""
    return pl.BlockSpec((1, 1, block, d),
                        lambda bh, i, j: (bh // h, bh % h, j, 0))


def _q_lse_spec(h, block):
    return pl.BlockSpec((1, 1, block, 1),
                        lambda bh, i, j: (bh // h, bh % h, i, 0))


def _kq_spec(h, block, d):
    """q-side tensors when the *k* block is grid dim 1 and q sweeps dim 2."""
    return pl.BlockSpec((1, 1, block, d),
                        lambda bh, j, i: (bh // h, bh % h, i, 0))


def _kk_spec(h, block, d):
    return pl.BlockSpec((1, 1, block, d),
                        lambda bh, j, i: (bh // h, bh % h, j, 0))


def _kq_lse_spec(h, block):
    return pl.BlockSpec((1, 1, block, 1),
                        lambda bh, j, i: (bh // h, bh % h, i, 0))


def _resolve(scale, d):
    return (1.0 / (d ** 0.5)) if scale is None else scale


def _fwd_call(q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset,
    )
    out, lse4 = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            _q_spec(h, block_q, d),
            _k_spec(h, block_k, d),
            _k_spec(h, block_k, d),
        ],
        out_specs=[
            _q_spec(h, block_q, d),
            _q_lse_spec(h, block_q),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, _LANES)),
            _scratch((block_q, _LANES)),
            _scratch((block_q, d)),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse4[..., 0]


def dq_chunk(q, k, v, do, lse, delta, *, causal, scale=None,
             block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
             q_offset=0, kv_offset=0):
    """dq contribution of one K/V chunk given the *global* ``lse``/``delta``.

    The flash-backward identity: each (q-block, k-block) pair's gradient
    depends on other blocks only through (lse, delta), so ring backward can
    re-drive this per visiting chunk.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    kernel = functools.partial(
        _dq_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset,
    )
    return pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            _q_spec(h, block_q, d),
            _k_spec(h, block_k, d),
            _k_spec(h, block_k, d),
            _q_spec(h, block_q, d),
            _q_lse_spec(h, block_q),
            _q_lse_spec(h, block_q),
        ],
        out_specs=_q_spec(h, block_q, d),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=_interpret(),
    )(q, k, v, do, lse[..., None], delta[..., None])


def dkv_chunk(q, k, v, do, lse, delta, *, causal, scale=None,
              block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
              q_offset=0, kv_offset=0):
    """(dk, dv) of one K/V chunk given the global ``lse``/``delta``."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    kernel = functools.partial(
        _dkv_kernel, scale=_resolve(scale, d), causal=causal,
        q_offset=q_offset, kv_offset=kv_offset,
    )
    return pl.pallas_call(
        kernel,
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            _kq_spec(h, block_q, d),
            _kk_spec(h, block_k, d),
            _kk_spec(h, block_k, d),
            _kq_spec(h, block_q, d),
            _kq_lse_spec(h, block_q),
            _kq_lse_spec(h, block_q),
        ],
        out_specs=[
            _kk_spec(h, block_k, d),
            _kk_spec(h, block_k, d),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=_interpret(),
    )(q, k, v, do, lse[..., None], delta[..., None])


# ---------------------------------------------------------------------------
# custom VJP + public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(
    q, k, v,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    q_offset: int = 0,
    kv_offset: int = 0,
):
    """Attention returning ``(out, lse)``.

    NB: the VJP propagates the cotangent of ``out`` only; ``lse`` is a
    by-product for sharded-softmax composition (ring attention defines its
    own VJP at the ring level for exactly that reason).
    """
    return _fwd_call(q, k, v, causal, scale, block_q, block_k, q_offset,
                     kv_offset)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, q_offset,
                   kv_offset):
    out, lse = _fwd_call(q, k, v, causal, scale, block_q, block_k, q_offset,
                         kv_offset)
    return (out, lse), (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, q_offset, kv_offset,
                   res, cts):
    q, k, v, out, lse = res
    do, _ = cts
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    kw = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              q_offset=q_offset, kv_offset=kv_offset)
    dq = dq_chunk(q, k, v, do, lse, delta, **kw)
    dk, dv = dkv_chunk(q, k, v, do, lse, delta, **kw)
    return dq, dk, dv


flash_attention_with_lse.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """``softmax(q k^T * scale [+ causal mask]) v`` without materialising
    the score matrix.  ``q,k,v: [batch, heads, seq, head_dim]``."""
    out, _ = flash_attention_with_lse(q, k, v, causal, scale, block_q,
                                      block_k, 0, 0)
    return out
