"""Fused softmax-cross-entropy with label smoothing — the ``xentropy`` analog.

Behavioral spec: ``apex/contrib/xentropy/softmax_xentropy.py:6-30`` over
``apex/contrib/csrc/xentropy/xentropy_kernel.cu``:

- forward (``:424-431``): per-row
  ``loss = (lse - Σlogits/C) * smoothing - log_prob[label] * (1-smoothing)``
  with ``lse = max + log Σ exp(x - max)``; rows whose ``label ==
  padding_idx`` are zeroed (``softmax_xentropy.py:11``);
- the kernel saves only ``max_log_sum_exp`` (one scalar per row) for the
  backward — *not* the softmax probabilities — and recomputes
  ``exp(logit - lse)`` from the logits in bprop (``:444-470``):
  ``dlogits = dloss * (exp(x - lse) - onehot*(1-smoothing) - smoothing/C)``,
  zeroed on padding rows.

The custom_vjp below has exactly that residual set (logits, lse, labels),
so activation memory matches the fused kernel: O(rows) extra instead of a
full [rows, classes] probability tensor.  ``half_to_float=True`` returns
fp32 losses from half logits (``softmax_xentropy.py:9``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy_loss"]


def _lse(x32):
    m = jnp.max(x32, axis=-1)
    return m + jnp.log(jnp.sum(jnp.exp(x32 - m[..., None]), axis=-1))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(
    logits,
    labels,
    smoothing: float = 0.0,
    padding_idx: int = 0,
    half_to_float: bool = False,
):
    """Per-row smoothed CE losses of shape ``labels.shape``.

    ``logits: [..., C]`` (any float dtype; math in fp32), ``labels: [...]``
    int.  Matches ``SoftmaxCrossEntropyLoss.apply`` including the
    padding-row zeroing.
    """
    loss, _ = _fwd_math(logits, labels, smoothing, padding_idx)
    if half_to_float or logits.dtype == jnp.float32:
        return loss
    return jnp.asarray(loss, logits.dtype)


def _fwd_math(logits, labels, smoothing, padding_idx):
    x32 = jnp.asarray(logits, jnp.float32)
    lse = _lse(x32)
    label_logit = jnp.take_along_axis(
        x32, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    log_prob = label_logit - lse
    C = x32.shape[-1]
    sum_logits = jnp.sum(x32, axis=-1)
    loss = (lse - sum_logits / C) * smoothing - log_prob * (1.0 - smoothing)
    loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss, lse


def _vjp_fwd(logits, labels, smoothing, padding_idx, half_to_float):
    loss, lse = _fwd_math(logits, labels, smoothing, padding_idx)
    if not (half_to_float or logits.dtype == jnp.float32):
        loss = jnp.asarray(loss, logits.dtype)
    # residuals: logits + one lse scalar per row (xentropy_kernel.cu:430)
    return loss, (logits, lse, labels)


def _vjp_bwd(smoothing, padding_idx, half_to_float, res, dloss):
    logits, lse, labels = res
    x32 = jnp.asarray(logits, jnp.float32)
    C = x32.shape[-1]
    probs = jnp.exp(x32 - lse[..., None])
    onehot = jax.nn.one_hot(labels, C, dtype=jnp.float32)
    g = probs - onehot * (1.0 - smoothing) - smoothing / C
    d32 = jnp.asarray(dloss, jnp.float32)
    d32 = jnp.where(labels == padding_idx, 0.0, d32)
    dlogits = d32[..., None] * g
    return (jnp.asarray(dlogits, logits.dtype), None)


softmax_cross_entropy_loss.defvjp(_vjp_fwd, _vjp_bwd)
