"""apex_tpu.ops — fused functional ops.

TPU-native replacements for the reference's fused CUDA op zoo:

- :mod:`softmax` — the megatron scale-mask-softmax family
  (``csrc/megatron/scaled_*_softmax*``, frontend
  ``apex/transformer/functional/fused_softmax.py``)
- :mod:`dense` — GEMM+bias(+GeLU) epilogue fusions
  (``csrc/fused_dense_cuda.cu``, ``apex/fused_dense``)
- :mod:`mlp` — whole-MLP forward/backward (``csrc/mlp_cuda.cu``, ``apex/mlp``)
- :mod:`flash_attention` — Pallas blockwise attention kernels
  (``apex/contrib/csrc/fmha``, ``apex/contrib/multihead_attn`` parity)
- :mod:`xentropy` — softmax-cross-entropy saving only max+logsumexp
  (``apex/contrib/csrc/xentropy``)
- :mod:`pallas_norm` — Pallas row-norm fast path
  (``apex/contrib/csrc/layer_norm`` FastLayerNorm analog)
"""

from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    generic_scaled_masked_softmax,
    FusedScaleMaskSoftmax,
    AttnMaskType,
)
from apex_tpu.ops.dense import (  # noqa: F401
    fused_dense,
    fused_dense_gelu_dense,
    FusedDense,
    FusedDenseGeluDense,
)
from apex_tpu.ops.mlp import MLP, mlp_forward  # noqa: F401
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss  # noqa: F401
from apex_tpu.ops import pallas_norm  # noqa: F401

from apex_tpu.ops.flash_attention import (  # noqa: E402
    flash_attention,
    flash_attention_with_lse,
)
