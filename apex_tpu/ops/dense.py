"""Fused dense — GEMM + bias (+ GeLU + GEMM + bias) epilogue fusions.

Behavioral spec: ``apex/fused_dense/fused_dense.py`` (``FusedDenseFunc:7``,
``FusedDenseGeluDenseFunc:35``, modules ``:65,83``) over
``csrc/fused_dense_cuda.cu`` (cuBLASLt ``BIAS`` and ``GELU_AUX_BIAS``
epilogues, dgelu+bgrad fused backward ``:194-232``).

On TPU these are exactly the fusions XLA performs from the naive
expression — a ``dot_general`` with a bias add and GeLU fuses into one MXU
pass with the epilogue on the VPU.  So the forward code *is* the naive
expression; what we preserve from the reference:

- GeLU uses the exact (erf) formulation, matching cuBLASLt's
  ``CUBLASLT_EPILOGUE_GELU_AUX_BIAS`` (erf-based, not tanh-approx);
- the gelu-input ("aux") is the saved residual in the packed two-GEMM
  backward — ``jax.checkpoint``-friendly because it falls out of the
  functional form automatically;
- weight layout follows the torch convention of the reference modules
  (``weight: [out, in]``, ``y = x @ w.T + b``) so migrated checkpoints map
  1:1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn
except Exception:  # pragma: no cover
    nn = None

__all__ = ["fused_dense", "fused_dense_gelu_dense", "FusedDense", "FusedDenseGeluDense"]


def fused_dense(x, weight, bias: Optional[jax.Array] = None):
    """GEMM + bias (``fused_dense_function``, ``apex/fused_dense/fused_dense.py:27``).

    ``weight``: [out_features, in_features] (torch layout).
    """
    y = jnp.dot(x, weight.T, preferred_element_type=x.dtype)
    if bias is not None:
        y = y + bias
    return y


def fused_dense_gelu_dense(x, weight1, bias1, weight2, bias2):
    """GEMM+bias+GeLU+GEMM+bias (``fused_dense_gelu_dense_function``,
    ``fused_dense.py:31``)."""
    h = fused_dense(x, weight1, bias1)
    h = jax.nn.gelu(h, approximate=False)  # erf GeLU = cuBLASLt epilogue
    return fused_dense(h, weight2, bias2)


if nn is not None:

    class FusedDense(nn.Module):
        """Module analog of ``apex.fused_dense.FusedDense`` (``:65``)."""

        in_features: int
        out_features: int
        use_bias: bool = True
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            w = self.param(
                "weight",
                nn.initializers.lecun_normal(),
                (self.out_features, self.in_features),
                self.param_dtype,
            )
            b = (
                self.param(
                    "bias", nn.initializers.zeros, (self.out_features,),
                    self.param_dtype,
                )
                if self.use_bias
                else None
            )
            return fused_dense(x, jnp.asarray(w, x.dtype),
                               None if b is None else jnp.asarray(b, x.dtype))

    class FusedDenseGeluDense(nn.Module):
        """Module analog of ``apex.fused_dense.FusedDenseGeluDense`` (``:83``)."""

        in_features: int
        intermediate_features: int
        out_features: int
        param_dtype: jnp.dtype = jnp.float32

        @nn.compact
        def __call__(self, x):
            k = nn.initializers.lecun_normal()
            w1 = self.param(
                "weight1", k, (self.intermediate_features, self.in_features),
                self.param_dtype,
            )
            b1 = self.param(
                "bias1", nn.initializers.zeros, (self.intermediate_features,),
                self.param_dtype,
            )
            w2 = self.param(
                "weight2", k, (self.out_features, self.intermediate_features),
                self.param_dtype,
            )
            b2 = self.param(
                "bias2", nn.initializers.zeros, (self.out_features,),
                self.param_dtype,
            )
            cast = lambda t: jnp.asarray(t, x.dtype)
            return fused_dense_gelu_dense(
                x, cast(w1), cast(b1), cast(w2), cast(b2)
            )

else:  # pragma: no cover
    FusedDense = FusedDenseGeluDense = None
