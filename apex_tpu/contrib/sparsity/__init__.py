"""ASP 2:4 structured sparsity (reference ``apex/contrib/sparsity``)."""

from apex_tpu.contrib.sparsity.asp import (
    ASP,
    SparseOptimizer,
    apply_masks,
    mask_sparsity,
)
from apex_tpu.contrib.sparsity.masklib import (
    create_mask,
    m4n2_1d,
    m4n2_2d_best,
    mn_1d_best,
    mn_2d_best,
)
from apex_tpu.contrib.sparsity.permutation import (
    kept_magnitude,
    permuted_mask,
    search_permutation,
)

__all__ = [
    "ASP",
    "SparseOptimizer",
    "apply_masks",
    "mask_sparsity",
    "create_mask",
    "m4n2_1d",
    "m4n2_2d_best",
    "mn_1d_best",
    "mn_2d_best",
    "kept_magnitude",
    "permuted_mask",
    "search_permutation",
]
