"""Input-channel permutation search for n:m sparsity.

Behavioral spec: ``apex/contrib/sparsity/permutation_lib.py`` (and its CUDA
search kernels ``permutation_search_kernels.cu``): find a permutation of the
*input channels* (mask-group dimension) that maximizes the magnitude kept by
the n:m mask, because grouping correlated channels together lets the 2-of-4
pattern keep more signal ("Channel Permutations for N:M Sparsity",
Pool & Yu, NeurIPS'21 — the reference implements this paper).

TPU-first divergence: the reference walks the torch.fx graph to apply one
permutation consistently across producer/consumer layers; this functional
API searches and returns ``(permutation, mask)`` per weight and computes
the mask **in permuted space, un-permuted back to the original layout** —
the kept-magnitude benefit is identical, no graph surgery is needed, and
since TPUs have no 2:4 hardware layout constraint the un-permuted mask is
exactly as executable as a permuted one.  The search itself is the
bounded-greedy column-swap ascent the reference's kernels implement.
"""

from __future__ import annotations

import numpy as np

from apex_tpu.contrib.sparsity import masklib

__all__ = ["search_permutation", "permuted_mask", "kept_magnitude"]


def _group_scores(mat_abs: np.ndarray, m: int, n: int) -> np.ndarray:
    """Kept magnitude per m-wide column group under the best n:m 1d mask:
    sum over rows of the top-n |w| within each group."""
    rows, cols = mat_abs.shape
    g = mat_abs.reshape(rows, cols // m, m)
    topn = np.sort(g, axis=2)[:, :, m - n:]
    return topn.sum(axis=(0, 2))


def kept_magnitude(mat_abs: np.ndarray, m: int = 4, n: int = 2) -> float:
    return float(_group_scores(mat_abs, m, n).sum())


def search_permutation(
    weight,
    m: int = 4,
    n: int = 2,
    max_passes: int = 10,
    seed: int = 0,
):
    """Greedy column-swap ascent on kept magnitude.

    ``weight``: 2D ``[rows, channels]`` (channels = the pruned direction,
    padded to a multiple of ``m`` by the caller or here).  Returns
    ``(perm, gain)`` where ``perm`` indexes the original channels and
    ``gain`` is the kept-magnitude improvement over identity.

    Each pass proposes swaps between columns of *different* groups (swaps
    within a group change nothing) and applies a swap when it improves the
    two affected groups' combined kept magnitude; stops when a full pass
    finds no improving swap or after ``max_passes``.
    """
    mat = np.abs(np.asarray(weight, np.float32))
    rows, cols = mat.shape
    pad = (-cols) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((rows, pad), np.float32)], 1)
        cols += pad
    perm = np.arange(cols)
    rng = np.random.RandomState(seed)
    base = kept_magnitude(mat, m, n)

    cur = mat.copy()
    scores = _group_scores(cur, m, n)
    n_groups = cols // m

    def group_score(block):
        topn = np.sort(block, axis=1)[:, m - n:]
        return topn.sum()

    for _ in range(max_passes):
        improved = False
        order = rng.permutation(cols)
        for a in order:
            ga = a // m
            # best swap partner for column a among a sampled set of columns
            candidates = rng.choice(cols, size=min(cols, 64), replace=False)
            best_gain, best_b = 0.0, -1
            for b in candidates:
                gb = b // m
                if gb == ga:
                    continue
                cur[:, [a, b]] = cur[:, [b, a]]
                new_a = group_score(cur[:, ga * m:(ga + 1) * m])
                new_b = group_score(cur[:, gb * m:(gb + 1) * m])
                gain = (new_a + new_b) - (scores[ga] + scores[gb])
                cur[:, [a, b]] = cur[:, [b, a]]
                if gain > best_gain + 1e-7:
                    best_gain, best_b = gain, b
            if best_b >= 0:
                b = best_b
                gb = b // m
                cur[:, [a, b]] = cur[:, [b, a]]
                perm[[a, b]] = perm[[b, a]]
                scores[ga] = group_score(cur[:, ga * m:(ga + 1) * m])
                scores[gb] = group_score(cur[:, gb * m:(gb + 1) * m])
                improved = True
        if not improved:
            break

    gain = float(scores.sum() - base)
    return perm[:cols - pad] if pad == 0 else perm, gain


def permuted_mask(weight, pattern: str = "m4n2_1d", m: int = 4, n: int = 2,
                  max_passes: int = 10, seed: int = 0):
    """n:m mask computed after channel permutation, returned in the
    original (un-permuted) layout — drop-in better mask for
    :func:`apex_tpu.contrib.sparsity.masklib.create_mask`."""
    import jax.numpy as jnp

    mat = masklib._to_matrix(weight)
    rows, cols = mat.shape
    pad = (-cols) % m
    mat_np = np.asarray(mat, np.float32)
    if pad:
        mat_np = np.concatenate(
            [mat_np, np.zeros((rows, pad), np.float32)], 1)
    perm, _gain = search_permutation(mat_np, m=m, n=n,
                                     max_passes=max_passes, seed=seed)
    permuted = mat_np[:, perm]
    mask_p = np.asarray(masklib.mn_1d_best(permuted, m, n)
                        if pattern == "m4n2_1d"
                        else masklib.mn_2d_best(permuted, m, n))
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    mask2d = jnp.asarray(mask_p[:, inv][:, :cols])
    return masklib._from_matrix(mask2d, weight.shape).astype(weight.dtype)
