"""ASP — automatic structured (2:4) sparsity for training.

Behavioral spec: ``apex/contrib/sparsity/asp.py`` —
``init_model_for_pruning`` (whitelist-module selection + mask buffers,
``:40-116``), ``init_optimizer_for_pruning`` (mask grads before / params
after the step, ``:185-211``), ``compute_sparse_masks``/
``restore_pruned_weights``/``is_sparsity_enabled`` (``:213-290``),
``prune_trained_model`` (``:292``).

TPU-first redesign: no monkey-patching or module mutation.  Masks are an
explicit pytree mirroring ``params`` (scalar ``1.0`` for non-pruned
leaves, so ``apply_masks`` is a plain fused tree-multiply under jit), and
the optimizer hook is :class:`SparseOptimizer`, a wrapper honoring the
framework's ``opt.step(grads, state, params, ...)`` protocol — the
functional analog of the reference's patched ``optimizer.step``.
Restoring dense weights is the caller keeping the pre-pruning params (pure
functions make ``allow_recompute_mask`` storage unnecessary).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.masklib import create_mask

__all__ = ["ASP", "SparseOptimizer", "apply_masks", "mask_sparsity"]


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def apply_masks(tree, masks):
    """Elementwise ``tree * masks`` (masks carry scalar 1.0 off the pruned
    leaves); jit-friendly."""
    return jax.tree_util.tree_map(
        lambda x, m: x * jnp.asarray(m, jnp.asarray(x).dtype), tree, masks)


def mask_sparsity(masks) -> dict:
    """{path: fraction_zero} for the pruned leaves."""
    out = {}
    for path, m in jax.tree_util.tree_leaves_with_path(masks):
        m = jnp.asarray(m)
        if m.ndim == 0:
            continue
        out[_path_str(path)] = float(1.0 - m.sum() / m.size)
    return out


class SparseOptimizer:
    """Masked-step wrapper: grads are masked before the inner step and the
    stepped params are re-masked after (the reference's ``__step`` patch,
    ``asp.py:197-211``), so pruned weights stay exactly zero through
    momentum/weight-decay updates."""

    def __init__(self, opt, masks):
        self.opt = opt
        self.masks = masks

    def init(self, params):
        return self.opt.init(params)

    def step(self, grads, state, params, **kwargs):
        grads = apply_masks(grads, self.masks)
        new_params, new_state = self.opt.step(grads, state, params, **kwargs)
        return apply_masks(new_params, self.masks), new_state

    def __getattr__(self, name):
        return getattr(self.opt, name)


@dataclasses.dataclass
class ASP:
    """Functional ASP.

    ``mask_calculator``: pattern string (``"m4n2_1d"``, ``"m4n2_2d_best"``)
    or a callable ``weight -> mask``; ``allow_permutation`` routes through
    the channel-permutation search
    (:func:`apex_tpu.contrib.sparsity.permutation.permuted_mask`).
    Eligibility mirrors the reference whitelist (Linear/Conv weights): leaf
    name in ``param_names``, ndim ≥ 2, and both matrix dims ≥ ``m`` after
    the [out, reduction] view; ``allowed/disallowed_layer_names`` filter on
    path substrings.
    """

    mask_calculator: Union[str, Callable] = "m4n2_1d"
    param_names: Sequence[str] = ("kernel",)
    allowed_layer_names: Optional[Sequence[str]] = None
    disallowed_layer_names: Sequence[str] = ()
    allow_permutation: bool = False
    m: int = 4
    n: int = 2

    def _eligible(self, path, leaf) -> bool:
        s = _path_str(path)
        name = s.rsplit("/", 1)[-1]
        if name not in self.param_names:
            return False
        x = jnp.asarray(leaf)
        if x.ndim < 2 or x.shape[-1] < self.m:
            return False
        red = 1
        for d in x.shape[:-1]:
            red *= d
        if red < self.m:
            return False
        if self.allowed_layer_names is not None and not any(
                a in s for a in self.allowed_layer_names):
            return False
        if any(d in s for d in self.disallowed_layer_names):
            return False
        return True

    def eligible_paths(self, params):
        return [_path_str(p)
                for p, leaf in jax.tree_util.tree_leaves_with_path(params)
                if self._eligible(p, leaf)]

    def compute_sparse_masks(self, params):
        """Masks pytree for ``params`` (reference
        ``compute_sparse_masks``); non-pruned leaves get scalar 1.0."""
        if self.allow_permutation:
            from apex_tpu.contrib.sparsity.permutation import permuted_mask

            def calc(w):
                return permuted_mask(
                    w,
                    pattern=self.mask_calculator
                    if isinstance(self.mask_calculator, str) else "m4n2_1d",
                    m=self.m, n=self.n)
        else:
            def calc(w):
                return create_mask(w, self.mask_calculator)

        def leaf_mask(path, leaf):
            if self._eligible(path, leaf):
                return calc(leaf)
            return jnp.ones((), jnp.asarray(leaf).dtype)

        return jax.tree_util.tree_map_with_path(leaf_mask, params)

    def prune(self, params) -> Tuple:
        """(pruned_params, masks)."""
        masks = self.compute_sparse_masks(params)
        return apply_masks(params, masks), masks

    def wrap_optimizer(self, opt, masks) -> SparseOptimizer:
        return SparseOptimizer(opt, masks)

    def prune_trained_model(self, params, opt):
        """One-call recipe (reference ``prune_trained_model:292``):
        returns ``(pruned_params, masks, sparse_opt)`` — fine-tune with
        ``sparse_opt`` to recover accuracy at 2:4 sparsity."""
        pruned, masks = self.prune(params)
        return pruned, masks, self.wrap_optimizer(opt, masks)

    @staticmethod
    def is_sparsity_enabled(masks) -> bool:
        """True if every pruned leaf is at the n:m ratio, False if all are
        dense; inconsistent mixes raise (reference
        ``is_sparsity_enabled:271-289``)."""
        ratios = []
        for _, m in jax.tree_util.tree_leaves_with_path(masks):
            m = jnp.asarray(m)
            if m.ndim == 0:
                continue
            ratios.append(float(m.sum() / m.size))
        if not ratios:
            return False
        if all(abs(r - 1.0) < 1e-6 for r in ratios):
            return False
        if all(abs(r - 0.5) < 1e-6 for r in ratios):
            return True
        raise AssertionError("Inconsistent model sparsity")
