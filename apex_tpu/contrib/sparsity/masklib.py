"""n:m structured sparsity mask calculation.

Behavioral spec: ``apex/contrib/sparsity/sparse_masklib.py`` —
``mn_1d_best`` (best n-of-m pattern per m-wide group by |w|·patternᵀ
argmax, ``:37-47``), ``m4n2_1d`` (``:49``), ``compute_valid_2d_patterns`` /
``mn_2d_best`` (m×m block patterns with exact n per row *and* column,
``:103-136``), zero-padding of widths not divisible by m (``reshape_1d``
``:13-20``).

TPU-first: the per-group pattern selection is one batched matmul
(``|w| @ patternsᵀ`` then argmax) — fully vectorized jnp, jittable, no
Python loop over groups (the reference's CUDA-side trick, same math).
TPUs have no 2:4 sparse MXU, so masks here buy the *training semantics*
(prune-and-keep-sparse, checkpoint compatibility) and model-size/accuracy
studies, not a matmul speedup — documented divergence.
"""

from __future__ import annotations

import itertools
from functools import lru_cache
from typing import Callable, Union

import jax.numpy as jnp
import numpy as np

__all__ = ["create_mask", "m4n2_1d", "m4n2_2d_best", "mn_1d_best",
           "mn_2d_best"]


@lru_cache(maxsize=None)
def _patterns_1d(m: int, n: int) -> np.ndarray:
    """All 0/1 m-vectors with exactly n ones (reference
    ``compute_valid_1d_patterns``)."""
    base = [1.0] * n + [0.0] * (m - n)
    pats = sorted(set(itertools.permutations(base)))
    return np.asarray(pats, np.float32)


@lru_cache(maxsize=None)
def _patterns_2d(m: int, n: int) -> np.ndarray:
    """All m×m 0/1 blocks with exactly n per row and ≤n per column
    (reference ``compute_valid_2d_patterns``)."""
    rows = _patterns_1d(m, n)
    blocks = []
    for combo in itertools.product(range(len(rows)), repeat=m):
        block = rows[list(combo)]
        if (block.sum(axis=0) <= n).all():
            blocks.append(block)
    return np.stack(blocks)


def mn_1d_best(matrix, m: int, n: int):
    """Best n:m mask per m-wide horizontal group of a 2D matrix."""
    rows, cols = matrix.shape
    pad = (-cols) % m
    mat = jnp.pad(jnp.abs(jnp.asarray(matrix, jnp.float32)),
                  ((0, 0), (0, pad)))
    groups = mat.reshape(-1, m)
    pats = jnp.asarray(_patterns_1d(m, n))
    best = jnp.argmax(groups @ pats.T, axis=1)
    mask = pats[best].reshape(rows, cols + pad)[:, :cols]
    return mask.astype(jnp.float32)


def mn_2d_best(matrix, m: int, n: int):
    """Best n:m mask per m×m block such that every row *and* column of the
    block keeps exactly/at-most n entries (prunes fprop and dgrad-transposed
    layouts alike — reference docstring ``sparse_masklib.py:53-66``)."""
    rows, cols = matrix.shape
    pr, pc = (-rows) % m, (-cols) % m
    mat = jnp.pad(jnp.abs(jnp.asarray(matrix, jnp.float32)),
                  ((0, pr), (0, pc)))
    R, C = mat.shape
    blocks = mat.reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    blocks = blocks.reshape(-1, m, m)
    pats = jnp.asarray(_patterns_2d(m, n))  # [P, m, m]
    score = jnp.einsum("bij,pij->bp", blocks, pats)
    best = jnp.argmax(score, axis=1)
    mask = pats[best].reshape(R // m, C // m, m, m).transpose(0, 2, 1, 3)
    mask = mask.reshape(R, C)[:rows, :cols]
    return mask.astype(jnp.float32)


def m4n2_1d(matrix, density: float = 0.5):
    return mn_1d_best(matrix, 4, 2)


def m4n2_2d_best(matrix, density: float = 0.5):
    return mn_2d_best(matrix, 4, 2)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_best": m4n2_2d_best,
}


def _to_matrix(w):
    """View a weight as [out, reduction]: flax keeps the output features
    last (Dense ``[in, out]``, Conv ``[kh, kw, in, out]``), so the
    "horizontal" n:m direction (the reduction the MXU contracts over —
    reference prunes torch's ``[out, in, ...]`` along ``in``) is
    everything *but* the last axis."""
    w = jnp.asarray(w)
    return jnp.moveaxis(w, -1, 0).reshape(w.shape[-1], -1)


def _from_matrix(mask2d, shape):
    lead = (shape[-1],) + tuple(shape[:-1])
    return jnp.moveaxis(mask2d.reshape(lead), 0, -1)


def create_mask(
    weight,
    pattern: Union[str, Callable] = "m4n2_1d",
    ) :
    """n:m mask with the same shape/broadcast layout as ``weight``
    (reference ``create_mask`` dispatch on pattern string)."""
    fn = _PATTERNS[pattern] if isinstance(pattern, str) else pattern
    mat = _to_matrix(weight)
    return _from_matrix(fn(mat, 0.5), weight.shape).astype(weight.dtype)
