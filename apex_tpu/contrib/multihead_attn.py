"""Fused multihead-attention modules.

Behavioral spec: ``apex/contrib/multihead_attn`` —
``SelfMultiheadAttn`` (``self_multihead_attn.py:21``: fused QKV
projection, optional biases, binary key-padding or additive masks,
attention dropout, optional *pre-LN + residual-add* fusion
``include_norm_add``) and ``EncdecMultiheadAttn``
(``encdec_multihead_attn.py``: separate Q and packed KV projections).
Layout [T, B, C] throughout, matching the reference (and Megatron).

TPU-first: the "fast" CUDA paths fuse GEMM+softmax+dropout+GEMM by hand;
here the binary-mask/no-mask path routes through the Pallas flash kernel
(:mod:`apex_tpu.ops.flash_attention` — padding becomes segment ids, the
dropout is in-kernel and counter-based) and the additive-mask path uses
the XLA softmax core, which XLA fuses end-to-end.  The reference ships
python reference impls to test against (``self_multihead_attn_func.py``);
``tests/test_multihead_attn.py`` plays that role here.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]


def _attention_core(q, k, v, *, scale, key_padding_mask, attn_mask,
                    dropout_rate, deterministic, make_rng):
    """q/k/v: [B, H, S, D] -> [B, H, Sq, D].

    ``key_padding_mask [B, Sk]`` (1/True = pad) uses the flash path;
    ``attn_mask`` (additive, broadcastable to [B, H, Sq, Sk]) uses the
    dense softmax path (matches the reference's mask_additive mode).
    """
    if attn_mask is None:
        from apex_tpu.ops.flash_attention import flash_attention

        kw = {}
        if key_padding_mask is not None:
            b, _, sk, _ = k.shape
            sq = q.shape[2]
            kw["segment_ids_q"] = jnp.zeros((b, sq), jnp.int32)
            kw["segment_ids_kv"] = key_padding_mask.astype(jnp.int32)
        if dropout_rate > 0.0 and not deterministic:
            kw["dropout_rate"] = dropout_rate
            kw["dropout_seed"] = jax.random.randint(
                make_rng("dropout"), (), 0, jnp.iinfo(jnp.int32).max)
        return flash_attention(q, k, v, causal=False, scale=scale, **kw)

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    scores = scores + attn_mask.astype(jnp.float32)
    if key_padding_mask is not None:
        scores = jnp.where(
            key_padding_mask[:, None, None, :].astype(bool), -1e30, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    probs = nn.Dropout(rate=dropout_rate, deterministic=deterministic,
                       rng_collection="dropout")(probs)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _split_heads(x, heads):
    # [T, B, C] -> [B, H, T, D]
    t, b, c = x.shape
    return x.reshape(t, b, heads, c // heads).transpose(1, 2, 0, 3)


def _merge_heads(x):
    # [B, H, T, D] -> [T, B, C]
    b, h, t, d = x.shape
    return x.transpose(2, 0, 1, 3).reshape(t, b, h * d)


class SelfMultiheadAttn(nn.Module):
    """Self-attention with optional pre-LN + residual fusion
    (reference ``SelfMultiheadAttn``; constructor knobs mirrored)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    mask_additive: bool = False

    @nn.compact
    def __call__(self, query, key=None, value=None, key_padding_mask=None,
                 attn_mask=None, deterministic: bool = True):
        # key/value args accepted for API parity; self-attention uses query.
        del key, value
        C, H = self.embed_dim, self.num_heads
        if C % H:
            raise ValueError("embed_dim must be divisible by num_heads")
        if self.mask_additive and self.include_norm_add:
            raise ValueError(
                "additive mask not supported with layer norm (reference "
                "constraint)")
        x = query
        residual = x
        if self.include_norm_add:
            from apex_tpu.normalization import FusedLayerNorm

            x = FusedLayerNorm(C, name="lyr_nrm")(x)
        qkv = nn.Dense(3 * C, use_bias=self.bias, name="in_proj",
                       kernel_init=nn.initializers.xavier_uniform())(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        out = _attention_core(
            _split_heads(q, H), _split_heads(k, H), _split_heads(v, H),
            scale=(C // H) ** -0.5,
            key_padding_mask=key_padding_mask,
            attn_mask=attn_mask if self.mask_additive else None,
            dropout_rate=self.dropout, deterministic=deterministic,
            make_rng=self.make_rng)
        out = _merge_heads(out)
        out = nn.Dense(C, use_bias=self.bias, name="out_proj",
                       kernel_init=nn.initializers.xavier_uniform())(out)
        if self.include_norm_add:
            out = nn.Dropout(rate=self.dropout,
                             deterministic=deterministic,
                             rng_collection="dropout")(out)
            out = residual + out
        return out


class EncdecMultiheadAttn(nn.Module):
    """Encoder-decoder attention: Q from the decoder stream, packed KV
    from the encoder stream (reference ``EncdecMultiheadAttn``)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False

    @nn.compact
    def __call__(self, query, key, value=None, key_padding_mask=None,
                 attn_mask=None, deterministic: bool = True):
        del value  # packed-KV: value rides with key (reference API)
        C, H = self.embed_dim, self.num_heads
        residual = query
        x = query
        if self.include_norm_add:
            from apex_tpu.normalization import FusedLayerNorm

            x = FusedLayerNorm(C, name="lyr_nrm")(x)
        q = nn.Dense(C, use_bias=self.bias, name="q_proj",
                     kernel_init=nn.initializers.xavier_uniform())(x)
        kv = nn.Dense(2 * C, use_bias=self.bias, name="kv_proj",
                      kernel_init=nn.initializers.xavier_uniform())(key)
        k, v = jnp.split(kv, 2, axis=-1)
        out = _attention_core(
            _split_heads(q, H), _split_heads(k, H), _split_heads(v, H),
            scale=(C // H) ** -0.5,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            dropout_rate=self.dropout, deterministic=deterministic,
            make_rng=self.make_rng)
        out = _merge_heads(out)
        out = nn.Dense(C, use_bias=self.bias, name="out_proj",
                       kernel_init=nn.initializers.xavier_uniform())(out)
        if self.include_norm_add:
            out = nn.Dropout(rate=self.dropout,
                             deterministic=deterministic,
                             rng_collection="dropout")(out)
            out = residual + out
        return out
