"""apex_tpu.contrib — optional extensions (reference ``apex/contrib``).

Subpackages/modules: ``optimizers`` (ZeRO-sharded DistributedFusedAdam/
LAMB), ``sparsity`` (ASP 2:4), ``group_norm`` (NHWC + SiLU),
``focal_loss``, ``index_mul_2d``, ``transducer`` (joint + loss).
"""

from apex_tpu.contrib.focal_loss import focal_loss  # noqa: F401
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc  # noqa: F401
from apex_tpu.contrib.index_mul_2d import index_mul_2d  # noqa: F401
from apex_tpu.contrib.transducer import (  # noqa: F401
    TransducerJoint,
    transducer_joint,
    transducer_loss,
)
