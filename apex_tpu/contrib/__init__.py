"""apex_tpu.contrib — optional extensions (reference ``apex/contrib``)."""
