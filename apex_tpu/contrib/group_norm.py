"""GroupNorm NHWC (+ fused SiLU) — diffusion-workload norm.

Behavioral spec: ``apex/contrib/group_norm/group_norm.py:29-109`` — a
``torch.nn.GroupNorm``-compatible module in NHWC layout with an optional
fused swish/SiLU epilogue (``act="silu"``), used by diffusion UNets; the
CUDA side ships one-pass/two-pass persistent kernels for many (C, g)
combos.

TPU-first: NHWC is already the native TPU conv layout, and XLA fuses the
(mean, rsqrt, scale, shift, silu) chain into one or two HBM passes —
there is no combo table to maintain.  Statistics accumulate in fp32
regardless of input dtype (the CUDA kernels do the same).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["GroupNorm", "group_norm_nhwc"]


def group_norm_nhwc(x, num_groups: int, weight=None, bias=None,
                    eps: float = 1e-5, act: str = ""):
    """GroupNorm over ``x: [N, H, W, C]`` (or any ``[N, ..., C]``).

    ``weight/bias: [C]``; ``act``: "" or "silu"/"swish" (reference
    ``group_norm.py`` supports exactly silu).
    """
    C = x.shape[-1]
    if C % num_groups != 0:
        raise ValueError(f"channels {C} not divisible by groups {num_groups}")
    orig_dtype = x.dtype
    xs = x.astype(jnp.float32).reshape(
        x.shape[0], -1, num_groups, C // num_groups)
    mean = xs.mean(axis=(1, 3), keepdims=True)
    var = xs.var(axis=(1, 3), keepdims=True)
    xs = (xs - mean) * jax.lax.rsqrt(var + eps)
    out = xs.reshape(x.shape)
    if weight is not None:
        out = out * jnp.asarray(weight, jnp.float32)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)
    if act in ("silu", "swish"):
        out = out * jnp.reciprocal(1.0 + jnp.exp(-out))
    elif act:
        raise ValueError(f"unsupported act {act!r} (reference supports silu)")
    return out.astype(orig_dtype)


class GroupNorm(nn.Module):
    """``torch.nn.GroupNorm``-compatible flax module in NHWC
    (reference ``GroupNorm`` module, ``group_norm.py:44-109``)."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        if x.shape[-1] != self.num_channels:
            raise ValueError(
                f"expected {self.num_channels} channels, got {x.shape[-1]}")
        w = b = None
        if self.affine:
            w = self.param("scale", nn.initializers.ones,
                           (self.num_channels,), jnp.float32)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), jnp.float32)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
