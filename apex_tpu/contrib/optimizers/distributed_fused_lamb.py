"""ZeRO-sharded LAMB — ``DistributedFusedLAMB`` rebuilt for SPMD.

Behavioral spec: ``apex/contrib/optimizers/distributed_fused_lamb.py:24`` —
LAMB with gradients reduce-scattered over dp, optimizer state sharded,
global-grad-norm clipping (``_pipeline_block_reductions:728``), per-tensor
trust ratios, and the stepped shards all-gathered back
(``_pipeline_step:812``).

SPMD mapping follows :mod:`.distributed_fused_adam` (per-leaf chunks via
``psum_scatter`` / ``all_gather``); the LAMB-specific parts are the two norm
reductions the reference launches as ``multi_tensor_l2norm`` + NCCL
all-reduce (``fused_lamb.py:116-147``): here each is a shard-local sum of
squares followed by one ``lax.psum`` over the dp axis.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel import collectives as cc

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    reduce_scatter_leaf,
    shard_leaf,
    unshard_leaf,
)
from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    tree_map_multi,
)
from apex_tpu.parallel.mesh import DATA_AXIS
from apex_tpu.optimizers.fused_lamb import lamb_flat_update

__all__ = ["DistributedFusedLAMB"]


class DistributedFusedLAMB:
    """ZeRO LAMB over the ``dp`` mesh axis; call inside ``shard_map`` with
    pre-reduction local grads (see ``DistributedFusedAdam``)."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        axis: str = DATA_AXIS,
        flat: bool = True,
    ):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.axis = axis
        # flat=True: the shard-local work runs over one chunked buffer
        # (FusedLAMB's r5 rebuild) — wide elementwise kernels, segmented
        # per-tensor norm partials, and still exactly ONE psum for all
        # 2*n_leaves norm partials.  flat=False keeps the per-leaf form.
        self.flat = flat

    def init(self, params) -> OptState:
        def shard_zero(p):
            return jnp.zeros_like(shard_leaf(f32(p), self.axis))

        return OptState(
            step=jnp.int32(0),
            slots={
                "exp_avg": jax.tree_util.tree_map(shard_zero, params),
                "exp_avg_sq": jax.tree_util.tree_map(shard_zero, params),
            },
            master=jax.tree_util.tree_map(
                lambda p: f32(shard_leaf(p, self.axis)), params
            ),
        )

    def step(self, grads, state: OptState, params, *, lr=None,
             grad_scale=None, skip_update=None):
        axis = self.axis
        world = cc.axis_size(axis)
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1

        inv_scale = 1.0 / f32(world)
        if grad_scale is not None:
            inv_scale = inv_scale / f32(grad_scale)
        g_shards = jax.tree_util.tree_map(
            lambda g: reduce_scatter_leaf(f32(g), axis) * inv_scale, grads
        )
        p32 = state.master

        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def clip_ratio(global_norm):
            if self.max_grad_norm and self.max_grad_norm > 0:
                return jnp.maximum(global_norm / self.max_grad_norm, 1.0)
            return jnp.float32(1.0)

        if self.flat:
            new_p32, new_m, new_v = self._flat_update(
                p32, g_shards, state.slots["exp_avg"],
                state.slots["exp_avg_sq"], lr, clip_ratio, beta3, bc1, bc2)
        else:
            new_p32, new_m, new_v = self._per_leaf_update(
                p32, g_shards, state.slots["exp_avg"],
                state.slots["exp_avg_sq"], lr, clip_ratio, beta3, bc1, bc2)
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        new_params = jax.tree_util.tree_map(
            lambda chunk, p: unshard_leaf(chunk, jnp.shape(p),
                                          jnp.asarray(p).dtype, axis),
            new_p32, params,
        )
        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_p32,
        )
        return new_params, new_state

    def _flat_update(self, p32, g_shards, m, v, lr, clip_ratio, beta3,
                     bc1, bc2):
        """Shard-local LAMB over one chunked buffer — THE shared
        :func:`lamb_flat_update` math with ``reduce=psum(dp)``: wide
        elementwise kernels, the global-norm partial as one row-reduce,
        ALL 2*n_leaves per-tensor norm partials via two segmented
        reductions, and still exactly one norm psum per step (the
        reference's one fused l2norm launch + one all-reduce,
        ``distributed_fused_lamb.py:728-811``)."""
        return lamb_flat_update(
            p32, g_shards, m, v, lr=lr, b1=self.beta1, b2=self.beta2,
            eps=self.eps, wd=self.weight_decay, beta3=beta3, bc1=bc1,
            bc2=bc2, adam_w_mode=self.adam_w_mode,
            use_nvlamb=self.use_nvlamb, clip_ratio=clip_ratio,
            reduce=lambda x: cc.all_reduce(x, self.axis))

    def _per_leaf_update(self, p32, g_shards, m, v, lr, clip_ratio, beta3,
                         bc1, bc2):
        axis = self.axis
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay

        # Global grad norm: shard-local sum of squares + one psum
        # (the reference's two-phase multi_tensor_l2norm + all_reduce,
        # distributed_fused_lamb.py:728-811).
        local_sq = sum(
            jnp.sum(jnp.square(g))
            for g in jax.tree_util.tree_leaves(g_shards)
        )
        clip = clip_ratio(jnp.sqrt(cc.all_reduce(local_sq, axis)))

        # Stage 1 (multi_tensor_lamb.cu stage 1): moments + raw update.
        def stage1(p, g, m, v):
            g = g / clip
            if wd != 0.0 and not self.adam_w_mode:
                g = g + wd * p
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if wd != 0.0 and self.adam_w_mode:
                update = update + wd * p
            return update, m, v

        updates, new_m, new_v = tree_map_multi(stage1, 3, p32, g_shards,
                                               m, v)

        p_leaves = jax.tree_util.tree_leaves(p32)
        u_leaves, u_def = jax.tree_util.tree_flatten(updates)
        if wd != 0.0 or self.use_nvlamb:
            # Per-tensor norms: all leaves' shard partials stacked into
            # ONE psum (the reference's single fused l2norm launch + one
            # all-reduce, not 2*n_leaves scalar collectives).  Statically
            # skipped when every trust ratio is 1.0 (wd=0, no nvlamb).
            partial = jnp.stack(
                [jnp.sum(jnp.square(l)) for l in p_leaves]
                + [jnp.sum(jnp.square(l)) for l in u_leaves]
            )
            norms = jnp.sqrt(cc.all_reduce(partial, axis))
            w_norms = norms[: len(p_leaves)]
            u_norms = norms[len(p_leaves):]

            def ratio(i):
                return jnp.where(
                    (w_norms[i] > 0) & (u_norms[i] > 0),
                    w_norms[i] / u_norms[i], jnp.float32(1.0),
                )
        else:
            def ratio(i):
                return jnp.float32(1.0)

        # Stage 2: trust-ratio application per leaf.
        new_p_leaves = [p - lr * ratio(i) * u
                        for i, (p, u) in enumerate(zip(p_leaves, u_leaves))]
        return (jax.tree_util.tree_unflatten(u_def, new_p_leaves),
                new_m, new_v)
