"""ZeRO-sharded LAMB — ``DistributedFusedLAMB`` rebuilt for SPMD.

Behavioral spec: ``apex/contrib/optimizers/distributed_fused_lamb.py:24`` —
LAMB with gradients reduce-scattered over dp, optimizer state sharded,
global-grad-norm clipping (``_pipeline_block_reductions:728``), per-tensor
trust ratios, and the stepped shards all-gathered back
(``_pipeline_step:812``).

SPMD mapping follows :mod:`.distributed_fused_adam`: the default
``flat_bucket=True`` packs the whole tree into chunked dtype-group buffers
— ONE (optionally ICI/DCN-hierarchical) reduce-scatter and ONE all-gather
per bucket, the bucketed exchange of the reference's flat
``_flat_grads``/``_new_params`` buffers (``distributed_fused_lamb.py:424``)
— with ``flat_bucket=False`` keeping the per-leaf ``psum_scatter`` /
``all_gather`` port.  The LAMB-specific parts are the two norm reductions
the reference launches as ``multi_tensor_l2norm`` + NCCL all-reduce
(``fused_lamb.py:116-147``): here each is a shard-local (segmented, for
the per-tensor set) sum of squares followed by one ``lax.psum`` over the
scatter axes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel import collectives as cc

from apex_tpu.contrib.optimizers import _flat_bucket as fb
from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    reduce_scatter_leaf,
    shard_leaf,
    unshard_leaf,
)
from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    tree_map_multi,
)
from apex_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS
from apex_tpu.optimizers.fused_lamb import lamb_flat_update

__all__ = ["DistributedFusedLAMB"]


def _lamb_stage1(p, g, m, v, *, clip, b1, b2, beta3, bc1, bc2, eps, wd,
                 adam_w_mode):
    """LAMB stage 1 (``multi_tensor_lamb.cu:41``) on fp32 values: clipped
    grad, moments, bc-corrected raw update — the LAMB analog of
    ``adam_apply``, shared by the per-leaf and flat-bucket paths so the
    math cannot diverge between them.  Returns ``(update, m, v)``."""
    g = g / clip
    if wd != 0.0 and not adam_w_mode:
        g = g + wd * p  # MODE_0: L2 into the clipped grad
    m = b1 * m + beta3 * g
    v = b2 * v + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd != 0.0 and adam_w_mode:
        update = update + wd * p  # MODE_1: decoupled decay
    return update, m, v


class DistributedFusedLAMB(fb.FlatBucketMixin):
    """ZeRO LAMB over the ``dp`` mesh axis; call inside ``shard_map`` with
    pre-reduction local grads (see ``DistributedFusedAdam``)."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        axis=DATA_AXIS,
        flat: bool = True,
        flat_bucket: bool = True,
        n_buckets: int = 1,
        chunk: int = 256,
        outer_axis: Optional[str] = DCN_AXIS,
        dcn_reduce_dtype=None,
    ):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.axis = axis
        # flat=True: the shard-local work runs over one chunked buffer
        # (FusedLAMB's r5 rebuild) — wide elementwise kernels, segmented
        # per-tensor norm partials, and still exactly ONE psum for all
        # 2*n_leaves norm partials.  flat=False keeps the per-leaf form.
        # Only consulted when flat_bucket=False.
        self.flat = flat
        # flat_bucket=True: the COMMUNICATION is bucketed too — one
        # reduce-scatter / all-gather per dtype-group bucket instead of
        # one pair per tensor (see distributed_fused_adam.py docstring);
        # outer_axis enables the hierarchical ICI/DCN reduction.
        self._init_bucket_config(
            flat_bucket=flat_bucket, n_buckets=n_buckets, chunk=chunk,
            outer_axis=outer_axis, dcn_reduce_dtype=dcn_reduce_dtype)

    def init(self, params) -> OptState:
        if self.flat_bucket:
            cfg = self._cfg()
            return fb.init_flat_state(
                params, cfg, self._layout(params, cfg.world_scatter))

        def shard_zero(p):
            return jnp.zeros_like(shard_leaf(f32(p), self.axis))

        return OptState(
            step=jnp.int32(0),
            slots={
                "exp_avg": jax.tree_util.tree_map(shard_zero, params),
                "exp_avg_sq": jax.tree_util.tree_map(shard_zero, params),
            },
            master=jax.tree_util.tree_map(
                lambda p: f32(shard_leaf(p, self.axis)), params
            ),
        )

    def step(self, grads, state: OptState, params, *, lr=None,
             grad_scale=None, skip_update=None):
        if self.flat_bucket:
            return self._step_flat_bucket(grads, state, params, lr=lr,
                                          grad_scale=grad_scale,
                                          skip_update=skip_update)
        axis = self.axis
        world = cc.axis_size(axis)
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1

        inv_scale = 1.0 / f32(world)
        if grad_scale is not None:
            inv_scale = inv_scale / f32(grad_scale)
        g_shards = jax.tree_util.tree_map(
            lambda g: reduce_scatter_leaf(f32(g), axis) * inv_scale, grads
        )
        p32 = state.master

        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def clip_ratio(global_norm):
            if self.max_grad_norm and self.max_grad_norm > 0:
                return jnp.maximum(global_norm / self.max_grad_norm, 1.0)
            return jnp.float32(1.0)

        if self.flat:
            new_p32, new_m, new_v = self._flat_update(
                p32, g_shards, state.slots["exp_avg"],
                state.slots["exp_avg_sq"], lr, clip_ratio, beta3, bc1, bc2)
        else:
            new_p32, new_m, new_v = self._per_leaf_update(
                p32, g_shards, state.slots["exp_avg"],
                state.slots["exp_avg_sq"], lr, clip_ratio, beta3, bc1, bc2)
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        new_params = jax.tree_util.tree_map(
            lambda chunk, p: unshard_leaf(chunk, jnp.shape(p),
                                          jnp.asarray(p).dtype, axis),
            new_p32, params,
        )
        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_p32,
        )
        return new_params, new_state

    def _flat_update(self, p32, g_shards, m, v, lr, clip_ratio, beta3,
                     bc1, bc2):
        """Shard-local LAMB over one chunked buffer — THE shared
        :func:`lamb_flat_update` math with ``reduce=psum(dp)``: wide
        elementwise kernels, the global-norm partial as one row-reduce,
        ALL 2*n_leaves per-tensor norm partials via two segmented
        reductions, and still exactly one norm psum per step (the
        reference's one fused l2norm launch + one all-reduce,
        ``distributed_fused_lamb.py:728-811``)."""
        return lamb_flat_update(
            p32, g_shards, m, v, lr=lr, b1=self.beta1, b2=self.beta2,
            eps=self.eps, wd=self.weight_decay, beta3=beta3, bc1=bc1,
            bc2=bc2, adam_w_mode=self.adam_w_mode,
            use_nvlamb=self.use_nvlamb, clip_ratio=clip_ratio,
            reduce=lambda x: cc.all_reduce(x, self.axis))

    def _per_leaf_update(self, p32, g_shards, m, v, lr, clip_ratio, beta3,
                         bc1, bc2):
        axis = self.axis
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay

        # Global grad norm: shard-local sum of squares + one psum
        # (the reference's two-phase multi_tensor_l2norm + all_reduce,
        # distributed_fused_lamb.py:728-811).
        local_sq = sum(
            jnp.sum(jnp.square(g))
            for g in jax.tree_util.tree_leaves(g_shards)
        )
        clip = clip_ratio(jnp.sqrt(cc.all_reduce(local_sq, axis)))

        # Stage 1 (multi_tensor_lamb.cu stage 1): moments + raw update.
        def stage1(p, g, m, v):
            return _lamb_stage1(p, g, m, v, clip=clip, b1=b1, b2=b2,
                                beta3=beta3, bc1=bc1, bc2=bc2, eps=eps,
                                wd=wd, adam_w_mode=self.adam_w_mode)

        updates, new_m, new_v = tree_map_multi(stage1, 3, p32, g_shards,
                                               m, v)

        p_leaves = jax.tree_util.tree_leaves(p32)
        u_leaves, u_def = jax.tree_util.tree_flatten(updates)
        if wd != 0.0 or self.use_nvlamb:
            # Per-tensor norms: all leaves' shard partials stacked into
            # ONE psum (the reference's single fused l2norm launch + one
            # all-reduce, not 2*n_leaves scalar collectives).  Statically
            # skipped when every trust ratio is 1.0 (wd=0, no nvlamb).
            partial = jnp.stack(
                [jnp.sum(jnp.square(l)) for l in p_leaves]
                + [jnp.sum(jnp.square(l)) for l in u_leaves]
            )
            norms = jnp.sqrt(cc.all_reduce(partial, axis))
            w_norms = norms[: len(p_leaves)]
            u_norms = norms[len(p_leaves):]

            def ratio(i):
                return jnp.where(
                    (w_norms[i] > 0) & (u_norms[i] > 0),
                    w_norms[i] / u_norms[i], jnp.float32(1.0),
                )
        else:
            def ratio(i):
                return jnp.float32(1.0)

        # Stage 2: trust-ratio application per leaf.
        new_p_leaves = [p - lr * ratio(i) * u
                        for i, (p, u) in enumerate(zip(p_leaves, u_leaves))]
        return (jax.tree_util.tree_unflatten(u_def, new_p_leaves),
                new_m, new_v)

    def _step_flat_bucket(self, grads, state: OptState, params, *, lr,
                          grad_scale, skip_update):
        """Bucketed ZeRO LAMB: one (hierarchical) reduce-scatter per
        dtype-group bucket, both LAMB stages on the local shard, one
        all-gather per bucket back.  The per-tensor trust-ratio norms are
        recovered from the shard by segmented row reductions (leaf
        boundaries are row-aligned, ``flatten_to_chunked``) + ONE psum of
        the stacked partial vector — the reference's single fused
        ``multi_tensor_l2norm`` launch + one all-reduce
        (``distributed_fused_lamb.py:728-811``), bucket-sharded."""
        cfg = self._cfg()
        layout = self._layout(params, cfg.world_scatter)
        rank = fb.flat_rank(cfg)
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1

        inv_scale = 1.0 / f32(cfg.world_total)
        if grad_scale is not None:
            inv_scale = inv_scale / f32(grad_scale)

        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        g_leaves = layout.treedef.flatten_up_to(grads)
        p_leaves = layout.treedef.flatten_up_to(params)

        # Gradient reduce-scatter (all buckets), then the global grad norm
        # from the shards: shards are distinct over the scatter axes only
        # (a hierarchical outer tier holds replicas), so ONE psum there.
        g_loc_groups, ids_groups = [], []
        for group in layout.groups:
            g32 = fb.flatten_group(layout, group, g_leaves,
                                   dtype=jnp.float32)
            g_loc_groups.append([
                g * inv_scale for g in fb.bucket_reduce_scatter(
                    g32, group, cfg, layout.n_buckets,
                    outer_reduce_dtype=self.dcn_reduce_dtype)])
            ids_groups.append(
                fb.local_leaf_ids(group, layout.n_buckets, rank))

        local_sq = sum(
            jnp.sum(jnp.square(g))
            for bufs in g_loc_groups for g in bufs
        ) if layout.groups else jnp.float32(0)
        global_sq = cc.all_reduce(local_sq, cfg.scatter_axes)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip = jnp.maximum(jnp.sqrt(global_sq) / self.max_grad_norm,
                               1.0)
        else:
            clip = jnp.float32(1.0)

        # Stage 1 (multi_tensor_lamb.cu:41): moments + raw update on the
        # local shard buffers — same _lamb_stage1 as the per-leaf path.
        updates, new_m, new_v = [], [], []
        for gi, group in enumerate(layout.groups):
            us, ms, vs = [], [], []
            for g, m, v, p in zip(g_loc_groups[gi],
                                  state.slots["exp_avg"][gi],
                                  state.slots["exp_avg_sq"][gi],
                                  state.master[gi]):
                u, m, v = _lamb_stage1(
                    p, g, m, v, clip=clip, b1=b1, b2=b2, beta3=beta3,
                    bc1=bc1, bc2=bc2, eps=eps, wd=wd,
                    adam_w_mode=self.adam_w_mode)
                us.append(u)
                ms.append(m)
                vs.append(v)
            updates.append(us)
            new_m.append(ms)
            new_v.append(vs)

        # Stage 2 (multi_tensor_lamb.cu:234): per-tensor trust ratios.
        # Shard-local segmented partials for EVERY leaf (params and
        # updates), stacked into one vector -> exactly one norm psum.
        if (wd != 0.0 or self.use_nvlamb) and layout.groups:
            def group_partials(bufs, gi, group):
                acc = jnp.zeros((len(group.indices),), jnp.float32)
                for buf, ids in zip(bufs, ids_groups[gi]):
                    row_sq = jnp.sum(jnp.square(buf), axis=1)
                    acc = acc + jax.ops.segment_sum(
                        row_sq, ids, num_segments=len(group.indices),
                        indices_are_sorted=True)
                return acc

            partial = jnp.concatenate(
                [group_partials(state.master[gi], gi, group)
                 for gi, group in enumerate(layout.groups)]
                + [group_partials(updates[gi], gi, group)
                   for gi, group in enumerate(layout.groups)])
            norms_sq = cc.all_reduce(partial, cfg.scatter_axes)
            half = partial.shape[0] // 2
            w_sq, u_sq = norms_sq[:half], norms_sq[half:]
            ratio_all = jnp.where(
                (w_sq > 0) & (u_sq > 0),
                jnp.sqrt(w_sq) / jnp.sqrt(jnp.where(u_sq > 0, u_sq, 1.0)),
                1.0,
            )

            def bucket_ratio(gi, offset, k):
                ids = ids_groups[gi][k]
                return ratio_all[offset + ids][:, None]
        else:
            def bucket_ratio(gi, offset, k):
                return jnp.float32(1.0)

        old_p32, new_p = [], []
        offset = 0
        for gi, group in enumerate(layout.groups):
            p32 = state.master[gi]
            new_p.append([
                p - lr * bucket_ratio(gi, offset, k) * u
                for k, (p, u) in enumerate(zip(p32, updates[gi]))
            ])
            old_p32.append(p32)
            offset += len(group.indices)

        new_p = apply_skip(skip_update, new_p, old_p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        full_bufs = [
            fb.bucket_all_gather(new_p[gi], group, cfg, dtype=group.dtype)
            for gi, group in enumerate(layout.groups)
        ]
        new_params = fb.unflatten_groups(layout, full_bufs, p_leaves)
        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_p,
        )
        return new_params, new_state
