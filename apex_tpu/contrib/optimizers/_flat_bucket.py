"""Flat-bucket ZeRO machinery — the TPU shape of apex's ``StateBucket``.

The reference packs all parameters into fixed-size flat buckets
(``apex/contrib/optimizers/distributed_fused_adam.py:397`` ``StateBucket``;
``distributed_fused_lamb.py:424`` flat ``_flat_grads``/``_new_params``
buffers) so the whole ZeRO exchange is a handful of large NCCL
reduce-scatters and all-gathers instead of one per tensor.  The first SPMD
port here kept *per-leaf* ``psum_scatter``/``all_gather`` — hundreds of
small collectives per step on a real transformer.  This module restores
the bucketed shape:

- the whole tree is packed into ONE chunked ``(rows, chunk)`` buffer per
  **dtype-group** (leaves that share a model dtype, so params travel the
  all-gather wire in their own dtype), rows padded to a multiple of
  ``world * n_buckets`` via :func:`apex_tpu.utils.tree.flatten_to_chunked`;
- each buffer is split into ``n_buckets`` equal row-ranges ("buckets");
  every bucket is one reduce-scatter on the way in and one all-gather on
  the way out — K > 1 lets XLA overlap the gather of bucket k with the
  update tail of bucket k+1, the bucketed-overlap scheme of the reference
  (``distributed_fused_adam.py`` docstring: overlapped grad reduce-scatter
  / param all-gather);
- reductions are optionally **hierarchy-aware**: reduce-scatter over the
  intra-slice ICI ``dp`` axis, then all-reduce the 1/dp shard across the
  cross-slice ``dcn`` axis
  (:func:`apex_tpu.parallel.collectives.hierarchical_reduce_scatter`),
  instead of treating ``(dcn, dp)`` as one flat group;
- per-tensor quantities (LAMB trust ratios) come back from the shard via
  the chunked segmented reductions: row-aligned leaf boundaries make a
  shard-local ``segment_sum`` + one psum exact.

Everything here is static host-side layout plus thin traced helpers; it
must run inside the ``shard_map`` that binds the mesh axes.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.observability.spans import named_span
from apex_tpu.parallel import collectives as cc
from apex_tpu.utils.tree import (
    chunked_meta,
    flatten_to_chunked,
    unflatten_from_chunked,
)

__all__ = [
    "AxisSpec",
    "GroupLayout",
    "BucketLayout",
    "resolve_axes",
    "flat_rank",
    "build_layout",
    "host_groups",
    "flatten_group",
    "unflatten_groups",
    "bucket_slices",
    "local_slices",
    "local_leaf_ids",
    "bucket_reduce_scatter",
    "bucket_all_gather",
    "FlatBucketMixin",
    "init_flat_state",
    "flat_state_specs",
]

AxisSpec = Union[str, Sequence[str]]


class AxisConfig(NamedTuple):
    """Resolved reduction topology (static at trace time)."""

    scatter_axes: Any        # axis name or tuple: where shards are distinct
    outer_axis: Optional[str]  # DCN tier (hierarchical) or None
    world_scatter: int       # shard count = prod of scatter axis sizes
    world_total: int         # replica count incl. the outer tier


class GroupLayout(NamedTuple):
    """One dtype-group's static packing (host-side)."""

    dtype: Any               # model dtype (the all-gather wire dtype)
    indices: Tuple[int, ...]  # leaf positions in the flattened tree
    meta: Any                # _ChunkMeta of the group's leaf list
    rows: int                # padded row count (multiple of world * K)
    rows_per_bucket: int
    local_rows: int          # rows_per_bucket // world


class BucketLayout(NamedTuple):
    treedef: Any
    n_leaves: int
    groups: Tuple[GroupLayout, ...]
    world: int
    n_buckets: int
    chunk: int


def resolve_axes(axis: AxisSpec, outer_axis: Optional[str]) -> AxisConfig:
    """Resolve the (inner, outer) reduction axes inside ``shard_map``.

    ``axis`` may be one mesh axis name or a tuple (flat multi-axis
    reduction group).  ``outer_axis`` enables the hierarchical ICI/DCN
    split and is ignored when unbound or size 1 (single slice), so the
    same optimizer config is correct at any scale; a tuple ``axis``
    cannot also have an outer tier."""
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    if outer_axis is not None and len(axes) > 1 and outer_axis not in axes:
        raise ValueError(
            "outer_axis is only meaningful with a single inner axis "
            f"(got axis={axis!r}, outer_axis={outer_axis!r})")
    # an outer_axis already inside the flat scatter tuple is simply
    # absorbed by it (axis=("dcn","dp") with the default outer="dcn" is
    # the explicit flat form, not a config error)
    outer = (outer_axis
             if outer_axis is not None and outer_axis not in axes
             and cc.bound_axis_size(outer_axis) > 1 else None)
    world_scatter = 1
    for a in axes:
        world_scatter *= cc.axis_size(a)
    world_total = world_scatter * (
        cc.bound_axis_size(outer) if outer is not None else 1)
    return AxisConfig(
        scatter_axes=axes[0] if len(axes) == 1 else axes,
        outer_axis=outer,
        world_scatter=world_scatter,
        world_total=world_total,
    )


def flat_rank(cfg: AxisConfig):
    """This rank's shard index: the row-major flattening of the scatter
    axes — exactly the tile order of a tiled ``psum_scatter`` over the
    same axis tuple, so no-communication slicing (:func:`local_slices`)
    and the reduce-scatter tiles agree."""
    axes = (cfg.scatter_axes if isinstance(cfg.scatter_axes, tuple)
            else (cfg.scatter_axes,))
    r = jnp.int32(0)
    for a in axes:
        r = r * cc.axis_size(a) + lax.axis_index(a)
    return r


def host_groups(params):
    """Dtype-grouping, world-independent: leaves that share a model dtype
    form one flat bucket group (the "per dtype-group" split of the
    reference's bucket assignment).  Group order is first-appearance, so
    the layout is a pure function of the tree."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    order = []
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        dt = jnp.asarray(leaf).dtype
        if dt not in by_dtype:
            by_dtype[dt] = []
            order.append(dt)
        by_dtype[dt].append(i)
    return treedef, leaves, [(dt, tuple(by_dtype[dt])) for dt in order]


def build_layout(params, *, world: int, n_buckets: int = 1,
                 chunk: int = 256) -> BucketLayout:
    """Static bucket layout for ``params`` (pure host math; call at trace
    time).  Rows of each dtype-group are padded to a multiple of
    ``world * n_buckets`` so every bucket reduce-scatters evenly."""
    treedef, leaves, raw_groups = host_groups(params)
    pad_to = world * n_buckets
    groups = []
    for dt, idx in raw_groups:
        sub = [leaves[i] for i in idx]
        meta = chunked_meta(
            jax.tree_util.tree_structure(list(sub)),
            [np.shape(x) for x in sub],
            [jnp.asarray(x).dtype for x in sub],
            chunk=chunk, pad_rows_to=pad_to)
        rows = meta.n_rows
        rpb = rows // n_buckets
        groups.append(GroupLayout(
            dtype=dt, indices=idx, meta=meta, rows=rows,
            rows_per_bucket=rpb, local_rows=rpb // world))
    return BucketLayout(treedef=treedef, n_leaves=len(leaves),
                        groups=tuple(groups), world=world,
                        n_buckets=n_buckets, chunk=chunk)


def flatten_group(layout: BucketLayout, group: GroupLayout, leaves,
                  dtype=jnp.float32):
    """Pack this group's leaves (from the full leaf list, aligned with
    the layout's tree order) into one padded ``(rows, chunk)`` buffer."""
    buf, meta = flatten_to_chunked(
        [leaves[i] for i in group.indices], chunk=layout.chunk,
        dtype=dtype, pad_rows_to=layout.world * layout.n_buckets)
    assert meta.n_rows == group.rows, (meta.n_rows, group.rows)
    return buf


def unflatten_groups(layout: BucketLayout, group_bufs, like_leaves):
    """Inverse of :func:`flatten_group` over all groups: scatter each
    group's leaves back into full-tree order and rebuild the tree.
    ``like_leaves`` supplies the output dtypes/shapes (the model params)."""
    out = list(like_leaves)
    for group, buf in zip(layout.groups, group_bufs):
        leaves = unflatten_from_chunked(buf, group.meta)
        for i, leaf in zip(group.indices, leaves):
            out[i] = leaf
    return jax.tree_util.tree_unflatten(layout.treedef, out)


def bucket_slices(buf, group: GroupLayout, n_buckets: int):
    """Static split of a full group buffer into its K bucket row-ranges."""
    rpb = group.rows_per_bucket
    return [lax.slice_in_dim(buf, k * rpb, (k + 1) * rpb, axis=0)
            for k in range(n_buckets)]


def local_slices(buf, group: GroupLayout, n_buckets: int, rank):
    """This rank's rows of each bucket, with **no communication** — the
    slicing dual of the tiled reduce-scatter (used to seed sharded
    optimizer state from replicated params, ``shard_leaf``'s bucket
    form)."""
    rpb, lr = group.rows_per_bucket, group.local_rows
    return [
        lax.dynamic_slice_in_dim(buf, k * rpb + rank * lr, lr, axis=0)
        for k in range(n_buckets)
    ]


def local_leaf_ids(group: GroupLayout, n_buckets: int, rank):
    """Per-bucket leaf ids (group-local) of this rank's rows — the
    segment ids for shard-local per-tensor reductions (LAMB trust
    ratios).  Non-decreasing within each bucket, so the segmented
    reductions keep ``indices_are_sorted``."""
    ids = jnp.asarray(group.meta.leaf_ids)
    rpb, lr = group.rows_per_bucket, group.local_rows
    return [
        lax.dynamic_slice_in_dim(ids, k * rpb + rank * lr, lr, axis=0)
        for k in range(n_buckets)
    ]


def bucket_reduce_scatter(buf, group: GroupLayout, cfg: AxisConfig,
                          n_buckets: int, *, outer_reduce_dtype=None):
    """ONE (hierarchical) reduce-scatter per bucket: full group buffer in,
    K summed local shards out.  Per-bucket profiler scopes
    (``apex/zero/reduce_scatter/bucket<k>``) make the bucketed-overlap
    schedule — gather of bucket k under the update tail of k+1 —
    readable in an xprof capture."""
    out = []
    for k, b in enumerate(bucket_slices(buf, group, n_buckets)):
        with named_span(f"zero/reduce_scatter/bucket{k}"):
            out.append(cc.hierarchical_reduce_scatter(
                b, cfg.scatter_axes, cfg.outer_axis, scatter_axis=0,
                outer_reduce_dtype=outer_reduce_dtype))
    return out


def bucket_all_gather(local_bufs, group: GroupLayout, cfg: AxisConfig,
                      dtype=None):
    """ONE all-gather per bucket (over the scatter axes only — the outer
    DCN tier already holds identical shards), concatenated back into the
    full group buffer.  ``dtype`` casts *before* the gather so
    half-precision params move half the bytes."""
    gathered = []
    for k, b in enumerate(local_bufs):
        with named_span(f"zero/all_gather/bucket{k}"):
            if dtype is not None:
                b = jnp.asarray(b, dtype)
            gathered.append(cc.hierarchical_all_gather(
                b, cfg.scatter_axes, concat_axis=0))
    return jnp.concatenate(gathered, axis=0)


class FlatBucketMixin:
    """Shared plumbing for flat-bucket-capable ZeRO optimizers: resolves
    the reduction topology and the bucket layout from the constructor
    attributes (``axis``, ``outer_axis``, ``flat_bucket``, ``n_buckets``,
    ``chunk``) and exposes the state ``PartitionSpec`` tree — ONE source
    for the layout rules both ``DistributedFusedAdam`` and
    ``DistributedFusedLAMB`` must agree on (``zero_init`` /
    ``zero_data_parallel_train_step`` build shard_map specs from it)."""

    def _init_bucket_config(self, *, flat_bucket: bool, n_buckets: int,
                            chunk: int, outer_axis: Optional[str],
                            dcn_reduce_dtype) -> None:
        """Set the bucket-layout knobs (call from the optimizer ctor).
        The hierarchical ``outer_axis`` only applies to the flat-bucket
        path — the per-leaf port is not hierarchy-aware."""
        self.flat_bucket = flat_bucket
        self.n_buckets = n_buckets
        self.chunk = chunk
        self.outer_axis = outer_axis if flat_bucket else None
        self.dcn_reduce_dtype = dcn_reduce_dtype

    def _cfg(self) -> AxisConfig:
        return resolve_axes(self.axis, self.outer_axis)

    def _layout(self, params, world: int) -> BucketLayout:
        return build_layout(params, world=world,
                            n_buckets=self.n_buckets, chunk=self.chunk)

    def state_partition_specs(self, params):
        """``PartitionSpec`` tree of ``init``'s output — what a
        ``shard_map`` carrying the sharded state across its boundary
        needs as in/out specs (rows sharded over the scatter axes; with
        a hierarchical ``outer_axis`` the shard is replicated across
        DCN, which the unmentioned axis already expresses)."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.optimizers._common import OptState

        if self.flat_bucket:
            return flat_state_specs(params, self.axis, self.n_buckets)
        chunk_spec = jax.tree_util.tree_map(lambda _: P(self.axis), params)
        return OptState(step=P(),
                        slots={"exp_avg": chunk_spec,
                               "exp_avg_sq": chunk_spec},
                        master=chunk_spec)


def init_flat_state(params, cfg: AxisConfig, layout: BucketLayout,
                    *, remainder_split=None):
    """Sharded flat-bucket optimizer state: zero moment buffers plus the
    local fp32 master rows, sliced from the replicated params with no
    communication.  ``remainder_split`` (the optimizer's ``split_fp32``)
    switches the master to the low-16-bit remainder buffers
    (``_bf16_rem_to_fp32``, ``distributed_fused_adam.py:240-265``)."""
    from apex_tpu.optimizers._common import OptState

    rank = flat_rank(cfg)
    leaves = jax.tree_util.tree_leaves(params)
    exp_avg, exp_avg_sq, master = [], [], []
    for group in layout.groups:
        def zeros():
            return [jnp.zeros((group.local_rows, layout.chunk), jnp.float32)
                    for _ in range(layout.n_buckets)]
        exp_avg.append(zeros())
        exp_avg_sq.append(zeros())
        p32 = flatten_group(layout, group, leaves, dtype=jnp.float32)
        locs = local_slices(p32, group, layout.n_buckets, rank)
        if remainder_split is not None:
            master.append([remainder_split(b)[1] for b in locs])
        else:
            master.append(locs)
    return OptState(step=jnp.int32(0),
                    slots={"exp_avg": exp_avg, "exp_avg_sq": exp_avg_sq},
                    master=master)


def flat_state_specs(params, axis: AxisSpec, n_buckets: int):
    """``PartitionSpec`` tree matching :func:`init_flat_state`'s output:
    buffer rows sharded over the scatter axes (a hierarchical outer tier
    is replicated, which the unmentioned axis already expresses)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers._common import OptState

    spec = P(tuple(axis)) if isinstance(axis, (tuple, list)) else P(axis)
    _, _, groups = host_groups(params)

    def bufs():
        return [[spec for _ in range(n_buckets)] for _ in groups]

    return OptState(step=P(), slots={"exp_avg": bufs(),
                                     "exp_avg_sq": bufs()},
                    master=bufs())
