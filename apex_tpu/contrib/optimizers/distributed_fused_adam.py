"""ZeRO-sharded Adam — ``DistributedFusedAdam`` rebuilt for SPMD.

Behavioral spec: ``apex/contrib/optimizers/distributed_fused_adam.py:266``
(docstring ``:267-369``): ZeRO-2 — optimizer state and reduced gradients
sharded over the data-parallel group, parameters replicated; gradients
reduce-scattered (not all-reduced), each rank steps only its shard, stepped
shards all-gathered back into the replicated parameters; optional bf16
state with the fp32-remainder storage trick (``_bf16_rem_to_fp32``
``:240-265``).

TPU-first mapping
-----------------
The reference hand-manages fixed-size flat buckets (``StateBucket:397``),
overlapped NCCL reduce-scatter during backward and param all-gathers in
forward.  Under SPMD inside ``shard_map`` there are two shapes:

**Flat-bucket (default, ``flat_bucket=True``)** — the bucketed shape of
the reference, rebuilt over chunked buffers (see
:mod:`._flat_bucket`): the whole grad tree is packed into one padded
``(rows, 256)`` buffer per dtype-group, reduce-scattered in
``n_buckets`` large collectives (not one per tensor), the local shard
stepped with the shared Adam math
(:func:`apex_tpu.optimizers._common.adam_apply`), and all-gathered back
in the model dtype.  The reduction is hierarchy-aware: reduce-scatter
rides the intra-slice ICI ``dp`` axis and the 1/dp shard is all-reduced
across the ``outer_axis`` (DCN) tier — optionally in bf16
(``dcn_reduce_dtype``) — instead of flattening ``(dcn, dp)`` into one
group (Xu et al., "Automatic Cross-Replica Sharding of Weight Update").

**Per-leaf (``flat_bucket=False``)** — the original port, kept for A/B
diagnosis and odd trees:

- each parameter leaf is raveled, padded to a multiple of the ``dp`` world
  and **reduce-scattered** (``lax.psum_scatter``) — the per-rank chunk *is*
  the bucket shard, contiguity for free, overlap scheduled by XLA;
- per-leaf chunking costs one collective pair per tensor — hundreds of
  small collectives on a real transformer, which is exactly what the
  reference's buckets exist to avoid and why flat-bucket is the default
  (bench row ``zero_adam_step``).

In both shapes Adam state (``exp_avg``/``exp_avg_sq``) and the fp32
master copy exist only for the local shard — the 1/dp state-memory
footprint that is ZeRO's point — and the stepped shard is all-gathered
back into the replicated parameter leaves (same total bytes on the wire
as a plain all-reduce: RS(g) + AG(p)).

``store_param_remainders`` reproduces the bf16+remainder trick exactly: the
fp32 master bits are split into the high 16 (the *truncated* bf16 the model
carries) and the low 16 stored as the only extra state — master precision
at half the master memory (``:240-265``).

Usage (inside the ``shard_map`` that binds the dp axis)::

    opt = DistributedFusedAdam(lr=1e-3, axis="dp")
    state = opt.init(params)                      # local shard state
    params, state = opt.step(local_grads, state, params)

``local_grads`` are the *pre-reduction* per-rank gradients; ``step`` does
the reduce-scatter itself (passing psum-reduced grads double-counts).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc
from apex_tpu.contrib.optimizers import _flat_bucket as fb
from apex_tpu.optimizers._common import (
    OptState,
    adam_apply,
    advance_step,
    apply_skip,
    f32,
    tree_map_multi,
)
from apex_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS

__all__ = ["DistributedFusedAdam", "shard_leaf", "unshard_leaf",
           "split_fp32", "join_fp32"]


def _world_rank(axis):
    return cc.axis_size(axis), cc.axis_index(axis)


def _chunk_size(n, world):
    return -(-n // world)  # ceil


def shard_leaf(x, axis):
    """Ravel + zero-pad + take this rank's chunk (no communication)."""
    world, rank = _world_rank(axis)
    flat = x.ravel()
    c = _chunk_size(flat.size, world)
    flat = jnp.pad(flat, (0, c * world - flat.size))
    return lax.dynamic_slice_in_dim(flat, rank * c, c)


def reduce_scatter_leaf(g, axis):
    """Ravel + pad + reduce-scatter: this rank's *summed* chunk.

    The ZeRO gradient reduction (``distributed_fused_adam.py`` docstring:
    "reduce-scatter instead of all-reduce").
    """
    world, _ = _world_rank(axis)
    flat = g.ravel()
    c = _chunk_size(flat.size, world)
    flat = jnp.pad(flat, (0, c * world - flat.size))
    return cc.reduce_scatter(flat, axis, scatter_axis=0)


def unshard_leaf(chunk, shape, dtype, axis):
    """All-gather chunks and restore the leaf shape/dtype.

    Casts to the model dtype *before* the gather so half-precision models
    move half the bytes (the reference all-gathers params in model dtype).
    """
    full = cc.all_gather(chunk.astype(dtype), axis, concat_axis=0)
    n = 1
    for s in shape:
        n *= s
    return full[:n].reshape(shape)


def split_fp32(x32):
    """fp32 -> (truncated bf16, int16 remainder) — ``_fp32_to_bf16_rem``."""
    bits = jax.lax.bitcast_convert_type(f32(x32), jnp.int32)
    hi = jax.lax.bitcast_convert_type(
        (bits >> 16).astype(jnp.int16), jnp.bfloat16
    )
    lo = (bits & 0xFFFF).astype(jnp.uint16)
    return hi, lo


def join_fp32(hi_bf16, lo_u16):
    """(bf16, remainder) -> exact fp32 — ``_bf16_rem_to_fp32``
    (``distributed_fused_adam.py:240-265``)."""
    hi = jax.lax.bitcast_convert_type(hi_bf16, jnp.int16).astype(jnp.int32)
    bits = (hi << 16) | lo_u16.astype(jnp.int32)
    return jax.lax.bitcast_convert_type(bits, jnp.float32)


class DistributedFusedAdam(fb.FlatBucketMixin):
    """ZeRO-2 Adam over the ``dp`` mesh axis (see module docstring)."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis=DATA_AXIS,
        grad_predivide_factor: Optional[float] = None,
        store_param_remainders: bool = False,
        flat_bucket: bool = True,
        n_buckets: int = 1,
        chunk: int = 256,
        outer_axis: Optional[str] = DCN_AXIS,
        dcn_reduce_dtype=None,
    ):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.axis = axis
        # reference averages grads over dp (predivide, distributed.py:229);
        # None = divide by world size.
        self.grad_predivide_factor = grad_predivide_factor
        self.store_param_remainders = store_param_remainders
        # flat_bucket=True: one padded chunked buffer per dtype-group,
        # split into n_buckets row-ranges — ONE reduce-scatter and ONE
        # all-gather per bucket (StateBucket:397's shape; n_buckets>1
        # lets XLA overlap bucket k's gather with bucket k+1's update
        # tail).  False keeps the per-leaf port (one collective pair per
        # tensor) for A/B diagnosis.  outer_axis is the hierarchical
        # tier: reduce-scatter over `axis` (ICI), all-reduce the shard
        # over `outer_axis` (DCN), optionally in `dcn_reduce_dtype`
        # (e.g. bf16 to halve cross-slice bytes); ignored when unbound
        # or size 1, so the default is correct at any scale.
        self._init_bucket_config(
            flat_bucket=flat_bucket, n_buckets=n_buckets, chunk=chunk,
            outer_axis=outer_axis, dcn_reduce_dtype=dcn_reduce_dtype)

    def init(self, params) -> OptState:
        if self.flat_bucket:
            return self._init_flat_bucket(params)

        def shard_zero(p):
            return jnp.zeros_like(shard_leaf(f32(p), self.axis))

        slots = {
            "exp_avg": jax.tree_util.tree_map(shard_zero, params),
            "exp_avg_sq": jax.tree_util.tree_map(shard_zero, params),
        }
        if self.store_param_remainders:
            def rem(p):
                _, lo = split_fp32(f32(shard_leaf(p, self.axis)))
                return lo
            master = jax.tree_util.tree_map(rem, params)
        else:
            master = jax.tree_util.tree_map(
                lambda p: f32(shard_leaf(p, self.axis)), params
            )
        return OptState(step=jnp.int32(0), slots=slots, master=master)

    def _init_flat_bucket(self, params) -> OptState:
        cfg = self._cfg()
        layout = self._layout(params, cfg.world_scatter)
        return fb.init_flat_state(
            params, cfg, layout,
            remainder_split=split_fp32 if self.store_param_remainders
            else None)

    def _master_shard(self, params, master):
        if self.store_param_remainders:
            # High bits live in the (replicated) bf16 params themselves.
            return jax.tree_util.tree_map(
                lambda p, lo: join_fp32(
                    shard_leaf(p, self.axis).astype(jnp.bfloat16), lo
                ),
                params, master,
            )
        return master

    def step(self, grads, state: OptState, params, *, lr=None,
             grad_scale=None, skip_update=None):
        if self.flat_bucket:
            return self._step_flat_bucket(grads, state, params, lr=lr,
                                          grad_scale=grad_scale,
                                          skip_update=skip_update)
        axis = self.axis
        world = cc.axis_size(axis)
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1

        # Predivide by f before the reduction, post-divide by world/f after
        # (net /world either way) — the overflow-headroom split of apex DDP
        # (apex/parallel/distributed.py gradient_predivide_factor), which a
        # bare replacement of the world divisor would *not* be.
        f = (f32(world) if self.grad_predivide_factor is None
             else f32(self.grad_predivide_factor))
        pre = 1.0 / f
        post = f / f32(world)
        if grad_scale is not None:
            pre = pre / f32(grad_scale)

        g_shards = jax.tree_util.tree_map(
            lambda g: reduce_scatter_leaf(f32(g) * pre, axis) * post, grads
        )
        p32 = self._master_shard(params, state.master)

        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            if not self.adam_w_mode and wd != 0.0:
                g = g + wd * p
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if self.adam_w_mode and wd != 0.0:
                update = update + wd * p
            return p - lr * update, m, v

        new_p32, new_m, new_v = tree_map_multi(
            leaf, 3, p32, g_shards,
            state.slots["exp_avg"], state.slots["exp_avg_sq"],
        )

        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        if self.store_param_remainders:
            hi_lo = jax.tree_util.tree_map(split_fp32, new_p32)
            new_master = jax.tree_util.tree_map(
                lambda hl: hl[1], hi_lo,
                is_leaf=lambda x: isinstance(x, tuple),
            )
            gather_src = jax.tree_util.tree_map(
                lambda hl: hl[0], hi_lo,
                is_leaf=lambda x: isinstance(x, tuple),
            )
        else:
            new_master = new_p32
            gather_src = new_p32

        new_params = jax.tree_util.tree_map(
            lambda chunk, p: unshard_leaf(chunk, jnp.shape(p),
                                          jnp.asarray(p).dtype, axis),
            gather_src, params,
        )
        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_master,
        )
        return new_params, new_state

    def _step_flat_bucket(self, grads, state: OptState, params, *, lr,
                          grad_scale, skip_update):
        """The bucketed ZeRO step: per dtype-group, ONE reduce-scatter per
        bucket in, shared Adam math on the local shard, ONE all-gather
        per bucket out (``StateBucket:397`` +
        ``_pipeline_step``-shaped exchange, expressed as chunked-buffer
        collectives XLA can overlap)."""
        cfg = self._cfg()
        layout = self._layout(params, cfg.world_scatter)
        rank = fb.flat_rank(cfg)
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1

        # predivide/postdivide split exactly as the per-leaf path; the
        # averaging divisor is the TOTAL replica count (inner dp x outer
        # dcn tier).
        f = (f32(cfg.world_total) if self.grad_predivide_factor is None
             else f32(self.grad_predivide_factor))
        pre = 1.0 / f
        post = f / f32(cfg.world_total)
        if grad_scale is not None:
            pre = pre / f32(grad_scale)

        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        g_leaves = layout.treedef.flatten_up_to(grads)
        p_leaves = layout.treedef.flatten_up_to(params)

        old_p32, new_p, new_m, new_v = [], [], [], []
        for gi, group in enumerate(layout.groups):
            g32 = fb.flatten_group(layout, group, g_leaves,
                                   dtype=jnp.float32)
            g_loc = fb.bucket_reduce_scatter(
                g32 * pre, group, cfg, layout.n_buckets,
                outer_reduce_dtype=self.dcn_reduce_dtype)
            g_loc = [g * post for g in g_loc]
            if self.store_param_remainders:
                # High bits live in the (replicated) bf16 params.
                hi = fb.flatten_group(layout, group, p_leaves,
                                      dtype=jnp.bfloat16)
                hi_loc = fb.local_slices(hi, group, layout.n_buckets, rank)
                p32 = [join_fp32(h, lo)
                       for h, lo in zip(hi_loc, state.master[gi])]
            else:
                p32 = state.master[gi]
            stepped = [
                adam_apply(p, g, m, v, lr=lr, b1=b1, b2=b2, eps=eps, wd=wd,
                           bc1=bc1, bc2=bc2, adam_w_mode=self.adam_w_mode)
                for p, g, m, v in zip(p32, g_loc,
                                      state.slots["exp_avg"][gi],
                                      state.slots["exp_avg_sq"][gi])
            ]
            old_p32.append(p32)
            new_p.append([s[0] for s in stepped])
            new_m.append([s[1] for s in stepped])
            new_v.append([s[2] for s in stepped])

        new_p = apply_skip(skip_update, new_p, old_p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        full_bufs, new_master = [], []
        for gi, group in enumerate(layout.groups):
            if self.store_param_remainders:
                hi_lo = [split_fp32(p) for p in new_p[gi]]
                new_master.append([hl[1] for hl in hi_lo])
                gather_src = [hl[0] for hl in hi_lo]
            else:
                new_master.append(new_p[gi])
                gather_src = new_p[gi]
            full_bufs.append(fb.bucket_all_gather(
                gather_src, group, cfg, dtype=group.dtype))
        new_params = fb.unflatten_groups(layout, full_bufs, p_leaves)

        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_master,
        )
        return new_params, new_state
