"""Distributed (ZeRO-sharded) optimizers — reference
``apex/contrib/optimizers``."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (
    DistributedFusedLAMB,
)

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB"]
