"""index_mul_2d: ``out = in1[idx] * in2`` fused gather-multiply.

Behavioral spec: ``apex/contrib/index_mul_2d/index_mul_2d.py`` — 2D
``in1 [N1, H]``, ``in2 [N2, H]``, ``idx [N2]`` indexing dim 0 of ``in1``;
backward scatter-adds ``grad_out * in2`` into ``grad_in1`` and gathers for
``grad_in2`` (their dedicated CUDA kernels incl. a fp16 variant with fp32
atomics).

TPU-first: ``jnp.take`` + multiply is one fused XLA gather-mul, and the
autodiff transpose of the gather *is* the scatter-add the reference hand
writes — no custom kernels, identical semantics, fp32 accumulation for
low-precision inputs via ``preferred`` upcast of the scatter (XLA default).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1, in2, idx1):
    """``out[i, :] = in1[idx1[i], :] * in2[i, :]``.

    Shape/dtype checks mirror the reference's (2D float tensors, matching
    dtypes, 1D int index).
    """
    if in1.ndim != 2 or in2.ndim != 2:
        raise ValueError("in1 and in2 must be 2-dimension tensors")
    if idx1.ndim != 1:
        raise ValueError("idx1 must be a 1-dimension tensor")
    if in2.shape[0] != idx1.shape[0]:
        raise ValueError("in2 and idx1 must agree on dim 0")
    if in1.dtype != in2.dtype:
        raise ValueError("in1 and in2 must share a dtype")
    return jnp.take(in1, idx1, axis=0) * in2
