"""Fused sigmoid focal loss (detection).

Behavioral spec: ``apex/contrib/focal_loss/focal_loss.py:6-60`` +
``apex/contrib/csrc/focal_loss/focal_loss_cuda_kernel.cu:16-131``:
per-anchor integer targets ``y`` with the EfficientDet conventions —
``y >= 0``: positive match at class ``y``; ``y == -1``: all-negative
anchor; ``y == -2``: ignored anchor (zero loss/grad); classes past
``num_real_classes`` are padding and contribute nothing.  Loss is summed
over all elements and normalized by ``num_positives_sum``; label smoothing
redistributes ``smoothing/K`` mass exactly as the kernel's
``nn/np/pn/pp_norm`` coefficients.

TPU-first: the kernel's stabilized ``base + off_a`` decomposition is just
the standard softplus-form BCE with a soft target ``q``::

    bce   = softplus(x) - q * x          # = -(q log σ + (1-q) log(1-σ))
    coeff = α·(1-σ)^γ  (positives)  |  (1-α)·σ^γ  (negatives)
    loss  = Σ coeff · bce / num_positives_sum

One fused XLA elementwise chain + reduction; gradients come from autodiff
of the same expression (the CUDA side saves ``partial_grad`` in forward —
unnecessary under XLA, recompute is a fused flop, not an HBM trip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss"]


def focal_loss(
    cls_output,
    cls_targets_at_level,
    num_positives_sum,
    num_real_classes: int,
    alpha: float,
    gamma: float,
    label_smoothing: float = 0.0,
):
    """Scalar focal loss.

    ``cls_output: [..., K_pad]`` logits (fp32/bf16/fp16),
    ``cls_targets_at_level: [...]`` int targets (-2 ignore, -1 negative,
    >=0 positive class), ``num_positives_sum``: scalar normalizer.
    """
    x = cls_output.astype(jnp.float32)
    y = cls_targets_at_level
    K = x.shape[-1]

    cls_idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    pos = (y[..., None] >= 0) & (cls_idx == y[..., None])
    valid = (y[..., None] != -2) & (cls_idx < num_real_classes)

    s = label_smoothing
    q_pos = 1.0 - s + s / num_real_classes
    q_neg = s / num_real_classes
    q = jnp.where(pos, q_pos, q_neg)

    bce = jax.nn.softplus(x) - q * x
    sig = jax.nn.sigmoid(x)
    coeff = jnp.where(pos,
                      alpha * (1.0 - sig) ** gamma,
                      (1.0 - alpha) * sig ** gamma)
    loss = jnp.where(valid, coeff * bce, 0.0)
    return jnp.sum(loss) / jnp.asarray(num_positives_sum, jnp.float32).reshape(())
