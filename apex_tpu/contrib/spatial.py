"""Spatial (2D-conv) parallelism: halo exchange + spatial bottleneck.

Behavioral spec: ``apex/contrib/peer_memory/peer_halo_exchanger_1d.py:5``
(exchange ``half_halo`` boundary rows with the low/high neighbor over CUDA
IPC peer memory; outermost ranks receive zeros) and
``apex/contrib/bottleneck/bottleneck.py:265,603`` (``SpatialBottleneck``:
ResNet-v1.5 bottleneck whose 3×3 conv runs on an H-split input with halo
exchange around it).

TPU-first: the halo exchange is one :func:`jax.lax.ppermute` pair on the
spatial mesh axis — ppermute's "missing source ⇒ zeros" semantics *is*
the reference's ``low_zero``/``high_zero`` edge handling, and XLA
schedules the two shifts concurrently with surrounding compute (the
reference hand-manages three CUDA streams for the same overlap).  No peer
pools, no IPC: ICI neighbors on the mesh axis are the peers.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel import collectives as cc

__all__ = ["halo_exchange_1d", "SpatialBottleneck", "spatial_conv_nhwc"]


def halo_exchange_1d(x, axis: str, half_halo: int, dim: int = 1):
    """Pad the locally-sharded spatial dim with neighbors' boundary rows.

    ``x``: this rank's shard (no halos), ``dim``: the split spatial dim
    (NHWC H by default).  Returns ``x`` extended by ``half_halo`` rows on
    both sides: rows received from the low/high neighbor on ``axis``, or
    zeros at the group edges (reference ``PeerHaloExchanger1d.__call__``).
    """
    if half_halo == 0:
        return x
    world = cc.axis_size(axis)
    n = x.shape[dim]
    if n < half_halo:
        raise ValueError(f"shard dim {n} smaller than halo {half_halo}")
    lo_edge = lax.slice_in_dim(x, 0, half_halo, axis=dim)
    hi_edge = lax.slice_in_dim(x, n - half_halo, n, axis=dim)
    # send my high edge to my high neighbor (their low halo), my low edge
    # to my low neighbor (their high halo); non-wrapping perms zero-fill.
    from_low = lax.ppermute(hi_edge, axis,
                            [(r, r + 1) for r in range(world - 1)])
    from_high = lax.ppermute(lo_edge, axis,
                             [(r + 1, r) for r in range(world - 1)])
    return jnp.concatenate([from_low, x, from_high], axis=dim)


def spatial_conv_nhwc(x, kernel, axis: str, *, stride: int = 1,
                      dilation: int = 1):
    """3×3-style conv over an H-split NHWC shard: halo-exchange then a
    conv that is VALID on H (halos supply the padding) and SAME on W."""
    kh = kernel.shape[0]
    half_halo = dilation * (kh - 1) // 2
    xp = halo_exchange_1d(x, axis, half_halo, dim=1)
    pw = (dilation * (kernel.shape[1] - 1)) // 2
    return lax.conv_general_dilated(
        xp, kernel,
        window_strides=(stride, stride),
        padding=((0, 0), (pw, pw)),
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


class SpatialBottleneck(nn.Module):
    """ResNet-v1.5 bottleneck with the 3×3 conv spatial-parallel over
    ``axis`` (reference ``SpatialBottleneck``, ``bottleneck.py:603``;
    stride lives on the 3×3 as in torchvision/v1.5).

    ``axis=None`` degrades to a plain (single-rank) bottleneck — the same
    convention as :class:`apex_tpu.parallel.SyncBatchNorm`.  ``norm``
    defaults to frozen scale+bias (the reference passes baked BN
    scale/bias tensors); pass a module factory for live normalization.
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dilation: int = 1
    axis: Optional[str] = None
    norm: Optional[Callable[[], nn.Module]] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        def norm(name):
            if self.norm is not None:
                return self.norm()
            return _FrozenScaleBias(name=name)

        conv = lambda feats, k, s, name: nn.Conv(  # noqa: E731
            feats, (k, k), strides=(s, s), padding="SAME", use_bias=False,
            dtype=self.dtype, name=name)

        residual = x
        out = conv(self.bottleneck_channels, 1, 1, "conv1")(x)
        out = nn.relu(norm("bn1")(out))

        if self.axis is None:
            out = conv(self.bottleneck_channels, 3, self.stride,
                       "conv2")(out)
        else:
            kernel = self.param(
                "conv2_kernel", nn.initializers.he_normal(),
                (3, 3, self.bottleneck_channels, self.bottleneck_channels),
                self.dtype)
            out = spatial_conv_nhwc(out, kernel, self.axis,
                                    stride=self.stride,
                                    dilation=self.dilation)
        out = nn.relu(norm("bn2")(out))

        out = conv(self.out_channels, 1, 1, "conv3")(out)
        out = norm("bn3")(out)

        if (self.stride != 1 or self.in_channels != self.out_channels):
            residual = conv(self.out_channels, 1, self.stride,
                            "downsample")(x)
            residual = norm("bn_ds")(residual)
        return nn.relu(out + residual)


class _FrozenScaleBias(nn.Module):
    """Per-channel scale+bias (the reference's baked frozen-BN tensors)."""

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        b = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return x * s + b
