"""RNN-T transducer joint + loss.

Behavioral spec: ``apex/contrib/transducer/transducer.py`` —
``TransducerJoint`` (``:5-66``: f[B,T,H] + g[B,U,H] broadcast-add with
optional fused ReLU/dropout; packed don't-care removal) and
``TransducerLoss`` (``:68-157``: log_softmax → alpha/beta forward-backward
over the (T, U) lattice → -log P(y|x), with the softmax backward fused
into the loss gradient), per "Sequence Transduction with Recurrent Neural
Networks" (Graves 2012).

TPU-first design:
- The joint is a fused broadcast add + epilogue; packing
  (``pack_output``) is a CUDA memory optimization for ragged batches —
  on TPU static dense shapes + length masking compile better, so packed
  mode is deliberately absent (documented divergence).
- The loss DP runs as a **wavefront scan over anti-diagonals in skewed
  coordinates**: ``A[d, u] = alpha[d-u, u]`` turns both dependencies
  (``alpha[t-1,u]``, ``alpha[t,u-1]``) into reads of the *previous* skew
  row, so one ``lax.scan`` of T+U steps with [B, U+1]-vector body covers
  the lattice — O(T·U) work, T+U sequential steps, no per-cell Python.
- Gradients come from autodiff through the scan: the transposed scan *is*
  the beta recursion, and differentiating through the in-graph
  log_softmax fuses the softmax backward exactly like
  ``fuse_softmax_backward=True``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["TransducerJoint", "transducer_joint", "transducer_loss"]

NEG = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, relu: bool = False,
                     dropout_rate: float = 0.0, dropout_rng=None):
    """``out[b,t,u,:] = f[b,t,:] + g[b,u,:]`` with optional fused
    ReLU/dropout epilogue; positions past ``f_len``/``g_len`` are zeroed
    (the dense analog of the reference's packed don't-care removal)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
    if f_len is not None:
        t_ok = jnp.arange(f.shape[1])[None, :] < f_len[:, None]
        out = out * t_ok[:, :, None, None]
    if g_len is not None:
        u_ok = jnp.arange(g.shape[1])[None, :] < g_len[:, None] + 1
        out = out * u_ok[:, None, :, None]
    return out


class TransducerJoint:
    """Module-style wrapper mirroring the reference constructor knobs."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA ragged-memory optimization; the "
                "TPU build uses dense shapes + masking (see module doc)")
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, dropout_rng=None,
                 training: bool = True):
        rate = self.dropout_prob if (self.dropout and training) else 0.0
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_rate=rate, dropout_rng=dropout_rng)


def _skew(m, fill):
    """``[B, T, U1] -> [B, T+U1-1, U1]`` with ``S[b, d, u] = m[b, d-u, u]``
    (invalid cells = ``fill``)."""
    B, T, U1 = m.shape
    D = T + U1 - 1
    d = jnp.arange(D)[:, None]
    u = jnp.arange(U1)[None, :]
    t = d - u
    valid = (t >= 0) & (t < T)
    tc = jnp.clip(t, 0, T - 1)
    out = m[:, tc, u[0]]            # [B, D, U1] gather over t
    return jnp.where(valid[None, :, :], out, fill)


def transducer_loss(x, label, f_len, y_len, blank_idx: int,
                    log_probs: bool = False):
    """Per-batch RNN-T loss ``[B]``.

    ``x: [B, T, U+1, K]`` joint logits (``log_probs=True`` to pass
    pre-computed log-probabilities), ``label: [B, U]`` int targets,
    ``f_len``: time lengths, ``y_len``: label lengths, ``blank_idx``: the
    null symbol (reference ``TransducerLoss.forward``).
    """
    B, T, U1, K = x.shape
    logp = x if log_probs else jax.nn.log_softmax(
        x.astype(jnp.float32), axis=-1)

    lp_blank = logp[..., blank_idx]                     # [B, T, U1]
    lab = jnp.clip(label, 0, K - 1)[:, None, :, None]   # [B, 1, U, 1]
    lab = jnp.broadcast_to(lab, (B, T, U1 - 1, 1))
    lp_emit = jnp.take_along_axis(logp[:, :, :U1 - 1, :], lab, axis=-1)
    lp_emit = lp_emit[..., 0]                           # [B, T, U]
    # emits past y_len are unreachable on any path to (f_len-1, y_len);
    # poison them anyway so partial DP rows can be inspected/debugged.
    u_ok = jnp.arange(U1 - 1)[None, None, :] < y_len[:, None, None]
    lp_emit = jnp.where(u_ok, lp_emit, NEG)
    lp_emit = jnp.pad(lp_emit, ((0, 0), (0, 0), (0, 1)),
                      constant_values=NEG)              # [B, T, U1]

    Bs = _skew(lp_blank, NEG)                           # [B, D, U1]
    Es = _skew(lp_emit, NEG)
    D = T + U1 - 1

    a0 = jnp.full((B, U1), NEG).at[:, 0].set(0.0)

    def step(prev, rows):
        b_row, e_row = rows                             # [B, U1] each
        blank_term = prev + b_row
        emit_term = (jnp.pad(prev[:, :-1], ((0, 0), (1, 0)),
                             constant_values=NEG)
                     + jnp.pad(e_row[:, :-1], ((0, 0), (1, 0)),
                               constant_values=NEG))
        new = jnp.logaddexp(blank_term, emit_term)
        return new, new

    rows = (jnp.moveaxis(Bs[:, :D - 1], 1, 0),
            jnp.moveaxis(Es[:, :D - 1], 1, 0))          # [D-1, B, U1]
    _, ys = lax.scan(step, a0, rows)
    A = jnp.concatenate([a0[None], ys], axis=0)         # [D, B, U1]

    # unskew the cells we need: alpha[b, f_len-1, y_len] = A[fl-1+yl, b, yl]
    bidx = jnp.arange(B)
    tl = f_len - 1
    ul = y_len
    alpha_end = A[tl + ul, bidx, ul]
    final_blank = lp_blank[bidx, tl, ul]
    return -(alpha_end + final_blank)


class TransducerLoss:
    """Module-style wrapper (reference ``TransducerLoss:68``); softmax
    backward is always fused (autodiff through the in-graph log_softmax)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False):
        if packed_input:
            raise NotImplementedError(
                "packed input is a CUDA ragged-memory optimization; the "
                "TPU build uses dense shapes + masking (see module doc)")

    def __call__(self, x, label, f_len, y_len, blank_idx: int):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
