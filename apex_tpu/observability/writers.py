"""Crash-safe metrics storage — append-only fsync'd JSONL.

The PR 3 checkpoint layer's durability argument, applied to metrics: a
run that dies must leave behind (a) every metric record that was
acknowledged and (b) a file a reader can always parse.  The format that
satisfies both with no recovery machinery is append-only JSONL with one
``open → write → fsync → close`` cycle per record:

- each record is a single ``os.write`` of one newline-terminated line to
  an ``O_APPEND`` descriptor — concurrent writers interleave at line
  granularity, never mid-line;
- ``fsync`` before the call returns makes acknowledged records durable
  (the same contract as the checkpoint temp-fsync-rename protocol,
  without the rename: appends never replace committed bytes);
- a crash mid-write can tear at most the *final* line;
  :func:`read_jsonl` therefore treats an unparseable tail as the
  expected torn-write artifact and returns the intact prefix (a torn
  *interior* line — real corruption — is skipped with a warning, or
  fatal under ``strict=True``);
- transient ``OSError`` (the NFS/GCS-fuse blip the checkpoint manager
  retries) gets the same bounded retry-with-backoff here
  (``testing/faults.transient_os_errors`` drives the test).

Rank-awareness lives one layer up (``MetricRegistry.flush`` writes only
on rank 0); this module is deliberately a dumb, durable pipe.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator, List, Optional

__all__ = ["JsonlWriter", "read_jsonl", "iter_jsonl"]

logger = logging.getLogger(__name__)


class JsonlWriter:
    """Append-only fsync'd JSONL writer.

    ``fsync=False`` trades durability of the last few records for write
    latency (the OS still sees every byte; only a *power* loss can eat
    buffered lines) — keep the default for rank-0 training telemetry,
    where one fsync per ``log_every_n`` steps is noise.
    """

    def __init__(self, path: str, *, fsync: bool = True, retries: int = 3,
                 backoff_s: float = 0.05, keep_open: bool = False,
                 rotate_bytes: Optional[int] = None):
        self.path = path
        self.fsync = fsync
        self.retries = retries
        self.backoff_s = backoff_s
        # rotate_bytes (default off, ISSUE 20): when appending the next
        # record would push the live file past the bound, the file is
        # first renamed to a `<stem>.rot-NNNNNN.jsonl` segment and the
        # append opens a fresh file.  Rotation happens strictly BETWEEN
        # records (a frame boundary), so every segment keeps the
        # torn-tail-only durability contract: the single-write line
        # atomicity is untouched, only the file the O_APPEND descriptor
        # points at changes.  Segment names keep the `.jsonl` suffix so
        # spill readers glob them up; ``trace.read_fleet_spills`` groups
        # segments back into one logical stream in rotation order.
        if rotate_bytes is not None and rotate_bytes <= 0:
            raise ValueError("rotate_bytes must be positive (or None)")
        self.rotate_bytes = rotate_bytes
        self.rotations = 0
        self._size: Optional[int] = None   # live-file bytes, lazy stat
        # keep_open=True holds one O_APPEND descriptor across records
        # instead of an open→write→close cycle per record.  Durability
        # is IDENTICAL (each record is still a single O_APPEND
        # ``os.write`` of one full line — torn-tail-only under SIGKILL,
        # line-atomic against concurrent appenders); what changes is
        # the per-record syscall cost (~54µs → ~10µs measured), which
        # matters on event-per-token spill rates (the ISSUE 15 traced
        # serving path).  Keep the default for rank-0 training metrics,
        # where a descriptor held across a fork/preemption is a leak
        # hazard and one open per logged step is noise.
        self.keep_open = keep_open
        self._fd: int = -1
        self.records_written = 0
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def write(self, record: dict) -> None:
        """Durably append one record.  Serialization errors propagate
        immediately (a bug, not weather); ``OSError`` is retried with
        exponential backoff and re-raised when the budget is spent.

        The retry tracks how many bytes actually landed, so a blip
        *after* the append (fsync, close) never re-appends the record as
        a duplicate, and a short/torn write is completed from where it
        stopped rather than restarted (O_APPEND continues the same
        line)."""
        data = (json.dumps(record, separators=(",", ":"),
                           default=_json_fallback) + "\n").encode()
        if self.rotate_bytes is not None:
            self._maybe_rotate(len(data))
        sent = 0
        for attempt in range(self.retries + 1):
            try:
                if self.keep_open:
                    if self._fd < 0:
                        self._fd = os.open(
                            self.path,
                            os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                            0o644)
                    while sent < len(data):
                        sent += os.write(self._fd, data[sent:])
                    if self.fsync:
                        os.fsync(self._fd)
                else:
                    # Open-per-record: no long-lived descriptor to leak
                    # across a fork/preemption, and the O_APPEND
                    # single-shot write keeps the line contiguous even
                    # with a concurrent writer.
                    fd = os.open(self.path,
                                 os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                                 0o644)
                    try:
                        while sent < len(data):
                            sent += os.write(fd, data[sent:])
                        if self.fsync:
                            os.fsync(fd)
                    finally:
                        os.close(fd)
                self.records_written += 1
                if self._size is not None:
                    self._size += len(data)
                return
            except OSError as e:
                # a kept descriptor that errored is suspect (stale NFS
                # handle, rotated file): drop it and let the retry
                # reopen — O_APPEND continues the same line from `sent`
                self.close()
                if attempt == self.retries:
                    raise
                delay = self.backoff_s * (2.0 ** attempt)
                logger.warning(
                    "metrics append to %s failed (%r), retry %d/%d in "
                    "%.2fs", self.path, e, attempt + 1, self.retries, delay)
                time.sleep(delay)

    def _rotated_name(self, seq: int) -> str:
        stem, ext = os.path.splitext(self.path)
        if ext != ".jsonl":
            stem, ext = self.path, ""
        return f"{stem}.rot-{seq:06d}{ext}"

    def _maybe_rotate(self, incoming: int) -> None:
        """Rename the live file aside when the next append would cross
        ``rotate_bytes`` — between records only, so every segment ends
        on a whole line.  Rotation is best-effort: a failed rename logs
        and keeps appending (durability beats the size bound)."""
        if self._size is None:
            try:
                self._size = os.stat(self.path).st_size
            except OSError:
                self._size = 0
        if self._size <= 0 or self._size + incoming <= self.rotate_bytes:
            return
        seq = self.rotations + 1
        while os.path.exists(self._rotated_name(seq)):
            seq += 1          # a restarted writer never clobbers history
        try:
            self.close()      # the kept descriptor must follow the file
            os.rename(self.path, self._rotated_name(seq))
        except OSError as e:
            logger.warning("JSONL rotation of %s failed (%r); appending "
                           "past rotate_bytes", self.path, e)
            return
        self.rotations = seq
        self._size = 0

    def close(self) -> None:
        """Release the kept descriptor (keep_open mode); a later write
        reopens.  No-op in open-per-record mode."""
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = -1


def _json_fallback(obj):
    """Serialize the numpy/jax scalars metric records naturally carry."""
    if hasattr(obj, "item"):
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj)!r}")


def iter_jsonl(path: str, *, strict: bool = False) -> Iterator[dict]:
    """Yield records, tolerating the crash artifacts the writer can
    leave: a torn FINAL line (writer died mid-append) is silently
    dropped — even under ``strict``, because it is the *expected* shape
    of a crash, not corruption; a torn interior line IS storage
    corruption — skipped with a warning, or raised under
    ``strict=True``."""
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    n = len(lines)
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            yield json.loads(line)
        except json.JSONDecodeError as e:
            # A tear happens mid-append, so the torn line is the file's
            # very last content AND unterminated (no trailing newline —
            # a terminated garbage line is interior corruption instead).
            if i == n - 1:
                logger.info("dropping torn JSONL tail in %s", path)
                return
            if strict:
                raise ValueError(
                    f"corrupt JSONL line {i} in {path}: {e}") from e
            logger.warning(
                "skipping corrupt JSONL line %d in %s (%s)", i, path, e)


def read_jsonl(path: str, *, strict: bool = False) -> List[dict]:
    """All intact records of a (possibly torn) metrics file."""
    return list(iter_jsonl(path, strict=strict))
