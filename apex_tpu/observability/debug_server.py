"""Live introspection endpoint — ``/metrics`` + ``/statusz`` + ``/healthz``.

Opt-in, stdlib-only (``http.server`` on a daemon thread): a long
training or serving process answers two questions over plain HTTP
without any agent, sidecar, or dependency the container doesn't have:

- ``GET /metrics`` — the :class:`~apex_tpu.observability.metrics.
  MetricRegistry` snapshot in Prometheus text exposition format
  (counters, gauges, histograms as ``_count``/``_sum``/``_min``/
  ``_max``/``quantile`` series), so any standard scraper ingests the
  whole PR 5/PR 8 catalog.  Names are sanitized (``serving/ttft_ms`` →
  ``apex_serving_ttft_ms``); every series carries a ``rank`` label so
  multi-host scrapes stay distinguishable (the host-local/global split,
  docs/observability.md).
- ``GET /metrics.prom`` — the same snapshot in strict OpenMetrics 1.0
  text (ISSUE 20): paired ``# HELP``/``# TYPE`` per family, counter
  samples suffixed ``_total``, terminated by ``# EOF`` — for scrapers
  that negotiate the OpenMetrics content type and reject the laxer
  Prometheus 0.0.4 body.
- ``GET /statusz`` — JSON for a human mid-incident: the flight
  recorder's timeline tail and goodput-so-far, plus the serving
  engine's live state (active slots, free blocks, queue depth,
  draining, MFU or the reason it is undefined) when one is attached.
- ``GET /healthz`` — the ONE health contract the fleet router and any
  external probe share (ISSUE 11): liveness is answering at all;
  readiness is the body's ``status`` — ``ok`` (HTTP 200) vs
  ``draining``/``down`` (HTTP 503, so a stock HTTP prober needs no
  JSON parsing).  ``draining`` comes from the attached engine's
  ``introspect()``; ``down`` means the engine is attached but its
  introspection raises — the process answers, the runtime inside it is
  broken.

Security model: binds ``127.0.0.1`` by default and serves read-only
snapshots — exposing it beyond the host is the operator's deliberate
choice (``host="0.0.0.0"``).

The server thread only ever *reads* locked snapshots
(``registry.snapshot_typed()``, ``recorder.tail()``/``report()``,
``engine.introspect()``); it can never block or mutate the training
loop — the free-telemetry discipline applied to introspection.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from typing import Optional

__all__ = ["DebugServer", "render_prometheus", "render_openmetrics"]

logger = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "apex_" + _NAME_RE.sub("_", name)


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    return repr(float(v))


def render_prometheus(registry) -> str:
    """Prometheus text exposition of one registry snapshot (the typed
    form — the ``# TYPE`` lines need each metric's kind, which the flat
    ``snapshot()`` erases)."""
    lines = []
    label = f'{{rank="{registry.rank}"}}'
    typed = registry.snapshot_typed()
    counters, gauges, hists = (typed["counters"], typed["gauges"],
                               typed["histograms"])
    for name, value in sorted(counters.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn}{label} {_prom_value(value)}")
    for name, value in sorted(gauges.items()):
        if value is None:
            continue
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn}{label} {_prom_value(value)}")
    for name, s in sorted(hists.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        lines.append(f"{pn}_count{label} {_prom_value(s['count'])}")
        lines.append(f"{pn}_sum{label} {_prom_value(s['total'])}")
        for key, q in (("p50", "0.5"), ("p99", "0.99")):
            if s.get(key) is not None:
                lines.append(
                    f'{pn}{{rank="{registry.rank}",quantile="{q}"}} '
                    f"{_prom_value(s[key])}")
        for key in ("min", "max", "last"):
            if s.get(key) is not None:
                lines.append(f"{pn}_{key}{label} {_prom_value(s[key])}")
    return "\n".join(lines) + "\n"


def render_openmetrics(registry) -> str:
    """OpenMetrics 1.0 text exposition of one registry snapshot
    (ISSUE 20: ``/metrics.prom``) — the stricter sibling of
    :func:`render_prometheus` for scrapers that negotiate the
    OpenMetrics content type: every metric family carries a paired
    ``# HELP``/``# TYPE`` preamble, counter *samples* take the
    mandatory ``_total`` suffix (the family name stays suffix-free),
    histograms expose as summaries (``_count``/``_sum`` + ``quantile``
    labels), and the body terminates with the required ``# EOF``.  The
    format-lint test in ``tests/test_slo.py`` parses this line by line
    so the scrape surface cannot silently drift."""
    lines = []
    label = f'{{rank="{registry.rank}"}}'
    typed = registry.snapshot_typed()

    def meta(pn: str, mtype: str, name: str) -> None:
        lines.append(f"# HELP {pn} apex_tpu metric {name}")
        lines.append(f"# TYPE {pn} {mtype}")

    for name, value in sorted(typed["counters"].items()):
        pn = _prom_name(name)
        meta(pn, "counter", name)
        lines.append(f"{pn}_total{label} {_prom_value(value)}")
    for name, value in sorted(typed["gauges"].items()):
        if value is None:
            continue
        pn = _prom_name(name)
        meta(pn, "gauge", name)
        lines.append(f"{pn}{label} {_prom_value(value)}")
    for name, s in sorted(typed["histograms"].items()):
        pn = _prom_name(name)
        meta(pn, "summary", name)
        lines.append(f"{pn}_count{label} {_prom_value(s['count'])}")
        lines.append(f"{pn}_sum{label} {_prom_value(s['total'])}")
        for key, q in (("p50", "0.5"), ("p99", "0.99")):
            if s.get(key) is not None:
                lines.append(
                    f'{pn}{{rank="{registry.rank}",quantile="{q}"}} '
                    f"{_prom_value(s[key])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class DebugServer:
    """Background HTTP thread serving ``/metrics`` and ``/statusz``.

    ``port=0`` binds an ephemeral port (resolved on :meth:`start` —
    read ``.port``).  ``recorder``/``engine`` are optional; absent
    sections render as ``null`` in ``/statusz``.  ``engine`` duck-types
    anything with ``introspect() -> dict`` (the serving engine)."""

    def __init__(self, *, registry=None, recorder=None, engine=None,
                 host: str = "127.0.0.1", port: int = 0,
                 tail_events: int = 64):
        if registry is None:
            from apex_tpu.observability.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.recorder = recorder
        self.engine = engine
        self.host = host
        self.port = port
        self.tail_events = tail_events
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ payloads

    def metrics_text(self) -> str:
        return render_prometheus(self.registry)

    def metrics_prom_text(self) -> str:
        return render_openmetrics(self.registry)

    def statusz(self) -> dict:
        rec = self.recorder
        if rec is None:
            from apex_tpu.observability import timeline

            rec = timeline.active()
        out = {
            "rank": self.registry.rank,
            "world": self.registry.world,
            "timeline": None,
            "goodput": None,
            "serving": None,
        }
        if rec is not None:
            out["timeline"] = rec.tail(self.tail_events)
            out["goodput"] = rec.report()
        engine = self.engine
        if engine is not None:
            try:
                out["serving"] = engine.introspect()
            except Exception as e:  # introspection must never 500 a scrape
                out["serving"] = {"error": repr(e)}
        return out

    def fleet_statusz(self) -> Optional[dict]:
        """The fleet aggregation plane (ISSUE 15): when the attached
        engine duck-types ``fleet_statusz()`` (a
        :class:`~apex_tpu.serving.fleet.FleetRouter`), its merged
        heartbeats + per-tenant/per-priority SLO view; ``None`` (a 404)
        otherwise — a plain engine has no fleet to aggregate."""
        engine = self.engine
        fn = getattr(engine, "fleet_statusz", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # aggregation must never 500 a scrape
            return {"error": repr(e)}

    def healthz(self) -> tuple:
        """``(http_code, payload)`` for ``/healthz``: 200 ``ok`` / 503
        ``draining`` / 503 ``down`` — the readiness half of the health
        contract (liveness is the connection succeeding at all; a dead
        process refuses it)."""
        engine = self.engine
        if engine is None:
            # nothing attached: the server answering IS the health fact
            return 200, {"status": "ok", "engine": False}
        try:
            draining = bool(engine.introspect().get("draining"))
        except Exception as e:  # the runtime behind the probe is broken
            return 503, {"status": "down", "engine": True,
                         "error": repr(e)}
        if draining:
            return 503, {"status": "draining", "engine": True}
        return 200, {"status": "ok", "engine": True}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "DebugServer":
        if self._httpd is not None:
            return self
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                try:
                    if self.path.split("?")[0] == "/metrics":
                        self._send(200, server.metrics_text().encode(),
                                   "text/plain; version=0.0.4")
                    elif self.path.split("?")[0] == "/metrics.prom":
                        self._send(200,
                                   server.metrics_prom_text().encode(),
                                   "application/openmetrics-text; "
                                   "version=1.0.0; charset=utf-8")
                    elif self.path.split("?")[0] == "/statusz":
                        self._send(200,
                                   json.dumps(server.statusz(),
                                              default=str).encode(),
                                   "application/json")
                    elif self.path.split("?")[0] == "/fleet/statusz":
                        payload = server.fleet_statusz()
                        if payload is None:
                            self._send(404, b"no fleet attached\n",
                                       "text/plain")
                        else:
                            self._send(200,
                                       json.dumps(payload,
                                                  default=str).encode(),
                                       "application/json")
                    elif self.path.split("?")[0] == "/healthz":
                        code, payload = server.healthz()
                        self._send(code, json.dumps(payload).encode(),
                                   "application/json")
                    elif self.path.split("?")[0] == "/":
                        self._send(200, b"apex_tpu debug server: "
                                   b"/metrics /metrics.prom /statusz "
                                   b"/healthz /fleet/statusz\n",
                                   "text/plain")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # a broken scrape never kills us
                    logger.warning("debug server GET %s failed: %r",
                                   self.path, e)
                    try:
                        self._send(500, repr(e).encode(), "text/plain")
                    except Exception:
                        pass

            def log_message(self, fmt, *args):
                logger.debug("debug server: " + fmt, *args)

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="apex-debug-server",
            daemon=True)
        self._thread.start()
        logger.info("debug server listening on http://%s:%d "
                    "(/metrics, /statusz, /healthz)", self.host, self.port)
        return self

    def url(self, path: str = "/") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "DebugServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
