"""Goodput/badput attribution over a flight-recorder timeline.

The question this module answers is the one TorchTitan-class production
stacks treat as the headline SLO: of a run's wall-clock, how much was
the accelerator doing useful training work (**goodput**) and where did
the rest go (**badput**, itemized)?  The input is the event log of
:mod:`.timeline`; the output is a report in which **every wall-clock
second is attributed to exactly one bucket**:

==============  ==========================================================
bucket          source events
==============  ==========================================================
``compute``     ``step`` intervals (not flagged ``skipped``)
``compile``     ``compile`` intervals
``data_stall``  ``data_stall`` intervals (blocking input wait)
``checkpoint``  ``checkpoint_save`` / ``_save_async_submit`` / ``_verify``
``restore``     ``checkpoint_restore`` intervals
``skipped_step````step`` intervals flagged ``skipped`` (sentinel)
``drain``       ``drain`` intervals (preemption wind-down)
``other``       the remainder: wall − sum(attributed) — init, host
                bookkeeping, anything not instrumented
==============  ==========================================================

Exhaustive and disjoint by construction: the instrumented intervals are
all **main-thread blocking time** measured at non-nested call sites (a
step scope never contains a data stall; the checkpoint manager's
``restore_latest`` wrapper is deliberately NOT an event — its inner
``verify``/``restore`` phases are, so nothing is counted twice).  If a
future instrumentation site breaks that discipline, the report exposes
it as ``overcommit_s > 0`` (attributed time exceeding wall-clock)
instead of silently double-counting — ``scripts/obs_smoke.sh`` asserts
it stays ~0 on a real run.

Serving-side attribution (:func:`serving_goodput_report`) works
per-request from the lifecycle events: ``queue_wait`` (submit → admit),
``active`` (admit → finish: prefill + decode — the useful serving
work), and ``drained`` (submitted but cancelled by a drain — wholly
wasted).  ``goodput_fraction`` is active over total request-seconds.

Cookbook: ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

__all__ = [
    "TRAIN_BUCKETS",
    "classify_event",
    "assemble_report",
    "split_runs",
    "goodput_report",
    "serving_goodput_report",
    "format_report",
]

TRAIN_BUCKETS = ("compute", "compile", "data_stall", "checkpoint",
                 "restore", "skipped_step", "drain", "other")

# kind -> bucket for the unconditional cases; ``step`` branches on the
# ``skipped`` flag in classify_event.
_KIND_BUCKET = {
    "compile": "compile",
    "data_stall": "data_stall",
    "checkpoint_save": "checkpoint",
    "checkpoint_save_async_submit": "checkpoint",
    "checkpoint_verify": "checkpoint",
    "checkpoint_restore": "restore",
    "drain": "drain",
}


def classify_event(event: dict) -> Optional[str]:
    """Bucket of one event, ``None`` for kinds that carry no wall-clock
    attribution (markers, serving lifecycle — those feed
    :func:`serving_goodput_report` instead)."""
    kind = event.get("kind")
    if kind == "step":
        return "skipped_step" if event.get("skipped") else "compute"
    return _KIND_BUCKET.get(kind)


def assemble_report(bucket_s: Dict[str, float], *, wall_s: float) -> dict:
    """Close the books over accumulated per-bucket seconds: fill the
    missing buckets with 0, attribute the remainder to ``other``, and
    derive ``goodput_fraction``.  ``overcommit_s`` > 0 means attributed
    time exceeded wall-clock — an instrumentation nesting bug, surfaced
    rather than hidden (``other`` is clamped at 0)."""
    buckets = {b: round(bucket_s.get(b, 0.0), 6) for b in TRAIN_BUCKETS
               if b != "other"}
    attributed = sum(buckets.values())
    wall_s = float(wall_s)
    buckets["other"] = round(max(0.0, wall_s - attributed), 6)
    return {
        "wall_s": round(wall_s, 6),
        "buckets": buckets,
        "goodput_fraction": (round(buckets["compute"] / wall_s, 6)
                             if wall_s > 0 else None),
        "overcommit_s": round(max(0.0, attributed - wall_s), 6),
    }


def _wall_from_events(events: List[dict]) -> float:
    """Run wall-clock: ``run_end.wall_s`` when the run closed cleanly,
    else the newest event's timestamp (the crash case — the tail of the
    run after the last event is unknowable and not counted)."""
    wall = 0.0
    for ev in events:
        if ev.get("kind") == "run_end" and "wall_s" in ev:
            wall = max(wall, float(ev["wall_s"]))
        elif "t" in ev:
            wall = max(wall, float(ev["t"]))
    return wall


def split_runs(events: Iterable[dict]) -> List[List[dict]]:
    """Segment a spilled timeline into its runs (each ``run_begin``
    starts a new segment).  A spill path reused across process
    restarts — the crash→resume shape — APPENDS runs to one file, and
    each run's ``t`` clock restarts at its own arm time, so events from
    different segments must never be summed together."""
    runs: List[List[dict]] = [[]]
    for ev in events:
        if ev.get("kind") == "run_begin" and runs[-1]:
            runs.append([])
        runs[-1].append(ev)
    return [r for r in runs if r]


def goodput_report(events: Iterable[dict], *,
                   wall_s: Optional[float] = None) -> dict:
    """Offline recompute over a (possibly torn) spilled timeline —
    ``goodput_report(read_jsonl(path))``.  Must agree with the armed
    recorder's incremental :meth:`~apex_tpu.observability.timeline.
    FlightRecorder.report` (pinned by ``tests/test_timeline.py``).

    A file carrying several appended runs (spill path reused across
    restarts) reports the NEWEST run — per-run clocks make a cross-run
    sum meaningless; map :func:`split_runs` to analyze the history."""
    runs = split_runs(events)
    events = runs[-1] if runs else []
    bucket_s: Dict[str, float] = {}
    for ev in events:
        bucket = classify_event(ev)
        if bucket is not None and "dur_s" in ev:
            bucket_s[bucket] = bucket_s.get(bucket, 0.0) + float(ev["dur_s"])
    if wall_s is None:
        wall_s = _wall_from_events(events)
    return assemble_report(bucket_s, wall_s=wall_s)


# --- serving ---------------------------------------------------------------


def serving_goodput_report(events: Iterable[dict]) -> dict:
    """Per-request attribution from the serving lifecycle events.

    For every request id seen: ``queue_wait_s`` (submit → admit),
    ``active_s`` (admit → finish — prefill plus decode, the useful
    work), or ``drained_s`` (submit → cancel/reject, wholly wasted;
    rejected requests are also counted in ``totals["rejected"]``).
    Requests
    still in flight at the end of the log are counted ``open`` and
    excluded from the fraction (their split is not yet known).  A
    terminal request whose ``request_submit`` fell off a wrapped ring
    still counts toward ``finished``/``cancelled`` — it just
    contributes no seconds (the fraction covers fully-observed
    lifecycles only)."""
    reqs: Dict[object, dict] = {}

    def rec(rid):
        return reqs.setdefault(rid, {"submit": None, "admit": None,
                                     "end": None, "state": "open",
                                     "tokens": 0})

    for ev in events:
        kind, rid = ev.get("kind"), ev.get("rid")
        if rid is None:
            continue
        t = float(ev.get("t", 0.0))
        if kind == "request_submit":
            rec(rid)["submit"] = t
        elif kind == "request_admit":
            rec(rid)["admit"] = t
        elif kind == "decode_tick":
            rec(rid)["tokens"] = max(rec(rid)["tokens"],
                                     int(ev.get("tokens", 0)))
        elif kind == "request_finish":
            r = rec(rid)
            r["end"], r["state"] = t, "finished"
            r["tokens"] = max(r["tokens"], int(ev.get("tokens", 0)))
        elif kind == "request_cancel":
            r = rec(rid)
            r["end"], r["state"] = t, "cancelled"
        elif kind == "request_reject":
            r = rec(rid)
            r["end"], r["state"] = t, "rejected"

    per_request = {}
    tot_queue = tot_active = tot_drained = 0.0
    n_finished = n_cancelled = n_rejected = n_open = 0
    for rid, r in reqs.items():
        sub = r["submit"]
        row = {"state": r["state"], "tokens": r["tokens"]}
        # Counts follow the terminal state even when the submit event
        # fell off a wrapped ring (only the time split needs the submit
        # timestamp) — totals must never contradict per-request states.
        if r["state"] == "finished":
            n_finished += 1
            if sub is not None:
                admit = r["admit"] if r["admit"] is not None else sub
                row["queue_wait_s"] = round(admit - sub, 6)
                row["active_s"] = round(r["end"] - admit, 6)
                tot_queue += row["queue_wait_s"]
                tot_active += row["active_s"]
        elif r["state"] == "cancelled":
            n_cancelled += 1
            if sub is not None:
                row["drained_s"] = round(r["end"] - sub, 6)
                tot_drained += row["drained_s"]
        elif r["state"] == "rejected":
            # refused at submit (drain window / overload shed): a typed
            # terminal state that holds ~zero request-seconds — counted,
            # and its sliver of wall lands in the wasted bucket
            n_rejected += 1
            if sub is not None:
                row["drained_s"] = round(r["end"] - sub, 6)
                tot_drained += row["drained_s"]
        else:
            n_open += 1
        per_request[rid] = row

    total = tot_queue + tot_active + tot_drained
    return {
        "requests": per_request,
        "totals": {
            "finished": n_finished, "cancelled": n_cancelled,
            "rejected": n_rejected, "open": n_open,
            "queue_wait_s": round(tot_queue, 6),
            "active_s": round(tot_active, 6),
            "drained_s": round(tot_drained, 6),
        },
        "goodput_fraction": (round(tot_active / total, 6)
                             if total > 0 else None),
    }


def format_report(report: dict) -> str:
    """One human-readable block (what the dryrun/smoke entries print)."""
    lines = [f"goodput: wall {report['wall_s']:.3f}s, "
             f"fraction {report['goodput_fraction']}"]
    wall = report["wall_s"] or 1.0
    for name in TRAIN_BUCKETS:
        sec = report["buckets"].get(name, 0.0)
        if sec:
            lines.append(f"  {name:<13} {sec:10.3f}s  {sec / wall:6.1%}")
    if report.get("overcommit_s"):
        lines.append(f"  OVERCOMMIT    {report['overcommit_s']:.3f}s "
                     "(instrumentation overlap bug)")
    return "\n".join(lines)
