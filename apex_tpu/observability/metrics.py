"""Host-side metrics pipeline: registry, MFU, heartbeat.

The device side (:mod:`.trainstats`) produces numbers; this module owns
what the *host* does with them between steps:

- :class:`MetricRegistry` — rank-aware counters/gauges/histograms.
  Every process may record (recording is cheap, lock-guarded dict math),
  but ``flush`` writes only on the writer rank (process 0 by default) —
  the multi-host discipline of the PR 3 checkpoint manifest: exactly one
  process owns the durable artifact.
- :func:`mfu` / :func:`compiled_flops` — model FLOPs utilization derived
  from ``compiled.cost_analysis()`` (the partitioner's own FLOP count
  for the program that actually ran, not an analytic formula that drifts
  from the model) over the device's peak.
- :class:`HeartbeatMonitor` — records the last-completed-step timestamp
  and, when no beat arrives within ``timeout_s``, flags the hang to
  :class:`apex_tpu.resilience.PreemptionGuard` (duck-typed: anything
  with ``.trigger()``, or a plain callable) so the training loop's
  existing drain-and-checkpoint path runs instead of the job burning its
  window wedged on a dead collective or a hung filesystem
  (``testing/faults.hung_writes`` drives the test).
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
from typing import Any, Callable, Dict, Optional

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "default_registry",
    "is_host_local",
    "HOST_LOCAL_PREFIXES",
    "compiled_flops",
    "peak_flops_for",
    "peak_flops_reason",
    "mfu",
    "mfu_or_reason",
    "HeartbeatMonitor",
]

logger = logging.getLogger(__name__)


def _safe_rank_world():
    """(process_index, process_count) without forcing backend init —
    mirrors ``RankInfoFormatter``'s guard (``apex_tpu/__init__.py``): a
    metrics registry constructed before jax.distributed.initialize must
    not initialize a backend as a side effect."""
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            import jax

            return jax.process_index(), jax.process_count()
    except Exception:  # pragma: no cover - private API moved
        pass
    return 0, 1


class Counter:
    """Monotonic counter (``inc``-only)."""

    def __init__(self, lock: Optional[threading.Lock] = None):
        self._lock = lock if lock is not None else threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins scalar."""

    def __init__(self, lock: Optional[threading.Lock] = None):
        self._lock = lock if lock is not None else threading.Lock()
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming summary (count/total/min/max/last) — enough for span
    timings and rates without holding samples.

    ``keep_samples > 0`` additionally retains the most recent N
    observations in a ring buffer so :meth:`percentile` works — the
    serving runtime's per-request latency percentiles (p50/p99
    time-per-output-token, docs/serving.md) need the distribution, not
    just the moments.  Bounded by construction: an unbounded sample
    list in a weeks-long serving process is a slow leak.
    """

    def __init__(self, lock: Optional[threading.Lock] = None,
                 keep_samples: int = 0):
        self._lock = lock if lock is not None else threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None
        self._samples = (collections.deque(maxlen=keep_samples)
                         if keep_samples > 0 else None)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.last = v
            if self._samples is not None:
                self._samples.append(v)

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    @staticmethod
    def _nearest_rank(ordered, q: float):
        rank = math.ceil(q / 100.0 * len(ordered))   # 1-indexed
        return ordered[max(0, min(len(ordered) - 1, rank - 1))]

    def percentile(self, q: float) -> Optional[float]:
        """q-th percentile (0..100, nearest-rank) over the retained
        window; ``None`` without samples (not constructed with
        ``keep_samples``, or nothing observed yet)."""
        with self._lock:
            if not self._samples:
                return None
            ordered = sorted(self._samples)
        return self._nearest_rank(ordered, q)

    def summary(self) -> dict:
        with self._lock:
            out = {"count": self.count, "total": self.total,
                   "mean": self.mean, "min": self.min, "max": self.max,
                   "last": self.last}
            # one copy+sort for all the percentile keys: the window can
            # be 64k samples and flush holds the lock observe() needs
            ordered = sorted(self._samples) if self._samples else None
        if self._samples is not None:
            out["p50"] = (self._nearest_rank(ordered, 50.0)
                          if ordered else None)
            out["p99"] = (self._nearest_rank(ordered, 99.0)
                          if ordered else None)
        return out


# Catalog prefixes whose values are HOST-LOCAL facts: every process
# measures its own (this host's input stall, this host's span timings,
# this host's serving slots), and rank 0's flush describes only rank 0.
# Everything else in the catalog (``train/*``) is a GLOBAL fact — the
# in-graph stats are reduced over the mesh before they reach any host,
# so rank 0's value IS the job's value and the default rank-0-only
# flush loses nothing.  For the host-local names, opt into
# ``flush(..., all_ranks=True)`` (rank-stamped records) when per-host
# visibility matters — docs/observability.md has the split table.
HOST_LOCAL_PREFIXES = (
    "data/", "span_ms/", "heartbeat/", "serving/", "ckpt/", "loader/",
    "fleet/",
)


def is_host_local(name: str) -> bool:
    """True when a catalog entry is a per-host fact (only the writer
    rank's value survives a default ``flush``) rather than a globally
    reduced one."""
    return name.startswith(HOST_LOCAL_PREFIXES)


class MetricRegistry:
    """Named metric store with rank-aware flushing.

    ``rank``/``world`` default to ``jax.process_index()``/``count`` when
    a backend exists, else ``0``/``1`` — so the registry works in
    host-only unit tests and before distributed init alike.  Thread-safe
    (async checkpoint writers and the heartbeat thread record too).
    """

    def __init__(self, *, rank: Optional[int] = None,
                 world: Optional[int] = None):
        auto_rank, auto_world = _safe_rank_world()
        self.rank = auto_rank if rank is None else rank
        self.world = auto_world if world is None else world
        # RLock, shared with every metric this registry creates: metric
        # mutation is atomic against snapshot(), and snapshot() can call
        # Histogram.summary() (which re-acquires) without deadlocking.
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    @property
    def is_writer(self) -> bool:
        """Exactly one process owns the durable metrics artifact."""
        return self.rank == 0

    def _get(self, store: dict, name: str, factory):
        with self._lock:
            if name not in store:
                store[name] = factory(self._lock)
            return store[name]

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str, *, keep_samples: int = 0) -> Histogram:
        """``keep_samples`` applies only on first creation (an existing
        histogram keeps its window — last-write-wins reconfiguration
        would silently truncate someone else's percentiles)."""
        return self._get(self._histograms, name,
                         lambda lock: Histogram(lock, keep_samples))

    def snapshot(self) -> dict:
        """Flat ``{name: value}`` view (histograms as summary dicts)."""
        typed = self.snapshot_typed()
        out: Dict[str, Any] = dict(typed["counters"])
        out.update(typed["gauges"])
        out.update(typed["histograms"])
        return out

    def snapshot_typed(self) -> dict:
        """Per-kind snapshot ``{"counters": {name: value}, "gauges":
        {...}, "histograms": {name: summary}}`` — for consumers that
        must know a metric's kind (the Prometheus exposition needs
        ``# TYPE`` lines), taken under the registry lock so it is
        consistent against concurrent recording."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: h.summary()
                               for n, h in self._histograms.items()},
            }

    def flush(self, writer, *, step: Optional[int] = None,
              extra: Optional[dict] = None,
              all_ranks: bool = False) -> Optional[dict]:
        """Write one record ``{ts, step, rank, metrics, **extra}`` via
        ``writer.write`` — **only on the writer rank** (other ranks
        return ``None`` without touching storage).  ``writer=None`` is a
        no-op, so callers thread an optional writer without branching.

        ``all_ranks=True`` opts into a per-rank flush: every process
        writes its (rank-stamped) record.  This exists because much of
        the catalog is **host-local** (:func:`is_host_local` —
        ``data/stall_ms``, loader throughput, span timings): under the
        default rank-0 gate, a rank-3 input stall is invisible in the
        durable record.  Point each rank's writer at a rank-qualified
        path (``metrics.rank{k}.jsonl``) — the JSONL append protocol is
        line-atomic but interleaving ranks in one file makes per-rank
        series needlessly order-dependent."""
        if writer is None or not (self.is_writer or all_ranks):
            return None
        record: Dict[str, Any] = {"ts": time.time(), "rank": self.rank}
        if step is not None:
            record["step"] = step
        record["metrics"] = self.snapshot()
        if extra:
            record.update(extra)
        writer.write(record)
        return record


_DEFAULT: Optional[MetricRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricRegistry:
    """Process-wide registry (what :func:`~apex_tpu.observability.spans.
    span` and the checkpoint-manager spans record into by default)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricRegistry()
        return _DEFAULT


# --- MFU -----------------------------------------------------------------

# bf16 peak FLOP/s per chip by device kind (public TPU specs — the same
# table bench.py uses for its MFU rows).
_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5", 197e12),
    ("v4", 275e12),
)


def peak_flops_reason(device):
    """``(peak_bf16_flops, reason)`` for a jax device — exactly one of
    the pair is ``None``.  The reason string names *why* MFU is
    undefined (unknown platform vs missing device) instead of the old
    silent ``None``, so a report can print "MFU: n/a (<reason>)" rather
    than dropping the row (ISSUE 10 satellite)."""
    if device is None:
        return None, "no device given (peak FLOP/s unknown)"
    kind = getattr(device, "device_kind", "").lower()
    platform = getattr(device, "platform", "")
    if platform != "tpu":
        return None, (f"no peak-FLOPs table entry for platform "
                      f"{platform!r} (MFU is defined against a TPU peak)")
    for tag, peak in _PEAK_FLOPS:
        if tag in kind:
            return peak, None
    return 197e12, None  # conservative default (v5e)


def peak_flops_for(device) -> Optional[float]:
    """Peak bf16 FLOP/s of a jax device, ``None`` when unknown (CPU —
    MFU against an undefined peak would be noise, not a metric).  Use
    :func:`peak_flops_reason` when the caller should say *why*."""
    return peak_flops_reason(device)[0]


def compiled_flops(compiled) -> Optional[float]:
    """Total FLOPs of one execution from ``compiled.cost_analysis()``.

    Handles both historical return shapes (a per-device list of dicts on
    jax 0.4.x, a plain dict later); returns ``None`` when the backend
    reports no estimate — callers must treat MFU as optional."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        logger.debug("cost_analysis unavailable: %r", e)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    return float(flops) if flops else None


def mfu_or_reason(flops_per_step: Optional[float], step_time_s: float, *,
                  peak_flops: Optional[float] = None,
                  device=None, n_devices: int = 1):
    """``(mfu, reason)`` — exactly one of the pair is ``None``.

    The reason distinguishes the two silent-``None`` cases the old API
    conflated: the backend reporting no cost-analysis FLOP count
    (:func:`compiled_flops` → ``None``) vs an unknown device peak
    (CPU / no device).  Callers that can only show a number keep using
    :func:`mfu`; callers with a text channel (serving ``/statusz``,
    bench rows, reports) surface the reason."""
    if step_time_s <= 0:
        return None, f"non-positive step time ({step_time_s})"
    if flops_per_step is None:
        return None, ("backend reported no cost-analysis FLOP count "
                      "(compiled_flops() returned None)")
    if peak_flops is None:
        peak_flops, reason = peak_flops_reason(device)
        if peak_flops is None:
            return None, reason
    value = flops_per_step / step_time_s / (peak_flops * max(n_devices, 1))
    return value, None


def mfu(flops_per_step: Optional[float], step_time_s: float, *,
        peak_flops: Optional[float] = None,
        device=None, n_devices: int = 1) -> Optional[float]:
    """Model FLOPs utilization: ``flops / time / (peak * n_devices)``.

    ``flops_per_step`` is the whole-program FLOP count (e.g.
    :func:`compiled_flops` of the jitted step — under SPMD that is the
    global program, hence ``n_devices`` scales the denominator).
    Returns ``None`` when either the FLOP count or the peak is unknown
    (CPU) rather than a made-up number; :func:`mfu_or_reason` says
    which."""
    return mfu_or_reason(flops_per_step, step_time_s,
                         peak_flops=peak_flops, device=device,
                         n_devices=n_devices)[0]


# --- heartbeat -----------------------------------------------------------


class HeartbeatMonitor:
    """Hung-step detector: ``beat(step)`` after every completed step; a
    background thread flags ``hung`` (and fires ``on_hang``) when no
    beat lands within ``timeout_s``.

    ``on_hang`` duck-types :class:`apex_tpu.resilience.PreemptionGuard`
    (``.trigger()`` preferred, else called directly): a hang is handled
    exactly like a preemption notice — the loop's next alive moment
    drains async saves and checkpoints, instead of the job dying wedged
    with hours of unsaved progress.  The flag fires once per hang
    episode (re-armed by the next beat) so a slow-but-alive step cannot
    machine-gun the guard.

    ``check_now()`` runs one poll synchronously — deterministic tests
    (``tests/test_observability.py`` with ``faults.hung_writes``) use it
    instead of racing the thread.
    """

    def __init__(self, *, timeout_s: float, on_hang: Optional[Any] = None,
                 registry: Optional[MetricRegistry] = None,
                 poll_s: Optional[float] = None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self.on_hang = on_hang
        self.registry = registry if registry is not None else \
            default_registry()
        self.poll_s = poll_s if poll_s is not None else \
            max(timeout_s / 4.0, 0.01)
        self.last_step: Optional[int] = None
        self.last_beat_time: Optional[float] = None
        self.hung = False
        self.hang_count = 0
        self._armed = False  # a beat arrived since the last hang flag
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def beat(self, step: int) -> None:
        """Record step completion (call from the training loop, after
        the step's results are materialized)."""
        with self._lock:
            self.last_step = step
            self.last_beat_time = time.monotonic()
            self.hung = False
            self._armed = True
        self.registry.gauge("heartbeat/last_step").set(step)
        self.registry.gauge("heartbeat/last_beat_ts").set(time.time())

    def check_now(self) -> bool:
        """One poll: returns (and latches) the hung verdict."""
        fire: Optional[Callable] = None
        with self._lock:
            if not self._armed or self.last_beat_time is None:
                return self.hung
            if time.monotonic() - self.last_beat_time > self.timeout_s:
                self.hung = True
                self.hang_count += 1
                self._armed = False  # once per episode
                on_hang = self.on_hang
                if on_hang is not None:
                    fire = getattr(on_hang, "trigger", on_hang)
        if fire is not None:
            logger.warning(
                "heartbeat: no step completed in %.1fs (last step %s) — "
                "flagging hang", self.timeout_s, self.last_step)
            self.registry.counter("heartbeat/hangs").inc()
            try:
                fire()
            except Exception as e:  # telemetry never kills training
                logger.warning("heartbeat on_hang raised: %r", e)
        elif self.hung:
            self.registry.counter("heartbeat/hangs").inc()
        return self.hung

    def start(self) -> "HeartbeatMonitor":
        if self._thread is not None:
            return self
        # Arm from "now" so a wedge BEFORE the first completed step —
        # the most common wedge shape (dead collective / compile hang on
        # step 0) — is detected too, not only gaps between beats.
        with self._lock:
            if self.last_beat_time is None:
                self.last_beat_time = time.monotonic()
                self._armed = True
        self._stop.clear()

        def run():
            while not self._stop.wait(self.poll_s):
                self.check_now()

        self._thread = threading.Thread(
            target=run, name="apex-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
