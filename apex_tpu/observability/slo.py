"""SLO policies and multi-window burn-rate alerting over the metric
history (ISSUE 20).

The Google-SRE construction, applied to the fleet's own longitudinal
store: an :class:`SLOPolicy` names a history series (``*`` segments
expand per tenant/priority/adapter), an objective a bucket must stay
under, a compliance window with an error budget (``1 - target``), and a
fast/slow window pair of burn-rate thresholds.  The **burn rate** over
a window is the bad-bucket fraction divided by the budget: burn 1.0
consumes exactly the budget over the compliance window, burn 14 over a
short window is a page.  An alert fires only when BOTH the fast and the
slow window burn over their thresholds (the fast window gives speed,
the slow window kills one-bucket blips), and clears only after the
condition has stayed healthy for ``clear_after_s`` — hysteresis, so a
metric flapping across the objective cannot produce an alert storm.
The math is pure bucket arithmetic on the injected clock, pinned golden
by ``tests/test_slo.py``.

Every transition is a typed timeline event — ``slo_burn_alert`` /
``slo_burn_clear`` with the evidence (both burns, remaining budget)
in-record, plus a low-cadence ``slo_state`` snapshot carrying the full
budget table so ``scripts/slo_report.py`` can reconstruct the alert
timeline and budget state offline from the ordinary fleet spill.  The
kinds close through analyzer rule APX302: consumed by
``observability/trace.py`` (``collect_slo_events``), no allowlist
entries.

Evaluation is deliberately deterministic and cheap: one pass over ring
buckets per armed policy per cadence tick, no wall clock, no threads —
the router calls :meth:`SLOEvaluator.evaluate` from its pump loop and
serves :attr:`SLOEvaluator.last_rows` at ``/fleet/statusz``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.observability import timeline
from apex_tpu.observability.timeseries import MetricHistory

__all__ = ["SLOPolicy", "SLOEvaluator"]


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """One objective over one history series (or a ``*`` family).

    A history bucket is *bad* when its ``field`` aggregate exceeds
    ``objective``; the error budget is ``1 - target`` of buckets over
    the compliance window.  ``fast_burn``/``slow_burn`` are the
    multi-window thresholds (SRE ch. 5 defaults: 14x over the fast
    window AND 6x over the slow one)."""

    name: str
    metric: str                        # e.g. "fleet/ttft_ms:p99"
    objective: float                   # bad when field value > objective
    target: float = 0.999              # good-bucket compliance target
    compliance_window_s: float = 3600.0
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 14.0
    slow_burn: float = 6.0
    clear_after_s: float = 60.0        # sustained recovery before clear
    field: str = "mean"                # bucket aggregate judged

    def __post_init__(self):
        if not self.name or not self.metric:
            raise ValueError("SLOPolicy needs a name and a metric")
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"target must be in (0, 1): {self.target}")
        if not (0.0 < self.fast_window_s <= self.slow_window_s
                <= self.compliance_window_s):
            raise ValueError(
                "windows must satisfy 0 < fast <= slow <= compliance")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError("burn thresholds must be positive")
        if self.clear_after_s < 0:
            raise ValueError("clear_after_s must be >= 0")
        if self.field not in ("mean", "max", "last"):
            raise ValueError(f"unknown field {self.field!r}")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def _bad_fraction(history: MetricHistory, series: str, policy: SLOPolicy,
                  window_s: float, now: float) -> float:
    """Bad-bucket fraction over the trailing window (0.0 with no data:
    an idle fleet burns nothing)."""
    return history.bad_fraction(series, window_s, policy.objective,
                                now=now, field=policy.field)


class SLOEvaluator:
    """Burn-rate evaluation + hysteresis alert state over one history."""

    def __init__(self, history: MetricHistory,
                 policies: Sequence[SLOPolicy], *,
                 clock=None, state_every_s: float = 1.0):
        self.history = history
        self.policies: Tuple[SLOPolicy, ...] = tuple(policies)
        self._clock = clock if clock is not None else history._clock
        self.state_every_s = float(state_every_s)
        # (policy.name, series) -> {"alerting", "since", "recover_t"}
        self._state: Dict[Tuple[str, str], dict] = {}
        self._last_state_emit: Optional[float] = None
        self.alerts = 0
        self.clears = 0
        self.last_rows: List[dict] = []

    def _row(self, policy: SLOPolicy, series: str, now: float) -> dict:
        burn_fast = _bad_fraction(self.history, series, policy,
                                  policy.fast_window_s, now) / policy.budget
        burn_slow = _bad_fraction(self.history, series, policy,
                                  policy.slow_window_s, now) / policy.budget
        consumed = _bad_fraction(self.history, series, policy,
                                 policy.compliance_window_s, now) \
            / policy.budget
        remaining = 1.0 - consumed
        if burn_slow > 0 and remaining > 0:
            exhaustion_s = remaining * policy.compliance_window_s / burn_slow
        elif remaining <= 0:
            exhaustion_s = 0.0
        else:
            exhaustion_s = None
        return {"policy": policy.name, "metric": series,
                "objective": policy.objective,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "budget_remaining": round(remaining, 6),
                "exhaustion_s": (None if exhaustion_s is None
                                 else round(exhaustion_s, 3)),
                "alerting": False}

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One cadence tick: recompute every (policy, series) row, walk
        the hysteresis state machine, emit transition events."""
        t = self._clock() if now is None else float(now)
        rows: List[dict] = []
        live_keys = set()
        for policy in self.policies:
            matched = self.history.match(policy.metric)
            if not matched and "*" not in policy.metric:
                matched = [policy.metric]   # explicit series: report idle
            for series in matched:
                key = (policy.name, series)
                live_keys.add(key)
                row = self._row(policy, series, t)
                state = self._state.get(key)
                if state is None:
                    state = self._state[key] = {
                        "alerting": False, "since": None, "recover_t": None}
                firing = (row["burn_fast"] >= policy.fast_burn
                          and row["burn_slow"] >= policy.slow_burn)
                if not state["alerting"]:
                    if firing:
                        state["alerting"] = True
                        state["since"] = t
                        state["recover_t"] = None
                        self.alerts += 1
                        timeline.emit(
                            "slo_burn_alert", policy=policy.name,
                            metric=series, burn_fast=row["burn_fast"],
                            burn_slow=row["burn_slow"],
                            budget_remaining=row["budget_remaining"],
                            objective=policy.objective)
                else:
                    if firing:
                        state["recover_t"] = None   # relapse resets
                    else:
                        if state["recover_t"] is None:
                            state["recover_t"] = t
                        if t - state["recover_t"] >= policy.clear_after_s:
                            state["alerting"] = False
                            state["since"] = None
                            state["recover_t"] = None
                            self.clears += 1
                            timeline.emit(
                                "slo_burn_clear", policy=policy.name,
                                metric=series,
                                burn_fast=row["burn_fast"],
                                burn_slow=row["burn_slow"],
                                budget_remaining=row["budget_remaining"])
                row["alerting"] = state["alerting"]
                rows.append(row)
        # a series cap-evicted upstream keeps no ghost alert state
        for key in [k for k in self._state if k not in live_keys]:
            del self._state[key]
        self.last_rows = rows
        if timeline.active() is not None and rows and (
                self._last_state_emit is None
                or t - self._last_state_emit >= self.state_every_s):
            self._last_state_emit = t
            timeline.emit("slo_state", rows=rows)
        return rows

    def worst(self) -> Optional[dict]:
        """The worst-burning row of the last evaluation (slow-window
        burn is the ranking: it is the one that exhausts budgets)."""
        if not self.last_rows:
            return None
        return max(self.last_rows, key=lambda r: r["burn_slow"])

    def introspect(self) -> dict:
        return {"policies": len(self.policies),
                "series_tracked": len(self._state),
                "alerts": self.alerts, "clears": self.clears,
                "alerting": sorted(
                    f"{p}:{m}" for (p, m), s in self._state.items()
                    if s["alerting"])}
