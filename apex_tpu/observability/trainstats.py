"""In-graph training telemetry — the :class:`TrainStats` pytree.

Production trainers treat per-step metrics as part of the *program*, not a
bolt-on (TorchTitan logs loss/grad-norm/MFU from inside the step,
arxiv 2410.06511 §3; veScale validates its overlap schedules against the
same counters, arxiv 2509.07003).  The contract here is strict, because a
metrics layer that slows the step it measures is worse than none:

- **zero extra host syncs** — every field is a jnp value computed inside
  the jitted step; nothing is fetched until a host-side logger decides to
  (:class:`TrainStatsLogger`, ``every_n`` steps), so steady-state steps
  dispatch fully async;
- **at most the collectives already on the path** — stats that need
  cross-rank agreement ride an all-reduce the trainer already performs
  (the loss reduction), *widened* by a few elements rather than added
  (:func:`pack_local_stats` / :func:`stats_from_reduced`); stats on
  replicated values (params, global grads) are local arithmetic.
  ``tests/test_observability.py`` pins this with an HLO collective-count
  compare (instrumented == bare) via :mod:`apex_tpu.analysis.hlo`;
- **bit-identical training** — the instrumented step's params/optimizer
  state match the uninstrumented step's bit for bit (observation never
  feeds back; auxiliary outputs are ``stop_gradient``-cut so the
  backward program is unchanged).

Threaded through
:func:`apex_tpu.parallel.distributed.zero_data_parallel_train_step`,
``build_gpt_3d``'s ``make_train_step`` (``collect_stats=True``), and the
driver dryrun entry; the metric catalog lives in ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.utils.tree import tree_l2_norm

__all__ = [
    "TrainStats",
    "PartialTrainStats",
    "train_stats",
    "partial_train_stats",
    "device_partial_norms",
    "local_grad_stats",
    "pack_local_stats",
    "stats_from_reduced",
    "stats_partition_specs",
    "TrainStatsLogger",
]


class TrainStats(NamedTuple):
    """Per-step telemetry, jit-carried (all jnp values, no host sync).

    ``loss``             — unscaled mean training loss (fp32).
    ``grad_norm``        — global L2 norm of the (unscaled) gradients.
                           On the ZeRO shard_map path this is the norm of
                           the *stacked per-replica local* grads (exactly
                           what rode the wire), not of their mean — see
                           docs/observability.md for the distinction.
    ``param_norm``       — global L2 norm of the parameters (pre-update).
    ``nonfinite_leaves`` — int32 count of gradient leaves containing any
                           NaN/Inf this step (0 on a healthy step; the
                           per-leaf refinement of ``amp.all_finite``).
    ``loss_scale``       — the loss scale the step ran under (1.0 when no
                           scaler is armed).
    ``skipped_steps``    — cumulative skipped updates from
                           ``resilience.SentinelState`` (0 when no
                           sentinel is armed).
    ``moe_aux``          — per-microbatch MoE auxiliary loss ``[m]``
                           (``None`` for dense models / trainers without
                           microbatch structure).
    """

    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    param_norm: jnp.ndarray
    nonfinite_leaves: jnp.ndarray
    loss_scale: jnp.ndarray
    skipped_steps: jnp.ndarray
    moe_aux: Optional[jnp.ndarray] = None


def stats_partition_specs(*, moe_aux: bool = False) -> TrainStats:
    """Replicated ``PartitionSpec`` tree matching a :class:`TrainStats`
    output crossing a ``shard_map`` boundary (``None`` for an absent
    ``moe_aux`` keeps the pytree structures aligned)."""
    return TrainStats(
        loss=P(), grad_norm=P(), param_norm=P(), nonfinite_leaves=P(),
        loss_scale=P(), skipped_steps=P(),
        moe_aux=P() if moe_aux else None,
    )


def local_grad_stats(grads):
    """``(sumsq, nonfinite_leaves)`` of a gradient tree — pure local
    arithmetic (fp32 sum of squares; int32 count of floating leaves with
    any non-finite element).  No collective, no host sync."""
    leaves = [
        jnp.asarray(x) for x in jax.tree_util.tree_leaves(grads)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.float32(0), jnp.int32(0)
    sumsq = jnp.sum(jnp.stack(
        [jnp.sum(jnp.square(jnp.asarray(x, jnp.float32))) for x in leaves]))
    bad = jnp.sum(jnp.stack(
        [jnp.any(~jnp.isfinite(x)) for x in leaves]).astype(jnp.int32))
    return sumsq, bad


def _f32(x, default):
    return jnp.float32(default) if x is None else jnp.asarray(x, jnp.float32)


def train_stats(
    loss,
    grads,
    params,
    *,
    grad_scale=None,
    loss_scale=None,
    skipped_steps=None,
    moe_aux=None,
) -> TrainStats:
    """Stats for **unsharded/replicated** global arrays (single-device
    trainers, host-side tests): everything is local arithmetic, so
    instrumentation adds zero collectives by construction.  For a
    trainer whose params are SHARDED global arrays (``build_gpt_3d``),
    plain arithmetic here would make the SPMD partitioner insert one
    all-reduce per leaf — use :func:`device_partial_norms` +
    :func:`partial_train_stats` instead.

    ``grad_scale`` — the scale the loss (hence grads) was multiplied by;
    the reported ``grad_norm`` is divided back so it is always unscaled.
    ``moe_aux`` is recorded via ``stop_gradient`` upstream (observational
    only — the backward program must not change).
    """
    sumsq, bad = local_grad_stats(grads)
    inv = 1.0 if grad_scale is None else 1.0 / _f32(grad_scale, 1.0)
    return TrainStats(
        loss=_f32(loss, 0.0),
        grad_norm=jnp.sqrt(sumsq) * inv,
        param_norm=tree_l2_norm(params),
        nonfinite_leaves=bad,
        loss_scale=_f32(loss_scale, 1.0),
        skipped_steps=(jnp.int32(0) if skipped_steps is None
                       else jnp.asarray(skipped_steps, jnp.int32)),
        moe_aux=moe_aux,
    )


# --- shard_map path: ride the existing loss all-reduce -------------------

# Element layout of the packed stats vector (one widened collective):
_PACK_LOSS, _PACK_SUMSQ, _PACK_BAD, PACK_LEN = 0, 1, 2, 3


def pack_local_stats(loss, grads) -> jnp.ndarray:
    """``[loss, grad_sumsq, nonfinite_leaves]`` as one ``(3,)`` fp32
    vector, to be **sum**-reduced over the data axes *in place of* the
    trainer's existing scalar loss reduction — the collective count stays
    exactly what the bare step had; only its payload widens by two
    elements.  Pass the loss pre-divided by any loss scale so element 0
    reduces to the same value (bitwise) the bare path's ``pmean``
    produced."""
    return jnp.stack([
        jnp.asarray(loss, jnp.float32).reshape(()),
        *local_grad_stats(grads),
    ]).astype(jnp.float32)


def stats_from_reduced(
    reduced: jnp.ndarray,
    world: int,
    params,
    *,
    grad_scale=None,
    loss_scale=None,
    skipped_steps=None,
    moe_aux=None,
):
    """Unpack the sum-reduced stats vector into ``(mean_loss,
    TrainStats)``.  ``world`` is the static replica count of the
    reduction axes, so ``reduced[0] / world`` reproduces ``pmean`` of the
    loss exactly (``lax.pmean`` is ``psum`` followed by the same static
    division).  ``grad_norm`` here is the L2 norm over the *stacked*
    per-replica local grads (``sqrt`` of the summed local sum-of-squares)
    — the honest quantity available without adding a second, full-width
    gradient collective; ``nonfinite_leaves`` sums every replica's count.
    ``param_norm`` stays local arithmetic (params are replicated)."""
    loss = reduced[_PACK_LOSS] / world
    inv = 1.0 if grad_scale is None else 1.0 / _f32(grad_scale, 1.0)
    stats = TrainStats(
        loss=loss,
        grad_norm=jnp.sqrt(reduced[_PACK_SUMSQ]) * inv,
        param_norm=tree_l2_norm(params),
        nonfinite_leaves=jnp.round(reduced[_PACK_BAD]).astype(jnp.int32),
        loss_scale=_f32(loss_scale, 1.0),
        skipped_steps=(jnp.int32(0) if skipped_steps is None
                       else jnp.asarray(skipped_steps, jnp.int32)),
        moe_aux=moe_aux,
    )
    return loss, stats


# --- sharded global-array path: per-device partials, host finalize -------


class PartialTrainStats(NamedTuple):
    """Device-partial form of :class:`TrainStats`, for trainers whose
    params/grads are SHARDED global arrays (``build_gpt_3d``).

    A global norm over a tp/pp-sharded tree cannot be computed in-graph
    without cross-shard reductions: written as plain arithmetic the SPMD
    partitioner inserts one all-reduce per leaf (dozens of collectives
    the bare step never performs).  So the step instead emits
    ``norm_partials`` — a tiny ``[n_devices, 2 + n_leaves]`` matrix of
    per-device partial sums produced by a ``shard_map`` whose outputs
    keep the device axis (:func:`device_partial_norms`, ZERO collectives
    by construction) — and the final reduction over that matrix happens
    on the **host**, at fetch time, where it is free.

    :class:`TrainStatsLogger` finalizes transparently; after a manual
    ``jax.device_get`` call :meth:`finalize` to get scalar
    :class:`TrainStats`.
    """

    loss: jnp.ndarray
    norm_partials: jnp.ndarray  # [D, 2+L] — see device_partial_norms
    grad_scale: jnp.ndarray
    loss_scale: jnp.ndarray
    skipped_steps: jnp.ndarray
    moe_aux: Optional[jnp.ndarray] = None

    def finalize(self) -> TrainStats:
        """Host-side reduction of the partials matrix (numpy — call on
        fetched values, not inside jit)."""
        import numpy as np

        parts = np.asarray(self.norm_partials, np.float32)
        g_sumsq = parts[:, 0].sum()
        p_sumsq = parts[:, 1].sum()
        # A leaf is non-finite if ANY device's shard of it was.
        leaf_bad = parts[:, 2:].max(axis=0) > 0.5
        inv = 1.0 / float(np.float32(self.grad_scale))
        return TrainStats(
            loss=np.float32(self.loss),
            grad_norm=np.float32(np.sqrt(g_sumsq) * inv),
            param_norm=np.float32(np.sqrt(p_sumsq)),
            nonfinite_leaves=np.int32(leaf_bad.sum()),
            loss_scale=np.float32(self.loss_scale),
            skipped_steps=np.int32(self.skipped_steps),
            moe_aux=self.moe_aux,
        )


def device_partial_norms(mesh, param_specs):
    """Build ``fn(grads, params) -> [n_devices, 2 + n_leaves]`` — the
    per-device norm partials feeding :class:`PartialTrainStats`.

    Runs a dedicated ``shard_map`` over the FULL mesh whose output keeps
    the device axis, so the compiled program contains zero collectives
    (pinned by the instrumented-vs-bare HLO compare in
    ``tests/test_observability.py``).  Columns:

    - 0 — this device's gradient sum-of-squares, weighted by
      1/replication (a leaf replicated over mesh axes its spec does not
      mention would otherwise be counted once per replica), so the
      column's SUM over devices is the exact global sum of squares;
    - 1 — the same for the params;
    - ``2+k`` — 1.0 iff any element of this device's shard of gradient
      leaf ``k`` is non-finite (the host ORs the column across devices,
      then counts flagged leaves).
    """
    from apex_tpu.parallel import collectives as cc

    axis_names = tuple(mesh.axis_names)
    n_devices = 1
    for a in axis_names:
        n_devices *= mesh.shape[a]
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    weights = []
    for spec in spec_leaves:
        sharded = 1
        for entry in tuple(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            for a in axes:
                sharded *= mesh.shape[a]
        weights.append(sharded / n_devices)

    def local(grads, params):
        g_leaves = jax.tree_util.tree_leaves(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        if len(g_leaves) != len(weights) or len(p_leaves) != len(weights):
            raise ValueError(
                f"param_specs leaves ({len(weights)}) do not match "
                f"grads ({len(g_leaves)}) / params ({len(p_leaves)})")

        def wsumsq(leaves):
            return jnp.sum(jnp.stack([
                w * jnp.sum(jnp.square(jnp.asarray(x, jnp.float32)))
                for w, x in zip(weights, leaves)]))

        flags = jnp.stack([
            jnp.any(~jnp.isfinite(jnp.asarray(x, jnp.float32)))
            for x in g_leaves]).astype(jnp.float32)
        vec = jnp.concatenate(
            [jnp.stack([wsumsq(g_leaves), wsumsq(p_leaves)]), flags])
        return vec[None, :]

    return cc.shard_over(
        local, mesh=mesh, in_specs=(param_specs, param_specs),
        out_specs=P(axis_names))


def partial_train_stats(
    loss,
    norm_partials,
    *,
    grad_scale=None,
    loss_scale=None,
    skipped_steps=None,
    moe_aux=None,
) -> PartialTrainStats:
    """Assemble a :class:`PartialTrainStats` (defaults mirror
    :func:`train_stats`; ``grad_scale`` divides the reported grad norm
    back to unscaled at finalize time)."""
    return PartialTrainStats(
        loss=_f32(loss, 0.0),
        norm_partials=norm_partials,
        grad_scale=_f32(grad_scale, 1.0),
        loss_scale=_f32(loss_scale, 1.0),
        skipped_steps=(jnp.int32(0) if skipped_steps is None
                       else jnp.asarray(skipped_steps, jnp.int32)),
        moe_aux=moe_aux,
    )


# --- host side: the log_every_n fetch ------------------------------------


class TrainStatsLogger:
    """The only place device stats meet the host — on a schedule.

    ``maybe_log(step, stats)`` is a no-op (not even a device poll) except
    every ``every_n``-th step, when the :class:`TrainStats` is fetched
    (ONE blocking transfer of a handful of scalars), written into the
    registry's gauges, and flushed to ``writer`` (a
    :class:`apex_tpu.observability.JsonlWriter`) — so the steady-state
    step stays fully async while the logged step pays one small sync.
    Returns the fetched ``dict`` when it logged, else ``None``.
    """

    def __init__(self, registry=None, *, every_n: int = 50, writer=None,
                 prefix: str = "train"):
        if every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if registry is None:
            from apex_tpu.observability.metrics import default_registry

            registry = default_registry()
        self.registry = registry
        self.every_n = every_n
        self.writer = writer
        self.prefix = prefix

    def fetch(self, stats) -> dict:
        """Blocking device→host fetch of one stats pytree
        (:class:`TrainStats` or :class:`PartialTrainStats` — partials
        are finalized here), flattened to plain floats/ints
        (``moe_aux`` becomes a list)."""
        import numpy as np

        host = jax.device_get(stats)
        if hasattr(host, "finalize"):
            host = host.finalize()
        out = {}
        for name, val in zip(TrainStats._fields, host):
            if val is None:
                continue
            # Everything is on the host already — plain numpy, no
            # round-trip back through a device array.
            arr = np.asarray(val)
            if arr.ndim == 0:
                out[name] = (int(arr) if np.issubdtype(arr.dtype, np.integer)
                             else float(arr))
            else:
                out[name] = [float(v) for v in arr.tolist()]
        return out

    def maybe_log(self, step: int, stats: TrainStats,
                  extra: Optional[dict] = None):
        if step % self.every_n:
            return None
        return self.log(step, stats, extra=extra)

    def log(self, step: int, stats: TrainStats,
            extra: Optional[dict] = None) -> dict:
        """Unconditional fetch + record (the ``every_n`` hit path)."""
        values = self.fetch(stats)
        for name, val in values.items():
            if isinstance(val, list):  # per-microbatch vector: log the mean
                if val:
                    self.registry.gauge(
                        f"{self.prefix}/{name}_mean").set(
                            sum(val) / len(val))
                continue
            self.registry.gauge(f"{self.prefix}/{name}").set(val)
        self.registry.counter(f"{self.prefix}/logged_steps").inc()
        record = dict(values)
        if extra:
            record.update(extra)
        self.registry.flush(self.writer, step=step, extra=record)
        return values
