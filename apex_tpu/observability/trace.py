"""Distributed tracing over the fleet — stitch N spills into one story.

The run-timeline layer (ISSUE 10) answers "where did this *process's*
wall-clock go"; the fleet (ISSUEs 11-14) made a single request traverse
router queue → wire → replica queue → admission → chunked prefill →
decode ticks, possibly detouring through preemption-recompute or a
kill-mid-decode failover onto a *different replica* — and no
process-local view can answer "where did this request's 900 ms go".
This module is the merge: given the router's spill and every replica's
spill (each written by its own :class:`~apex_tpu.observability.
timeline.FlightRecorder`, process identity in the ``run_begin`` meta),
it reconstructs one span tree per ``trace_id`` and attributes **every
wall-clock millisecond of the request to exactly one hop bucket**:

==================  =====================================================
hop bucket          interval
==================  =====================================================
``router_queue``    ``fleet_submit`` → ``fleet_dispatch`` (router pool)
``wire``            dispatch → replica ``request_submit`` (transport +
                    command queue), and replica ``request_finish`` →
                    router ``fleet_finish`` (the return leg)
``replica_queue``   ``request_submit`` → ``request_admit`` (the engine's
                    waiting deque — no free slot / first-chunk blocks)
``admission_wait``  ``request_admit`` → the request's first prefill
                    chunk actually starting (admitted but the packed
                    prefill hasn't picked it up yet)
``prefill``         first own chunk start → ``request_prefilled``
                    (includes inter-chunk waits while other slots run)
``decode``          ``request_prefilled`` → ``request_finish``
``preempted``       ``request_preempt`` → re-``request_admit``
                    (recompute-on-readmit, PR 11)
``failover_replay`` the dead replica's last flushed event →
                    the re-``fleet_dispatch`` (detection + probe ladder
                    + router requeue — the failover *cost*)
``kv_migrate``      ``fleet_migrate_start`` → the dispatch onto the
                    decode replica (ISSUE 16: export + per-block relay
                    + commit — the disaggregation handoff cost,
                    attributed, never guessed)
==================  =====================================================

Exhaustive and disjoint **by construction**: the attribution is a
single monotone walk over the request's merged milestones, so the hop
sum equals the trace's wall-clock exactly — the PR 9 goodput discipline
(``overcommit_s``) applied per-request, fleet-wide.  What *can* go
wrong cross-process is the clock: mapped timestamps from different
hosts can disagree by up to the link RTT, so the walk clamps any
backwards step and reports the total as ``clock_clamped_s`` instead of
silently reordering (a large value means the offset samples are stale
or the link asymmetric, not that time ran backwards).

Clock alignment (the PR 13 rule: cross-host clocks are never compared
raw): the socket transport's ping/pong and hello exchanges carry the
replica host's monotonic stamp; :func:`estimate_offset` is the NTP
midpoint construction — the remote stamped its clock somewhere inside
the client's ``[t_send, t_recv]`` window, so ``offset = midpoint −
remote`` errs by at most RTT/2.  The router mirrors each sample into
its spill as a ``link_clock`` event (refreshed per ping), and the
merger maps every replica event onto the **router host's** monotonic
clock via the sample nearest on the replica's own clock — so a stepped
or restarted replica clock uses the samples of its own era.  Links
with no samples (the in-process ``ReplicaProcess`` transport — same
host, one ``CLOCK_MONOTONIC``) map with offset 0.

CLI: ``scripts/trace_report.py <spill-dir>``.  End-to-end gate:
``scripts/trace_smoke.sh`` (3-replica loopback fleet, tracing armed,
one SIGKILL — every request's hop sum must match the router-side
stopwatch within 2%).  Cookbook: docs/observability.md.
"""

from __future__ import annotations

import bisect
import glob
import itertools
import os
import re
from typing import Dict, List, Optional, Tuple

from apex_tpu.observability.goodput import split_runs
from apex_tpu.observability.timeline import FlightRecorder
from apex_tpu.observability.writers import read_jsonl

__all__ = [
    "TRACE_HOP_BUCKETS",
    "arm_process",
    "estimate_offset",
    "map_time",
    "read_fleet_spills",
    "stitch_traces",
    "summarize_traces",
    "collect_decisions",
    "collect_slo_events",
    "merge_dir",
    "format_trace_report",
    "TRACE_UNATTRIBUTED_KINDS",
]

TRACE_HOP_BUCKETS = (
    "router_queue", "wire", "replica_queue", "admission_wait",
    "prefill", "decode", "preempted", "failover_replay", "kv_migrate",
)

# Milestone kinds and their state transitions (the walk below).  Rank
# breaks exact-time ties in logical order — at equal mapped timestamps
# a dispatch must precede the replica-side submit it caused, and a
# replica finish must precede the router observing it.
_KIND_RANK = {
    "fleet_submit": 0, "fleet_dispatch": 1, "request_submit": 2,
    "request_admit": 3, "prefill_chunk_start": 4,
    "prefill_chunk_end": 5, "decode_tick": 6, "request_prefilled": 6,
    "request_preempt": 7, "request_cancel": 7, "request_reject": 7,
    "fleet_migrate_start": 7,
    "fleet_replay": 8, "request_finish": 9, "fleet_finish": 10,
    "fleet_reject": 10,
}
_ROUTER_KINDS = ("fleet_submit", "fleet_dispatch", "fleet_replay",
                 "fleet_migrate_start", "fleet_finish", "fleet_reject")
_REPLICA_KINDS = ("request_submit", "request_admit",
                  "request_prefilled", "decode_tick", "request_preempt",
                  "request_cancel", "request_reject", "request_finish")

# Marker kinds deliberately outside every attribution bucket, each with
# the reason it is a point event, not an interval.  The event-schema
# lint (APX302, apex_tpu.analysis.control_plane) holds every other
# emitted kind to a consumer in this module or goodput.py, and fails
# when an entry here goes stale (nothing emits it anymore).
TRACE_UNATTRIBUTED_KINDS = {
    "preemption": "guard-trip marker; the drain cost it starts is "
                  "attributed by the 'drain' scope / 'preempted' hop",
    "sentinel_skip": "forensic marker; goodput charges skipped time via "
                     "the 'step' event's skipped flag, not this point",
    "request_export": "KV-handoff forensics on the prefill side; the "
                      "migration interval is the 'kv_migrate' hop "
                      "(fleet_migrate_start -> commit dispatch)",
    "adapter_load": "registration forensics; load latency is router-"
                    "side (fleet/adapter_loads + ack pump), not a "
                    "request interval",
    "adapter_unload": "registration forensics, same as adapter_load",
}


# --------------------------------------------------------------- arming


def arm_process(timeline_dir: str, role: str, name: str) -> FlightRecorder:
    """Arm this process's flight recorder for fleet tracing: the spill
    lands at ``<dir>/timeline.<role>.<name>.<pid>.jsonl`` and the
    ``run_begin`` meta carries the same identity, which is what
    :func:`read_fleet_spills` classifies on.  One directory per fleet
    run; every process (the router and each replica) arms its own."""
    from apex_tpu.observability import timeline as tl

    os.makedirs(timeline_dir, exist_ok=True)
    pid = os.getpid()
    rec = FlightRecorder(
        os.path.join(timeline_dir,
                     f"timeline.{role}.{name}.{pid}.jsonl"),
        meta={"role": role, "name": name, "pid": pid})
    return tl.arm(rec)


# --------------------------------------------------------- clock algebra


def estimate_offset(t_send: float, t_recv: float,
                    remote_mono: float) -> Tuple[float, float]:
    """One round trip's clock-offset estimate: ``(offset_s, err_s)``
    with ``local ≈ remote + offset``.

    The NTP midpoint construction: the remote stamped ``remote_mono``
    somewhere inside the local ``[t_send, t_recv]`` window, so mapping
    it to the midpoint errs by at most half the round trip —
    ``err_s = (t_recv - t_send) / 2`` is the hard bound the
    injected-clock tests pin, however skewed or stepped the remote
    clock is."""
    if t_recv < t_send:
        raise ValueError(
            f"t_recv ({t_recv}) precedes t_send ({t_send})")
    offset = (t_send + t_recv) / 2.0 - remote_mono
    return offset, (t_recv - t_send) / 2.0


def map_time(raw_mono: float,
             samples: List[Tuple[float, float]]) -> float:
    """Map a remote monotonic stamp onto the local (router) clock using
    the offset sample **nearest on the remote's own clock** —
    ``samples`` is a sorted list of ``(remote_mono, offset_s)``.  A
    remote clock that stepped (process restart, a different boot epoch)
    gets the samples of its own era; no samples means the identity map
    (the same-host transports share one CLOCK_MONOTONIC)."""
    if not samples:
        return raw_mono
    i = bisect.bisect_left(samples, (raw_mono, float("-inf")))
    best = None
    for j in (i - 1, i):
        if 0 <= j < len(samples):
            if best is None or (abs(samples[j][0] - raw_mono)
                                < abs(samples[best][0] - raw_mono)):
                best = j
    return raw_mono + samples[best][1]


# ------------------------------------------------------------- spill IO


def _run_meta(run: List[dict]) -> dict:
    head = run[0] if run and run[0].get("kind") == "run_begin" else {}
    return head


_ROTATED_RE = re.compile(r"\.rot-(\d+)\.jsonl$")


def _spill_groups(timeline_dir: str) -> List[List[str]]:
    """Group a spill directory's files into logical streams: a
    ``JsonlWriter(rotate_bytes=...)`` leaves ``<stem>.rot-NNNNNN.jsonl``
    segments beside the live ``<stem>.jsonl`` (ISSUE 20); each group is
    its segments in rotation order with the live file last, so
    concatenating a group replays the stream's append order exactly."""
    groups: Dict[str, List[Tuple[int, str]]] = {}
    for path in sorted(glob.glob(
            os.path.join(timeline_dir, "timeline*.jsonl"))):
        m = _ROTATED_RE.search(path)
        if m:
            base = path[:m.start()] + ".jsonl"
            seq = int(m.group(1))
        else:
            base, seq = path, 1 << 62
        groups.setdefault(base, []).append((seq, path))
    return [[p for _seq, p in sorted(groups[base])]
            for base in sorted(groups)]


def read_fleet_spills(timeline_dir: str, *, strict: bool = True):
    """Discover and load a fleet run's spills: ``(router_run,
    replica_runs)`` where ``replica_runs`` maps replica name → list of
    runs (a rolled replica leaves one spill per incarnation, each its
    own pid).  Rotated segments of one stream are concatenated back in
    order first; then the newest run per stream (`split_runs` — a
    reused spill path appends).  Files whose ``run_begin`` carries no
    fleet role are ignored (a plain PR 9 timeline can share the
    directory)."""
    router_run: Optional[List[dict]] = None
    replica_runs: Dict[str, List[List[dict]]] = {}
    for group in _spill_groups(timeline_dir):
        events: List[dict] = []
        for path in group:
            events.extend(read_jsonl(path, strict=strict))
        runs = split_runs(events)
        if not runs:
            continue
        run = runs[-1]
        meta = _run_meta(run)
        role = meta.get("role")
        if role == "router":
            if router_run is not None:
                raise ValueError(
                    f"{timeline_dir}: more than one router spill "
                    "(one merge covers one router's fleet)")
            router_run = run
        elif role == "replica":
            replica_runs.setdefault(str(meta.get("name")), []).append(run)
    if router_run is None:
        raise ValueError(
            f"{timeline_dir}: no router spill found (arm the router "
            "process with trace.arm_process(dir, 'router', <name>))")
    return router_run, replica_runs


# ------------------------------------------------------------ stitching


def _link_samples(router_run: List[dict]) -> Dict[str, list]:
    """Per-replica sorted ``(remote_mono, offset_s)`` samples from the
    router spill's ``link_clock`` events."""
    samples: Dict[str, list] = {}
    for ev in router_run:
        if ev.get("kind") == "link_clock":
            samples.setdefault(str(ev.get("replica")), []).append(
                (float(ev["remote_mono"]), float(ev["offset_s"])))
    for lst in samples.values():
        lst.sort()
    return samples


def stitch_traces(router_run: List[dict],
                  replica_runs: Dict[str, List[List[dict]]]) -> dict:
    """Merge one router run + N replica runs into per-request traces:
    ``{trace_id: record}`` where every record's ``hops`` partition its
    wall-clock exactly (see the module docstring for the walk)."""
    router_t0 = float(_run_meta(router_run).get("mono_t0", 0.0))
    samples = _link_samples(router_run)
    seq = itertools.count()
    milestones: Dict[str, list] = {}
    meta_by_trace: Dict[str, dict] = {}

    def add(tid: str, t: float, kind: str, process: str, ev: dict):
        milestones.setdefault(tid, []).append(
            (t, _KIND_RANK.get(kind, 6), next(seq), kind, process, ev))

    for ev in router_run:
        tid = ev.get("trace_id")
        kind = ev.get("kind")
        if tid is None or kind not in _ROUTER_KINDS:
            continue
        if kind == "fleet_submit":
            meta_by_trace[tid] = {
                "rid": ev.get("rid"), "tenant": ev.get("tenant"),
                "priority": ev.get("priority"),
                "prompt_tokens": ev.get("prompt_tokens"),
                "max_new_tokens": ev.get("max_new_tokens"),
            }
        add(tid, float(ev["t"]), kind, "router", ev)

    for name, runs in replica_runs.items():
        link = samples.get(name, [])
        for run in runs:
            t0 = float(_run_meta(run).get("mono_t0", 0.0))
            rid_to_trace: Dict[object, str] = {}

            def mapped(t: float) -> float:
                return map_time(t0 + float(t), link) - router_t0

            for ev in run:
                kind = ev.get("kind")
                tid = ev.get("trace_id")
                if tid is not None and "rid" in ev:
                    rid_to_trace[ev["rid"]] = tid
                if tid is not None and kind in _REPLICA_KINDS:
                    add(tid, mapped(ev["t"]), kind, name, ev)
                elif kind == "prefill":
                    # the packed prefill scope covers several slots; a
                    # traced request's FIRST own chunk start is its
                    # admission_wait → prefill boundary (rid → trace
                    # resolved through the process-local submit events)
                    t_end = mapped(ev["t"])
                    t_start = t_end - float(ev.get("dur_s", 0.0))
                    for rid in ev.get("rids", ()):
                        rtid = rid_to_trace.get(rid)
                        if rtid is not None:
                            add(rtid, t_start, "prefill_chunk_start",
                                name, ev)
                            add(rtid, t_end, "prefill_chunk_end",
                                name, ev)

    traces = {}
    for tid, events in milestones.items():
        events, clamped = _clamp_causal(events)
        events.sort(key=lambda m: m[:3])
        record = _walk(events)
        record["clock_clamped_s"] = round(
            record["clock_clamped_s"] + clamped, 6)
        record["trace_id"] = tid
        record.update(meta_by_trace.get(tid, {}))
        traces[tid] = record
    return traces


def _clamp_causal(events: list) -> Tuple[list, float]:
    """Clock-offset error can map a replica event *before* the router
    dispatch that caused it (bounded by the link RTT — the estimator's
    hard bound).  Causality wins: every replica-side milestone of
    attempt k is clamped forward to that attempt's ``fleet_dispatch``
    time, and the total shift is reported as ``clock_clamped_s`` (a
    large value means stale offset samples or an asymmetric link, not
    a broken trace — the hop books still close exactly)."""
    dispatch_t: Dict[int, float] = {}
    for m in events:
        if m[3] == "fleet_dispatch":
            dispatch_t[int(m[5].get("attempt", 1))] = m[0]
    clamped = 0.0
    fixed = []
    for t, rank, seq, kind, process, ev in events:
        if kind in _REPLICA_KINDS:
            dt = dispatch_t.get(int(ev.get("attempt", 0) or 0))
            if dt is not None and t < dt:
                clamped += dt - t
                t = dt
        fixed.append((t, rank, seq, kind, process, ev))
    return fixed, clamped


# The state a milestone transitions the walk INTO (None = activity
# marker, no transition).  ``return_wire`` is the replica-finish →
# router-finish leg, bucketed as wire.
_TRANSITION = {
    "fleet_submit": "router_queue",
    "fleet_dispatch": "wire",
    "request_submit": "replica_queue",
    "request_admit": "admission_wait",
    "request_prefilled": "decode",
    "request_preempt": "preempted",
    "fleet_replay": "failover_replay",
    # the disaggregation handoff (ISSUE 16): opened by the router's
    # migrate-start, closed by the dispatch-onto-decode (the ordinary
    # "wire" transition) — a failed handoff exits through fleet_replay
    # instead, so either way the books close
    "fleet_migrate_start": "kv_migrate",
    "request_finish": "return_wire",
}
_BUCKET_OF = {state: state for state in TRACE_HOP_BUCKETS}
_BUCKET_OF["return_wire"] = "wire"
_TERMINAL = {"fleet_finish": "finished", "fleet_reject": "rejected"}


def _walk(events: list) -> dict:
    """One monotone pass over a trace's merged milestones: each
    inter-milestone interval lands in exactly one hop bucket (the state
    the walk was in), so the buckets partition the wall-clock by
    construction.  Backwards mapped time (clock-offset error, bounded
    by the link RTT) is clamped forward and totalled, never reordered;
    ``fleet_replay`` retro-attributes the interval since the dead
    replica's last flushed event to ``failover_replay`` (the unknowable
    post-kill remainder is failover cost, not decode)."""
    hops = {b: 0.0 for b in TRACE_HOP_BUCKETS}
    spans: List[dict] = []
    state: Optional[str] = None
    prev_t: Optional[float] = None
    t_begin: Optional[float] = None
    clamped = 0.0
    replicas: List[str] = []
    attempts = 0
    terminal = None
    for t, _rank, _seq, kind, process, ev in events:
        if terminal is not None:
            break
        if prev_t is not None and t < prev_t:
            clamped += prev_t - t
            t = prev_t
        if state is not None and prev_t is not None and t > prev_t:
            bucket = ("failover_replay" if kind == "fleet_replay"
                      else _BUCKET_OF[state])
            hops[bucket] += t - prev_t
            if (spans and spans[-1]["hop"] == bucket
                    and spans[-1]["process"] == process
                    and spans[-1]["t1"] == round(prev_t, 6)):
                # coalesce adjacent same-hop activity (per-token decode
                # ticks would otherwise leave a span per token)
                spans[-1]["t1"] = round(t, 6)
            else:
                spans.append({"t0": round(prev_t, 6),
                              "t1": round(t, 6),
                              "hop": bucket, "process": process})
        if kind == "fleet_submit" and t_begin is None:
            t_begin = t
        if kind == "fleet_dispatch":
            attempts = max(attempts, int(ev.get("attempt", 1)))
            rep = ev.get("replica")
            if rep is not None and rep not in replicas:
                replicas.append(rep)
        if kind in _TERMINAL:
            terminal = _TERMINAL[kind]
        elif kind == "prefill_chunk_start":
            # conditional boundary: only the request's FIRST chunk of
            # this admission ends its admission_wait — later chunks
            # (and other slots' chunks it rode along with) are just
            # prefill-phase activity
            if state == "admission_wait":
                state = "prefill"
        elif kind in _TRANSITION:
            state = _TRANSITION[kind]
        prev_t = t
    wall = (prev_t - t_begin) if (prev_t is not None
                                  and t_begin is not None) else 0.0
    attributed = sum(hops.values())
    return {
        "state": terminal if terminal is not None else "open",
        "t_submit": round(t_begin, 6) if t_begin is not None else None,
        "t_end": round(prev_t, 6) if prev_t is not None else None,
        "wall_s": round(wall, 6),
        "hops": {b: round(s, 6) for b, s in hops.items()},
        "spans": spans,
        "attempts": attempts,
        "replicas": replicas,
        # the per-request books, closed: a monotone partition cannot
        # double-count, so both stay 0 unless the milestone chain
        # itself is malformed — surfaced, never hidden (PR 9 rule)
        "overcommit_s": round(max(0.0, attributed - wall), 6),
        "unattributed_s": round(max(0.0, wall - attributed), 6),
        "clock_clamped_s": round(clamped, 6),
    }


# ------------------------------------------------------------ reporting


def summarize_traces(traces: dict, *, tail_pct: float = 99.0) -> dict:
    """Fleet-level rollup: total seconds per hop bucket, terminal-state
    counts, and **slowest-hop attribution for the tail** — the traces
    at or above the ``tail_pct`` wall-clock percentile, each with the
    hop that dominated it (the "where did the p99's time go" answer)."""
    closed = [r for r in traces.values() if r["state"] != "open"]
    hop_totals = {b: 0.0 for b in TRACE_HOP_BUCKETS}
    states: Dict[str, int] = {}
    for rec in traces.values():
        states[rec["state"]] = states.get(rec["state"], 0) + 1
        for b, s in rec["hops"].items():
            hop_totals[b] += s
    tail = []
    tail_wall = None
    if closed:
        walls = sorted(r["wall_s"] for r in closed)
        idx = max(0, min(len(walls) - 1,
                         int(round(tail_pct / 100.0 * len(walls))) - 1))
        tail_wall = walls[idx]
        for rec in sorted(closed, key=lambda r: -r["wall_s"]):
            if rec["wall_s"] < tail_wall:
                break
            slowest = max(rec["hops"], key=lambda b: rec["hops"][b])
            tail.append({
                "trace_id": rec["trace_id"], "rid": rec.get("rid"),
                "wall_s": rec["wall_s"], "slowest_hop": slowest,
                "slowest_hop_s": rec["hops"][slowest],
                "attempts": rec["attempts"],
                "replicas": rec["replicas"],
            })
    return {
        "requests": len(traces),
        "states": states,
        "hop_totals_s": {b: round(s, 6) for b, s in hop_totals.items()},
        "overcommit_s": round(sum(r["overcommit_s"]
                                  for r in traces.values()), 6),
        "unattributed_s": round(sum(r["unattributed_s"]
                                    for r in traces.values()), 6),
        "clock_clamped_s": round(sum(r["clock_clamped_s"]
                                     for r in traces.values()), 6),
        "tail_pct": tail_pct,
        "tail_wall_s": tail_wall,
        "tail": tail,
    }


def collect_decisions(router_run: Optional[List[dict]]) -> List[dict]:
    """(ISSUE 18) Reconstruct the autopilot's decision timeline from
    the router spill: the four ``autopilot_*`` event kinds grouped by
    ``decision_id`` into ``{decision_id, t, loop, action, reason,
    verdict, events}`` rows in decision order — the "why did the fleet
    change shape" answer printed next to the request traces."""
    by_id: Dict[str, dict] = {}
    for ev in router_run or []:
        kind = ev.get("kind", "")
        if not kind.startswith("autopilot_"):
            continue
        did = ev.get("decision_id")
        rec = by_id.setdefault(did, {
            "decision_id": did, "t": ev.get("t"), "loop": None,
            "action": None, "reason": None, "verdict": None,
            "events": []})
        rec["events"].append(dict(ev))
        if ev.get("loop") is not None:
            rec["loop"] = ev["loop"]
        if kind == "autopilot_decide":
            rec["action"] = ev.get("action")
            rec["reason"] = ev.get("reason")
        elif kind == "autopilot_verdict":
            rec["verdict"] = ev.get("verdict")
    return sorted(by_id.values(),
                  key=lambda r: (r["t"] if r["t"] is not None else 0.0,
                                 str(r["decision_id"])))


def collect_slo_events(events: Optional[List[dict]]) -> dict:
    """(ISSUE 20) Reconstruct the SLO plane's story from a spill: the
    burn-rate transition events and the periodic budget-table snapshots
    the evaluator emitted.  ``{"alerts": [...], "clears": [...],
    "states": [...], "open": [...]}`` — ``open`` lists the
    ``(policy, metric)`` pairs whose newest transition is an alert with
    no later clear (an incident still burning at end of spill).  This
    is the consumption side of the ``slo_burn_alert`` /
    ``slo_burn_clear`` / ``slo_state`` vocabulary (APX302) and the raw
    material of ``scripts/slo_report.py``."""
    alerts: List[dict] = []
    clears: List[dict] = []
    states: List[dict] = []
    last: Dict[Tuple[str, str], str] = {}
    for ev in events or []:
        kind = ev.get("kind")
        if kind == "slo_burn_alert":
            alerts.append(dict(ev))
            last[(str(ev.get("policy")), str(ev.get("metric")))] = "alert"
        elif kind == "slo_burn_clear":
            clears.append(dict(ev))
            last[(str(ev.get("policy")), str(ev.get("metric")))] = "clear"
        elif kind == "slo_state":
            states.append(dict(ev))
    return {"alerts": alerts, "clears": clears, "states": states,
            "open": sorted(k for k, v in last.items() if v == "alert")}


def merge_dir(timeline_dir: str, *, strict: bool = True,
              tail_pct: float = 99.0) -> dict:
    """The one-call merge: read a fleet run's spills, stitch, and
    summarize — ``{"traces": {...}, "summary": {...}, "decisions":
    [...]}`` (``decisions`` is the autopilot's reconstructed timeline,
    empty when no autopilot ran)."""
    router_run, replica_runs = read_fleet_spills(timeline_dir,
                                                 strict=strict)
    traces = stitch_traces(router_run, replica_runs)
    return {"traces": traces,
            "summary": summarize_traces(traces, tail_pct=tail_pct),
            "decisions": collect_decisions(router_run)}


def format_trace_report(report: dict) -> str:
    """Human-readable block (what ``scripts/trace_report.py`` prints)."""
    summary = report["summary"]
    lines = [
        f"traces: {summary['requests']} request(s), "
        f"states {summary['states']}",
    ]
    total = sum(summary["hop_totals_s"].values()) or 1.0
    for bucket in TRACE_HOP_BUCKETS:
        sec = summary["hop_totals_s"].get(bucket, 0.0)
        if sec:
            lines.append(f"  {bucket:<16} {sec:10.3f}s  "
                         f"{sec / total:6.1%}")
    for key in ("overcommit_s", "unattributed_s", "clock_clamped_s"):
        if summary.get(key):
            lines.append(f"  {key.upper()} {summary[key]:.6f}s")
    if summary["tail"]:
        lines.append(f"tail (>= p{summary['tail_pct']:g} wall "
                     f"{summary['tail_wall_s']:.3f}s):")
        for row in summary["tail"]:
            lines.append(
                f"  {row['trace_id']} rid={row['rid']} "
                f"wall {row['wall_s']:.3f}s <- {row['slowest_hop']} "
                f"({row['slowest_hop_s']:.3f}s, "
                f"attempts={row['attempts']}, "
                f"replicas={row['replicas']})")
    decisions = report.get("decisions") or []
    if decisions:
        lines.append(f"autopilot decisions: {len(decisions)}")
        for rec in decisions:
            verdict = rec["verdict"] if rec["verdict"] is not None \
                else "(open)"
            lines.append(
                f"  {rec['decision_id']} t={rec['t']:.3f} "
                f"[{rec['loop']}] {rec['action']} -> {verdict}"
                + (f"  # {rec['reason']}" if rec.get("reason") else ""))
    return "\n".join(lines)
