"""Flight recorder — the run-timeline layer (ISSUE 10 tentpole).

PR 5 gave every subsystem numbers (gauges, histograms, spans); what no
subsystem had is a shared **timeline**: one monotonic-clock event log a
human (or :mod:`.goodput`) can replay to answer "where did this run's
wall-clock actually go?".  TorchTitan (PAPERS.md, arxiv 2410.06511)
treats exactly this — per-phase time attribution over always-on
lightweight tracing — as table stakes for a production stack.

One object, :class:`FlightRecorder`, owns the log:

- **events** are flat JSON dicts ``{"t": <monotonic seconds since the
  recorder armed>, "kind": <type>, ...}``; interval kinds additionally
  carry ``dur_s`` (the event is emitted at the interval's *end*, so a
  crash loses at most the in-flight interval — there are no dangling
  ``begin`` markers to repair);
- a **bounded in-memory ring** keeps the newest events for live
  introspection (``/statusz`` tail, :meth:`FlightRecorder.tail`) — a
  weeks-long run cannot leak memory through its own telemetry;
- an optional **JSONL spill** writes every event through
  :class:`~apex_tpu.observability.writers.JsonlWriter` — one
  ``O_APPEND`` single-shot line per event, so a SIGKILL tears at most
  the final line and :func:`~apex_tpu.observability.writers.read_jsonl`
  (strict) recovers the intact prefix — the PR 3/PR 5 crash-safety
  contract applied to the timeline (``fsync=False`` by default: process
  death cannot tear a buffered line, only power loss can, and an fsync
  per decode tick would tax the serving hot loop);
- **goodput buckets accumulate incrementally** at emit time (see
  :mod:`.goodput` for the classification), so goodput-so-far is O(1)
  to read at any instant even after the ring has wrapped.

Event schema (the full catalog is documented in
``docs/observability.md``):

=====================  ====================================================
kind                   payload (beyond ``t`` / ``dur_s``)
=====================  ====================================================
``run_begin``          ``wall_ts`` (epoch seconds) + caller metadata
``run_end``            ``wall_s`` — total armed wall-clock
``step``               ``step``; ``skipped=True`` for sentinel skips
``compile``            ``what`` — program name
``checkpoint_save``    (also ``checkpoint_save_async_submit``) ``step``
``checkpoint_verify``  ``step``
``checkpoint_restore`` ``step``
``data_stall``         blocking input wait (``data/prefetch.py``)
``sentinel_skip``      ``step``, ``skipped_steps`` (cumulative)
``preemption``         ``wall_ts``
``drain``              serving/trainer drain window
``request_submit``     ``rid``, ``prompt_tokens``, ``max_new_tokens``
``request_admit``      ``rid``, ``slot``, ``blocks``
``prefill``            ``rids`` (packed row), ``tokens``
``decode_tick``        ``rid``, ``tokens`` — every N generated tokens
``request_finish``     ``rid``, ``tokens``
``request_cancel``     ``rid``
``request_reject``     ``rid`` — refused at submit (drain window /
                       overload shed), never queued
``autopilot_observe``  ``decision_id``, ``loop`` + the signal snapshot
                       (queue depth, p99 trend, attribution, ...) the
                       decision was made on (ISSUE 18)
``autopilot_decide``   ``decision_id``, ``loop``, ``action``,
                       ``reason`` — what the autopilot chose and why
``autopilot_act``      ``decision_id``, ``action`` + actuation detail
                       (``replica`` spawned/drained/quarantined, knob
                       ``payload`` + ``canary`` host, ...)
``autopilot_verdict``  ``decision_id``, ``verdict`` — how the decision
                       resolved: ``joined`` / ``drained`` / ``reaped``
                       / ``quarantined`` / ``commit`` / ``rollback`` /
                       ``inconclusive`` / ``no action`` (+ ``ratio``,
                       ``rounds`` for canary judges)
=====================  ====================================================

The four ``autopilot_*`` kinds share one ``decision_id`` per decision
(observe → decide → act → verdict), so ``scripts/trace_report.py`` can
reconstruct *why* the fleet changed shape next to the request traces.

Arming is process-global and **opt-in**: the module-level
:func:`emit`/:func:`scope` used by the instrumented subsystems
(trainer drivers, ``CheckpointManager``, ``DevicePrefetcher``, the
serving engine) are a single ``is None`` check when no recorder is
armed — the free-telemetry property (overhead A/B ≤ 1.05, zero HLO
difference) is pinned by ``tests/test_timeline.py`` and the
``telemetry_overhead`` bench row, which times its instrumented variant
with a recorder armed.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

from apex_tpu.observability.goodput import assemble_report, classify_event

__all__ = [
    "FlightRecorder",
    "arm",
    "arm_from_env",
    "disarm",
    "active",
    "emit",
    "scope",
    "TIMELINE_ENV_VAR",
]

TIMELINE_ENV_VAR = "APEX_TPU_TIMELINE_DIR"


class FlightRecorder:
    """Crash-safe structured event log on one process-local monotonic
    clock.

    ``path``   — optional JSONL spill; every event is durably appended
                 (torn-tail-only loss under SIGKILL).  ``None`` keeps
                 the ring only (unit tests, pure introspection).
    ``ring``   — in-memory tail size for live introspection.
    ``fsync``  — per-event fsync on the spill.  Off by default: the
                 single ``os.write`` of a full line already survives
                 process death; fsync only buys power-loss durability
                 at a syscall per event.
    ``meta``   — extra fields stamped onto the ``run_begin`` event
                 (run name, mesh shape, ...).
    """

    def __init__(self, path: Optional[str] = None, *, ring: int = 4096,
                 fsync: bool = False, meta: Optional[dict] = None):
        from apex_tpu.observability.writers import JsonlWriter

        if ring < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        self.path = path
        # keep_open: the spill is per-process (a traced fleet child
        # arms its own recorder post-spawn, never inheriting this
        # descriptor), and at traced-serving event rates the
        # open-per-record cycle would be the dominant cost of the
        # armed path (the vs_bare <= 1.05 gate); durability is
        # unchanged — one O_APPEND write per event, torn-tail-only
        self._writer = (JsonlWriter(path, fsync=fsync, keep_open=True)
                        if path else None)
        self._ring: "collections.deque[dict]" = collections.deque(maxlen=ring)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.events_emitted = 0
        # incremental goodput accounting: bucket -> attributed seconds
        # (classification lives in goodput.py; accumulating here keeps
        # goodput-so-far exact after the ring wraps)
        self._bucket_s: Dict[str, float] = {}
        # mono_t0 anchors this spill on the process's monotonic clock:
        # cross-process trace stitching (observability/trace.py) maps an
        # event's relative ``t`` back to raw monotonic time as
        # ``mono_t0 + t``, then onto the router clock via the per-link
        # offset samples — relative-only spills could never be merged
        self.emit("run_begin", wall_ts=time.time(),
                  mono_t0=round(self._t0, 6), **(meta or {}))

    # ------------------------------------------------------------ clock

    @property
    def elapsed_s(self) -> float:
        return time.monotonic() - self._t0

    # ------------------------------------------------------------- emit

    def emit(self, kind: str, *, dur_s: Optional[float] = None,
             **fields: Any) -> dict:
        """Record one event now.  Interval events pass ``dur_s`` (the
        caller measured it; the event lands at the interval's end)."""
        ev: Dict[str, Any] = {"t": round(self.elapsed_s, 6), "kind": kind}
        if dur_s is not None:
            ev["dur_s"] = round(float(dur_s), 6)
        ev.update(fields)
        bucket = classify_event(ev)
        with self._lock:
            self._ring.append(ev)
            self.events_emitted += 1
            if bucket is not None and dur_s is not None:
                self._bucket_s[bucket] = (
                    self._bucket_s.get(bucket, 0.0) + float(dur_s))
        if self._writer is not None:
            self._writer.write(ev)
        return ev

    @contextlib.contextmanager
    def scope(self, kind: str, **fields: Any):
        """Time a block and emit one ``kind`` event with its ``dur_s``
        when it exits (even on exception — the crash-visible shape is a
        *missing* final event, never a dangling half-interval)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.emit(kind, dur_s=time.monotonic() - t0, **fields)

    # ----------------------------------------------------- typed helpers

    def step(self, step: int, **fields: Any):
        """Scope for one training step's host dispatch+sync window."""
        return self.scope("step", step=step, **fields)

    def compile(self, what: str):
        return self.scope("compile", what=what)

    def data_stall(self, dur_s: float, **fields: Any) -> dict:
        return self.emit("data_stall", dur_s=dur_s, **fields)

    def sentinel_skip(self, step: int, skipped_steps: int) -> dict:
        return self.emit("sentinel_skip", step=step,
                         skipped_steps=skipped_steps)

    def preemption(self, **fields: Any) -> dict:
        return self.emit("preemption", wall_ts=time.time(), **fields)

    # ------------------------------------------------------ introspection

    def events(self) -> List[dict]:
        """Snapshot of the in-memory ring (oldest retained first)."""
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 32) -> List[dict]:
        with self._lock:
            if n >= len(self._ring):
                return list(self._ring)
            return list(self._ring)[-n:]

    def report(self) -> dict:
        """Goodput-so-far from the incremental bucket accounting (exact
        even after the ring wrapped) — see :func:`goodput.goodput_report`
        for the offline recompute over a spilled timeline."""
        with self._lock:
            buckets = dict(self._bucket_s)
        return assemble_report(buckets, wall_s=self.elapsed_s)

    # ------------------------------------------------------------- flush

    def flush(self, goodput_path: Optional[str] = None) -> dict:
        """Emit ``run_end``, compute the final goodput report, and
        optionally write it as JSON.  Idempotent-ish: callable once per
        run end (a second call emits a second ``run_end``)."""
        wall = self.elapsed_s
        self.emit("run_end", wall_s=round(wall, 6))
        report = self.report()
        if goodput_path:
            import json

            parent = os.path.dirname(goodput_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = goodput_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, goodput_path)
        return report


# --- process-global arming ------------------------------------------------

_ACTIVE: Optional[FlightRecorder] = None
_ARM_LOCK = threading.Lock()


def arm(recorder_or_path) -> FlightRecorder:
    """Install the process-wide recorder (a :class:`FlightRecorder`, or
    a path string to spill to).  Instrumented subsystems pick it up via
    the module-level :func:`emit`/:func:`scope`."""
    global _ACTIVE
    rec = (recorder_or_path if isinstance(recorder_or_path, FlightRecorder)
           else FlightRecorder(recorder_or_path))
    with _ARM_LOCK:
        _ACTIVE = rec
    return rec


def arm_from_env() -> Optional[FlightRecorder]:
    """Arm from ``APEX_TPU_TIMELINE_DIR`` (spill to
    ``<dir>/timeline.jsonl``); ``None`` when the variable is unset —
    the zero-cost default."""
    d = os.environ.get(TIMELINE_ENV_VAR)
    if not d:
        return None
    return arm(os.path.join(d, "timeline.jsonl"))


def disarm() -> Optional[FlightRecorder]:
    """Remove (and return) the process recorder."""
    global _ACTIVE
    with _ARM_LOCK:
        rec, _ACTIVE = _ACTIVE, None
    return rec


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def emit(kind: str, *, dur_s: Optional[float] = None,
         **fields: Any) -> Optional[dict]:
    """Emit into the armed recorder; a single ``None`` check when
    unarmed — safe on any hot host path."""
    rec = _ACTIVE
    if rec is None:
        return None
    return rec.emit(kind, dur_s=dur_s, **fields)


@contextlib.contextmanager
def scope(kind: str, **fields: Any):
    """Module-level :meth:`FlightRecorder.scope`; no-op (no clock read,
    no allocation beyond the generator) when unarmed."""
    rec = _ACTIVE
    if rec is None:
        yield
        return
    with rec.scope(kind, **fields):
        yield
