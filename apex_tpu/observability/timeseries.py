"""Longitudinal metrics history — fixed-memory ring-buffer time series
(ISSUE 20).

Every number the fleet exposes today is a *snapshot*: ``/fleet/statusz``
answers "what is the p99 right now", the autopilot's trend deque holds
whatever samples happened to land in its window, and nothing can answer
"what was the queue depth ninety seconds before the burst".
:class:`MetricHistory` is the memory: it snapshots a
:class:`~apex_tpu.observability.metrics.MetricRegistry` on an
injectable-clock cadence and folds every reading into multi-resolution
ring buffers — by default 1 s × 512, 10 s × 512, 60 s × 512 buckets, so
RAM is bounded regardless of uptime (the coarse rings ARE the
downsample: one bucket aggregates count/sum/min/max/last of every raw
sample that landed in its window, so the 10 s ring's mean/max equals
the mean/max of the 1 s ring over the same span — pinned by
``tests/test_slo.py``).

Reading rules, per registry type:

- **counters** become *rates* (delta / sample interval).  A monotonic
  drop — a replica restart resetting its counters — is treated as a
  reset: the post-reset value is the delta (never a negative rate).
- **gauges** record their value (``None`` gauges are skipped).
- **sampled histograms** record their windowed ``p50``/``p99`` under
  ``<name>:p50`` / ``<name>:p99``, plus a ``<name>:rate`` series from
  the observation-count delta (same reset handling as counters).

Cardinality is bounded twice: the registry's own key caps upstream, and
``max_series`` here — a novel series name past the cap lands in the
explicit ``(other)`` overflow series and fires ``on_overflow`` (the
fleet router wires that to the ``fleet/series_overflow`` counter), so
an adversarial tenant-id stream cannot grow the store.

Replica → router shipping rides the existing state-heartbeat path as
*compacted deltas*: :meth:`MetricHistory.export_delta` returns only the
fine-ring buckets completed since the last export, and the router's
:meth:`MetricHistory.ingest_delta` merges them under a
``replica/<name>/`` prefix, rebasing the replica's monotonic bucket
stamps onto the local clock by the export-time offset (error bounded by
heartbeat cadence + link delay — the PR 13 rule that cross-host clocks
are never compared raw, applied cheaply).

jax-free, stdlib-only, single-threaded by design: the router samples
from its own pump loop, a replica from its heartbeat closure.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MetricHistory", "match_series"]

# Bucket layout (a plain list, mutated in place on merge):
# [t_bucket_start, count, sum, min, max, last]
_T, _COUNT, _SUM, _MIN, _MAX, _LAST = range(6)

DEFAULT_RESOLUTIONS: Tuple[Tuple[float, int], ...] = (
    (1.0, 512), (10.0, 512), (60.0, 512))

OVERFLOW_SERIES = "(other)"


def match_series(pattern: str, name: str) -> bool:
    """Segment-wise series-name match: ``*`` matches exactly one
    ``/``-separated segment (``fleet/tenant/*/ttft_ms:p99`` matches
    every tenant's TTFT tail and nothing else)."""
    pseg = pattern.split("/")
    nseg = name.split("/")
    if len(pseg) != len(nseg):
        return False
    return all(p == "*" or p == n for p, n in zip(pseg, nseg))


class MetricHistory:
    """Fixed-memory multi-resolution history over one metric registry."""

    def __init__(self, registry=None, *,
                 resolutions: Sequence[Tuple[float, int]] = DEFAULT_RESOLUTIONS,
                 max_series: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 on_overflow: Optional[Callable[[], None]] = None):
        if not resolutions:
            raise ValueError("resolutions must be non-empty")
        res = [(float(r), int(n)) for r, n in resolutions]
        for (r, n) in res:
            if r <= 0 or n <= 0:
                raise ValueError(f"bad resolution {(r, n)!r}")
        if any(res[i][0] >= res[i + 1][0] for i in range(len(res) - 1)):
            raise ValueError("resolutions must be strictly ascending")
        if max_series < 1:
            raise ValueError("max_series must be >= 1")
        self.resolutions: Tuple[Tuple[float, int], ...] = tuple(res)
        self.max_series = int(max_series)
        self._registry = registry
        self._clock = clock
        self._on_overflow = on_overflow
        self._series: Dict[str, List[deque]] = {}
        self._prev: Dict[Tuple[str, str], float] = {}   # counter/count memory
        self._cursor: Dict[str, float] = {}             # export watermark
        self._last_t: Optional[float] = None
        self._samples = 0

    # ------------------------------------------------------------ write

    def _rings_for(self, name: str) -> Tuple[str, List[deque]]:
        rings = self._series.get(name)
        if rings is None:
            if len(self._series) >= self.max_series \
                    and name != OVERFLOW_SERIES:
                if self._on_overflow is not None:
                    self._on_overflow()
                name = OVERFLOW_SERIES
                rings = self._series.get(name)
            if rings is None:
                rings = [deque(maxlen=n) for _r, n in self.resolutions]
                self._series[name] = rings
        return name, rings

    def _merge(self, name: str, t: float, count: float, total: float,
               vmin: float, vmax: float, last: float) -> None:
        _name, rings = self._rings_for(name)
        for (res, _n), ring in zip(self.resolutions, rings):
            tb = math.floor(t / res) * res
            if ring and ring[-1][_T] >= tb:
                b = ring[-1]          # in-order or late: fold into newest
                b[_COUNT] += count
                b[_SUM] += total
                if vmin < b[_MIN]:
                    b[_MIN] = vmin
                if vmax > b[_MAX]:
                    b[_MAX] = vmax
                b[_LAST] = last
            else:
                ring.append([tb, count, total, vmin, vmax, last])

    def record(self, name: str, value: float,
               now: Optional[float] = None) -> None:
        """Fold one raw reading into every resolution ring."""
        t = self._clock() if now is None else float(now)
        v = float(value)
        self._merge(name, t, 1.0, v, v, v, v)

    def _rated(self, kind: str, name: str, cur: float,
               dt: Optional[float]) -> Optional[float]:
        """Counter→rate with monotonic-reset handling: a drop means the
        source restarted, so the post-reset value IS the delta."""
        prev = self._prev.get((kind, name))
        self._prev[(kind, name)] = cur
        if prev is None or dt is None or dt <= 0:
            return None
        delta = cur - prev if cur >= prev else cur
        return delta / dt

    def sample(self, now: Optional[float] = None) -> None:
        """Snapshot the registry once: counters as rates, gauges as
        values, sampled histograms as ``:p50``/``:p99``/``:rate``."""
        if self._registry is None:
            raise ValueError("MetricHistory built without a registry")
        t = self._clock() if now is None else float(now)
        dt = None if self._last_t is None else t - self._last_t
        snap = self._registry.snapshot_typed()
        for name in sorted(snap["counters"]):
            rate = self._rated("c", name, float(snap["counters"][name]), dt)
            if rate is not None:
                self.record(name, rate, now=t)
        for name in sorted(snap["gauges"]):
            val = snap["gauges"][name]
            if val is not None:
                self.record(name, float(val), now=t)
        for name in sorted(snap["histograms"]):
            summ = snap["histograms"][name]
            for field in ("p50", "p99"):
                val = summ.get(field)
                if val is not None:
                    self.record(f"{name}:{field}", float(val), now=t)
            rate = self._rated("h", name, float(summ.get("count", 0)), dt)
            if rate is not None:
                self.record(f"{name}:rate", rate, now=t)
        self._last_t = t
        self._samples += 1

    # ------------------------------------------------------- delta wire

    def export_delta(self, now: Optional[float] = None) -> Optional[dict]:
        """Fine-ring buckets completed since the last export (a bucket
        is complete once its window closed), or ``None`` when nothing
        new finished — the compacted payload the replica heartbeat
        attaches to its ``("state", snap)`` event."""
        t = self._clock() if now is None else float(now)
        res = self.resolutions[0][0]
        series: Dict[str, List[list]] = {}
        for name, rings in self._series.items():
            cur = self._cursor.get(name)
            fresh = [list(b) for b in rings[0]
                     if (cur is None or b[_T] > cur) and b[_T] + res <= t]
            if fresh:
                series[name] = fresh
                self._cursor[name] = fresh[-1][_T]
        if not series:
            return None
        return {"v": 1, "res": res, "now": t, "series": series}

    def ingest_delta(self, payload: dict, *, prefix: str = "",
                     now: Optional[float] = None) -> int:
        """Merge an exported delta (rebased onto the local clock by the
        export-time offset) under ``prefix``; returns buckets merged."""
        if not payload:
            return 0
        t = self._clock() if now is None else float(now)
        offset = t - float(payload.get("now", t))
        merged = 0
        for name, buckets in sorted((payload.get("series") or {}).items()):
            for b in buckets:
                tb, count, total, vmin, vmax, last = b
                self._merge(prefix + name, float(tb) + offset,
                            float(count), float(total), float(vmin),
                            float(vmax), float(last))
                merged += 1
        return merged

    # ------------------------------------------------------------- read

    def series_names(self) -> List[str]:
        return sorted(self._series)

    def _ring_covering(self, rings: List[deque],
                       cut: float) -> Tuple[float, deque]:
        """The finest ring whose retained span still reaches back to
        ``cut`` (else the coarsest non-empty ring)."""
        best = None
        for (res, _n), ring in zip(self.resolutions, rings):
            if ring:
                best = (res, ring)
                if ring[0][_T] <= cut:
                    break
        return best if best is not None else (self.resolutions[0][0],
                                              deque())

    def bucket_points(self, name: str, window_s: float, *,
                      now: Optional[float] = None,
                      field: str = "mean") -> List[Tuple[float, float]]:
        """``(bucket_midpoint_t, value)`` pairs over the trailing
        window, from the finest ring that still covers it."""
        rings = self._series.get(name)
        if not rings:
            return []
        t = self._clock() if now is None else float(now)
        cut = t - float(window_s)
        res, ring = self._ring_covering(rings, cut)
        out = []
        for b in ring:
            if b[_T] + res <= cut or b[_T] > t:
                continue
            if field == "mean":
                v = b[_SUM] / b[_COUNT] if b[_COUNT] else 0.0
            elif field == "max":
                v = b[_MAX]
            elif field == "min":
                v = b[_MIN]
            elif field == "last":
                v = b[_LAST]
            else:
                raise ValueError(f"unknown field {field!r}")
            out.append((b[_T] + res / 2.0, v))
        return out

    def bad_fraction(self, name: str, window_s: float, objective: float,
                     *, now: Optional[float] = None,
                     field: str = "mean") -> float:
        """Fraction of trailing-window buckets whose ``field`` aggregate
        exceeds ``objective`` (0.0 with no data retained there).  This
        is the SLO evaluator's inner loop — three window scans per
        policy row per cadence tick — so it walks the ring in place
        instead of materializing :meth:`bucket_points` tuples (~3x off
        the armed-path cost the ``serving_slo_overhead`` bench gates)."""
        rings = self._series.get(name)
        if not rings:
            return 0.0
        t = self._clock() if now is None else float(now)
        cut = t - float(window_s)
        res, ring = self._ring_covering(rings, cut)
        total = bad = 0
        # newest-first with an early break: a 5 s fast window touches
        # ~6 buckets of a 512-bucket ring, not all of them
        for b in reversed(ring):
            if b[_T] > t:
                continue
            if b[_T] + res <= cut:
                break
            if field == "mean":
                v = b[_SUM] / b[_COUNT] if b[_COUNT] else 0.0
            elif field == "max":
                v = b[_MAX]
            elif field == "last":
                v = b[_LAST]
            else:
                raise ValueError(f"unknown field {field!r}")
            total += 1
            if v > objective:
                bad += 1
        return bad / total if total else 0.0

    def window(self, name: str, window_s: float, *,
               now: Optional[float] = None) -> Optional[dict]:
        """Aggregate over the trailing window: ``{count, mean, min,
        max, last}``, or ``None`` with no data retained there."""
        rings = self._series.get(name)
        if not rings:
            return None
        t = self._clock() if now is None else float(now)
        cut = t - float(window_s)
        res, ring = self._ring_covering(rings, cut)
        hits = [b for b in ring if b[_T] + res > cut and b[_T] <= t]
        if not hits:
            return None
        count = sum(b[_COUNT] for b in hits)
        total = sum(b[_SUM] for b in hits)
        return {"count": count,
                "mean": total / count if count else 0.0,
                "min": min(b[_MIN] for b in hits),
                "max": max(b[_MAX] for b in hits),
                "last": hits[-1][_LAST]}

    def latest(self, name: str) -> Optional[float]:
        rings = self._series.get(name)
        for ring in (rings or []):
            if ring:
                return ring[-1][_LAST]
        return None

    def slope(self, name: str, window_s: float, *,
              now: Optional[float] = None,
              field: str = "mean") -> float:
        """Least-squares slope (value units per second) over the
        trailing window; 0.0 until two buckets exist — the longitudinal
        replacement for the router's ad-hoc trend deque."""
        pts = self.bucket_points(name, window_s, now=now, field=field)
        if len(pts) < 2:
            return 0.0
        n = float(len(pts))
        mean_t = sum(t for t, _v in pts) / n
        mean_v = sum(v for _t, v in pts) / n
        den = sum((t - mean_t) ** 2 for t, _v in pts)
        if den <= 0:
            return 0.0
        num = sum((t - mean_t) * (v - mean_v) for t, v in pts)
        return num / den

    def match(self, pattern: str) -> List[str]:
        """Series names matching a ``*``-segment pattern (sorted)."""
        if "*" not in pattern:
            return [pattern] if pattern in self._series else []
        return [n for n in self.series_names() if match_series(pattern, n)]

    def introspect(self) -> dict:
        return {
            "series": len(self._series),
            "max_series": self.max_series,
            "overflowed": OVERFLOW_SERIES in self._series,
            "resolutions": [[r, n] for r, n in self.resolutions],
            "samples": self._samples,
            "last_sample_t": self._last_t,
        }
