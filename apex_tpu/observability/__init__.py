"""Training telemetry — in-graph stats, profiler spans, crash-safe metrics.

A training run on preemptible hardware is flying blind without three
layers the production JAX/PyTorch trainers treat as first-class
(TorchTitan arxiv 2410.06511; veScale arxiv 2509.07003):

- :mod:`.trainstats` — the **device** layer: a jit-safe
  :class:`TrainStats` pytree (loss, grad/param global norms, non-finite
  leaf count, loss scale, cumulative sentinel skips, per-microbatch MoE
  aux) computed inside the step with zero extra host syncs and **at
  most the collectives already on the path** (cross-rank stats ride the
  trainer's existing loss reduction, widened — never added; pinned by
  an HLO compare in ``tests/test_observability.py``).  Threaded through
  ``zero_data_parallel_train_step``, ``build_gpt_3d``
  (``collect_stats=True``) and the driver dryrun.
- :mod:`.spans` — the **profiler** layer: ``named_span`` op-metadata
  scopes on the hot traced paths (collective-matmul rings, ZeRO bucket
  exchange, pipeline ticks), host ``span`` wall-clock timers
  (checkpoint save/verify/restore), ``step_trace`` step annotations,
  and :class:`TraceWindow` windowed programmatic xprof capture — the
  evidence channel the real-TPU ``overlap_comm`` A/B needs (ROADMAP).
- :mod:`.metrics` + :mod:`.writers` — the **host** layer: rank-aware
  :class:`MetricRegistry` (counters/gauges/histograms, flushed on rank
  0 only), MFU from ``compiled.cost_analysis()``, a
  :class:`HeartbeatMonitor` that flags hung steps to
  ``resilience.PreemptionGuard``, and an append-only fsync'd
  :class:`JsonlWriter` whose reader tolerates torn tails (the PR 3
  crash-safety contract, applied to metrics).

- :mod:`.timeline` + :mod:`.goodput` + :mod:`.debug_server` — the
  **run-timeline** layer (ISSUE 10): a crash-safe monotonic-clock
  :class:`FlightRecorder` (bounded ring + JSONL spill, torn-tail-only
  loss) fed by the trainer drivers, ``CheckpointManager``,
  ``DevicePrefetcher``, and the serving engine; a goodput/badput
  report attributing every wall-clock second to one bucket (compute /
  compile / data stall / checkpoint / restore / skipped / drain /
  other) plus per-request serving attribution; and an opt-in stdlib
  HTTP :class:`DebugServer` (``/metrics`` Prometheus text,
  ``/statusz`` live timeline tail + goodput + engine state).

- :mod:`.timeseries` + :mod:`.slo` — the **longitudinal** layer
  (ISSUE 20): a jax-free fixed-memory multi-resolution ring-buffer
  :class:`MetricHistory` snapshotting a registry on an injected-clock
  cadence (counter→rate with reset handling, compacted deltas over the
  fleet's state heartbeats), plus :class:`SLOPolicy` /
  :class:`SLOEvaluator` — Google-SRE multi-window burn-rate alerting
  with hysteresis, typed ``slo_burn_alert`` / ``slo_burn_clear``
  timeline events, error-budget accounting, and the predictive signals
  the fleet autopilot scales on; OpenMetrics exposition at
  ``/metrics.prom`` (:func:`render_openmetrics`).

Catalog, span map, timeline schema, goodput cookbook, and the
profiler-capture cookbook: ``docs/observability.md``.
"""

from apex_tpu.observability.debug_server import DebugServer, render_openmetrics
from apex_tpu.observability.goodput import (
    format_report,
    goodput_report,
    serving_goodput_report,
)
from apex_tpu.observability.metrics import (
    HeartbeatMonitor,
    MetricRegistry,
    compiled_flops,
    default_registry,
    is_host_local,
    mfu,
    mfu_or_reason,
    peak_flops_for,
    peak_flops_reason,
)
from apex_tpu.observability.slo import SLOEvaluator, SLOPolicy
from apex_tpu.observability.timeline import FlightRecorder
from apex_tpu.observability.timeseries import MetricHistory, match_series
from apex_tpu.observability.trace import (
    TRACE_HOP_BUCKETS,
    collect_slo_events,
    estimate_offset,
    format_trace_report,
    merge_dir,
    stitch_traces,
    summarize_traces,
)
from apex_tpu.observability.spans import (
    TraceWindow,
    named_span,
    span,
    step_trace,
)
from apex_tpu.observability.trainstats import (
    PartialTrainStats,
    TrainStats,
    TrainStatsLogger,
    device_partial_norms,
    local_grad_stats,
    pack_local_stats,
    partial_train_stats,
    stats_from_reduced,
    stats_partition_specs,
    train_stats,
)
from apex_tpu.observability.writers import JsonlWriter, iter_jsonl, read_jsonl

__all__ = [
    "TrainStats",
    "PartialTrainStats",
    "TrainStatsLogger",
    "train_stats",
    "partial_train_stats",
    "device_partial_norms",
    "local_grad_stats",
    "pack_local_stats",
    "stats_from_reduced",
    "stats_partition_specs",
    "named_span",
    "span",
    "step_trace",
    "TraceWindow",
    "MetricRegistry",
    "default_registry",
    "is_host_local",
    "HeartbeatMonitor",
    "compiled_flops",
    "peak_flops_for",
    "peak_flops_reason",
    "mfu",
    "mfu_or_reason",
    "JsonlWriter",
    "read_jsonl",
    "iter_jsonl",
    "FlightRecorder",
    "DebugServer",
    "goodput_report",
    "serving_goodput_report",
    "format_report",
    "TRACE_HOP_BUCKETS",
    "estimate_offset",
    "stitch_traces",
    "summarize_traces",
    "merge_dir",
    "format_trace_report",
    "MetricHistory",
    "match_series",
    "SLOPolicy",
    "SLOEvaluator",
    "render_openmetrics",
    "collect_slo_events",
]
