"""Span timers + profiler annotations — the NVTX/xprof layer.

Two kinds of instrumentation, deliberately distinct because they see
different clocks:

- :func:`named_span` — for **traced** code (inside jit/shard_map): a
  ``jax.named_scope`` that stamps the emitted ops' metadata so xprof
  groups the ring-matmul chunk GEMMs, bucket reduce-scatters, and
  pipeline ticks under readable names.  Adds ZERO HLO operations (pure
  metadata — the instrumented/bare HLO-parity test in
  ``tests/test_observability.py`` depends on this), so it is safe on any
  hot path.
- :func:`span` — for **host** code (checkpoint save/verify/restore,
  data loading, the step dispatch loop): wall-clock timing recorded into
  a :class:`~apex_tpu.observability.metrics.MetricRegistry` histogram
  plus a ``jax.profiler.TraceAnnotation`` so the same interval shows up
  as a range in a captured trace (the ``nvtx.range_push`` analog,
  ``apex/parallel/distributed.py:363``).

Plus the two step-level tools the real-TPU ``overlap_comm`` A/B runbook
needs (ROADMAP; ``docs/tpu_capture_runbook.md``):

- :func:`step_trace` — ``jax.profiler.StepTraceAnnotation`` wrapper, so
  xprof's step-time view segments by training step;
- :class:`TraceWindow` — windowed programmatic capture: every
  ``every_n`` steps, ``jax.profiler.start_trace`` for ``capture_steps``
  steps then stop, so a long run continuously produces *small* trace
  windows instead of one giant (or zero) capture — the per-step timing
  evidence the overlap A/B must land with.

The span catalog (which names instrument which subsystem) is documented
in ``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Optional

import jax

__all__ = ["named_span", "span", "step_trace", "TraceWindow"]

logger = logging.getLogger(__name__)

# One shared prefix so apex spans are greppable in an xprof trace among
# the framework-emitted scopes.
_PREFIX = "apex"


def named_span(name: str):
    """Trace-time scope for jitted code: ``with named_span("zero/rs")``.

    Pure op-metadata (``jax.named_scope``) — compiles to the identical
    HLO program, only with attributable op names.  Use this inside any
    traced function; use :func:`span` for host-side intervals.
    """
    return jax.named_scope(f"{_PREFIX}/{name}")


@contextlib.contextmanager
def span(name: str, *, registry=None):
    """Host wall-clock span: times the block, records
    ``span_ms/<name>`` into the registry's histogram, and opens a
    ``jax.profiler.TraceAnnotation`` so captured traces carry the range.

    NOTE: host spans measure *dispatch* unless the block itself blocks
    (``jax.block_until_ready``, file I/O) — time jitted work with
    :func:`step_trace` + a trace window, not with a host span around an
    async dispatch.
    """
    if registry is None:
        from apex_tpu.observability.metrics import default_registry

        registry = default_registry()
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(f"{_PREFIX}/{name}"):
            yield
    finally:
        registry.histogram(f"span_ms/{name}").observe(
            (time.perf_counter() - t0) * 1e3)


def step_trace(step_num: int, name: str = "train_step"):
    """``jax.profiler.StepTraceAnnotation`` for one training step — wrap
    the step dispatch so xprof's step-time view segments correctly::

        with step_trace(step):
            state = train_step(*state)
    """
    return jax.profiler.StepTraceAnnotation(name, step_num=step_num)


class TraceWindow:
    """Windowed programmatic profiler capture.

    ``on_step(step)`` is called once per training step (before or after
    the dispatch — it only manages capture state): at every
    ``every_n``-th step a trace starts into
    ``<logdir>/step_<step>``, and after ``capture_steps`` more calls it
    stops — so a week-long run leaves a trail of small, per-window xprof
    captures instead of requiring a human to attach at the right moment.
    This is how the real-TPU ``overlap_comm`` A/B run collects its
    comm/compute-overlap evidence for free (ROADMAP).

    Profiler failures (already-active sessions, missing profiler plugin)
    are logged and disable the window rather than killing the run —
    telemetry must never take down training.  ``_profiler`` is
    injectable for tests.
    """

    def __init__(self, logdir: str, *, every_n: int = 100,
                 capture_steps: int = 3, enabled: bool = True,
                 _profiler=None):
        if every_n < 1 or capture_steps < 1:
            raise ValueError(
                f"every_n ({every_n}) and capture_steps ({capture_steps}) "
                "must be >= 1")
        self.logdir = logdir
        self.every_n = every_n
        self.capture_steps = capture_steps
        self.enabled = enabled
        self.windows_captured = 0
        self._active_until: Optional[int] = None
        self._profiler = _profiler if _profiler is not None else jax.profiler

    @property
    def active(self) -> bool:
        return self._active_until is not None

    def on_step(self, step: int) -> None:
        if not self.enabled:
            return
        if self._active_until is not None:
            if step >= self._active_until:
                self._stop()
            return
        if step % self.every_n == 0:
            path = os.path.join(self.logdir, f"step_{step:08d}")
            try:
                os.makedirs(path, exist_ok=True)
                self._profiler.start_trace(path)
            except Exception as e:  # profiler unavailable / double-start
                logger.warning(
                    "TraceWindow disabled: start_trace failed (%r)", e)
                self.enabled = False
                return
            self._active_until = step + self.capture_steps

    def _stop(self) -> None:
        try:
            self._profiler.stop_trace()
            self.windows_captured += 1
        except Exception as e:
            logger.warning("TraceWindow stop_trace failed (%r)", e)
            self.enabled = False
        self._active_until = None

    def close(self) -> None:
        """Stop any in-flight capture (call at shutdown so the last
        window is flushed rather than torn)."""
        if self._active_until is not None:
            self._stop()

    def __enter__(self) -> "TraceWindow":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
