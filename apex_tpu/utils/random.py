"""Model-parallel RNG policy.

Reference: Megatron's ``CudaRNGStatesTracker``
(``apex/transformer/tensor_parallel/random.py:124``) keeps named CUDA RNG
streams and forks a ``model-parallel-rng`` state seeded with
``seed + 2718 + tp_rank`` (``model_parallel_cuda_manual_seed``,
``random.py:204-236``) so that:

- tensor-parallel ranks get **different** dropout masks on sharded
  activations (each rank holds different neurons), but
- **the same** seed for operations on replicated activations.

JAX PRNG is functional — there are no global states to track, so the whole
tracker collapses to key derivation: :func:`model_parallel_rngs` returns a
``(replicated_key, model_parallel_key)`` pair where the model-parallel key is
``fold_in(key, MODEL_PARALLEL_OFFSET + axis_index(tp))``.  Inside
``shard_map`` the fold-in happens per shard; under plain pjit use
:func:`fold_in_axis` inside the partitioned function.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["RngPolicy", "model_parallel_rngs", "fold_in_axis"]

# Reference uses `seed + 2718` for the tensor-parallel stream offset
# (apex/transformer/tensor_parallel/random.py:219); we fold the same constant
# into the key for the analogous split.
_MODEL_PARALLEL_OFFSET = 2718
# Pipeline stages additionally offset by 100 * pp_rank in Megatron-LM
# conventions (the reference test harness seeds per-stage the same way).
_PIPELINE_OFFSET = 100


def fold_in_axis(key: jax.Array, axis_name: str, offset: int = 0) -> jax.Array:
    """Derive a per-rank key along a mesh axis (call inside shard_map/jit
    where ``axis_name`` is bound)."""
    return jax.random.fold_in(key, offset + lax.axis_index(axis_name))


def model_parallel_rngs(
    key: jax.Array, tp_axis: str = "tp", pp_axis: Optional[str] = None
) -> Tuple[jax.Array, jax.Array]:
    """Return ``(replicated_key, model_parallel_key)``.

    Analog of ``model_parallel_cuda_manual_seed``
    (``apex/transformer/tensor_parallel/random.py:204``): the replicated key
    is identical on all tp ranks (use for dropout on replicated activations);
    the model-parallel key differs per tp rank (use for dropout on sharded
    activations and per-rank init).  Must be called where ``tp_axis`` is bound.
    """
    mp_key = fold_in_axis(key, tp_axis, _MODEL_PARALLEL_OFFSET)
    if pp_axis is not None:
        key = fold_in_axis(key, pp_axis, _PIPELINE_OFFSET)
        mp_key = fold_in_axis(mp_key, pp_axis, _PIPELINE_OFFSET)
    return key, mp_key


@dataclasses.dataclass(frozen=True)
class RngPolicy:
    """Named-stream facade matching the tracker API shape.

    ``CudaRNGStatesTracker.add/fork`` (``random.py:141-199``) becomes pure
    key derivation: ``policy.key(name, step)`` is deterministic in
    (base_seed, name, step) and, for ``model_parallel=True`` streams,
    in the tp rank.
    """

    base_seed: int = 0
    tp_axis: str = "tp"

    def base_key(self) -> jax.Array:
        return jax.random.PRNGKey(self.base_seed)

    def key(self, name: str, step=0, *, model_parallel: bool = False) -> jax.Array:
        # crc32, not hash(): python string hashing is randomized per process,
        # which would give different keys on different hosts of a multi-host
        # run — silent divergence of replicated state.
        k = jax.random.fold_in(self.base_key(), zlib.crc32(name.encode()))
        k = jax.random.fold_in(k, step)
        if model_parallel:
            k = fold_in_axis(k, self.tp_axis, _MODEL_PARALLEL_OFFSET)
        return k
