"""Wall-clock timers — Megatron ``_Timers`` analog.

Reference: ``apex/transformer/pipeline_parallel/_timers.py:6-83`` — named
timers with ``torch.cuda.synchronize`` on start/stop, ``log`` printing and a
TensorBoard writer hook; accessor ``get_timers``
(``pipeline_parallel/utils.py:146-157``).

TPU version synchronizes via ``jax.block_until_ready`` on a token the caller
passes (or ``jax.effects_barrier``), and also exposes
``jax.profiler.TraceAnnotation`` context managers as the NVTX-range analog
(``apex/parallel/distributed.py:363`` ``nvtx.range_push``).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax

__all__ = ["Timers", "get_timers", "trace_annotation"]


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self, sync_on: Optional[jax.Array] = None):
        assert not self.started_, f"timer {self.name} already started"
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, sync_on: Optional[jax.Array] = None):
        assert self.started_, f"timer {self.name} not started"
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """Group of named timers (``_Timers`` ``_timers.py:40-83``)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True) -> str:
        names = names if names is not None else list(self.timers)
        parts = [
            f"{n}: {self.timers[n].elapsed(reset=reset) * 1000.0 / normalizer:.2f}ms"
            for n in names
            if n in self.timers
        ]
        line = "time (ms) | " + " | ".join(parts)
        print(line, flush=True)
        return line

    def write(self, names, writer, iteration: int, normalizer: float = 1.0,
              reset: bool = False) -> None:
        """Export timer values (reference ``_Timers.write``
        ``pipeline_parallel/_timers.py:69-77``, which targets a
        TensorBoard ``SummaryWriter``).

        ``writer`` is duck-typed: anything with ``add_scalar(tag, value,
        step)`` (TensorBoard-compatible), or a file path — then one JSON
        line ``{"iteration", "timers": {name: seconds}}`` is appended (no
        TB dependency in this image; the JSONL is trivially convertible).
        """
        values = {n: self.timers[n].elapsed(reset=reset) / normalizer
                  for n in names if n in self.timers}
        if hasattr(writer, "add_scalar"):
            for name, value in values.items():
                writer.add_scalar(f"timers/{name}", value, iteration)
        else:
            import json

            with open(writer, "a") as f:
                f.write(json.dumps({"iteration": iteration,
                                    "timers": values}) + "\n")


_GLOBAL_TIMERS: Optional[Timers] = None


def get_timers() -> Timers:
    """Accessor analog of ``pipeline_parallel/utils.py:146-157``."""
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def trace_annotation(name: str):
    """Profiler range context — the NVTX ``range_push/pop`` analog."""
    return jax.profiler.TraceAnnotation(name)
