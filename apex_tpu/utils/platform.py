"""Backend-availability probing for driver/bench entry points.

The sandbox's sitecustomize can force an experimental TPU PJRT plugin whose
backend init either *errors* ("Unable to initialize backend") or *wedges*
indefinitely.  Probing in a subprocess with a timeout catches both without
poisoning the caller's process (backend init is once-per-process), so the
caller can pin ``JAX_PLATFORMS=cpu`` and continue.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time
from typing import Callable, Optional

__all__ = ["force_host_device_count", "pin_cpu", "probe_default_platform",
           "resolve_platform"]


def force_host_device_count(n: int) -> None:
    """Set (or raise to ``n``) ``--xla_force_host_platform_device_count``.

    Only effective before this process initializes a JAX backend.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    pat = r"--xla_force_host_platform_device_count=(\d+)"
    m = re.search(pat, flags)
    if m:
        if int(m.group(1)) < n:
            flags = re.sub(
                pat, f"--xla_force_host_platform_device_count={n}", flags)
            os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def pin_cpu() -> None:
    """Pin the CPU platform (env + config) before backend init; harmless
    after (``jax.devices("cpu")`` keeps working either way)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend may already be initialized


def probe_default_platform(
    max_tries: int = 1,
    timeout: float = 150.0,
    sleep_s: float = 10.0,
    log: Optional[Callable[[str], None]] = None,
) -> Optional[str]:
    """Return the default JAX platform name ("tpu", "cpu", ...) if its
    backend initializes cleanly in a fresh subprocess, else ``None``."""
    for i in range(max_tries):
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=timeout, capture_output=True,
            )
            if proc.returncode == 0:
                out = proc.stdout.decode().strip().splitlines()
                if out:
                    return out[-1]
            elif log:
                log("probe rc=%d: %s" % (
                    proc.returncode,
                    proc.stderr.decode(errors="replace")[-500:]))
        except Exception as e:  # TimeoutExpired = wedged plugin
            if log:
                log(f"probe attempt {i + 1} raised {e!r}")
        if i + 1 < max_tries:
            time.sleep(sleep_s)
    return None


def resolve_platform(
    max_tries: int = 1,
    timeout: float = 150.0,
    log: Optional[Callable[[str], None]] = None,
) -> str:
    """The full fallback policy shared by the driver/bench entry points:
    honor an explicit CPU pin, otherwise probe the default backend and
    return its platform, degrading to "cpu" (without pinning — callers pin
    or set child env as appropriate) when it errors or wedges."""
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return "cpu"
    platform = probe_default_platform(max_tries=max_tries, timeout=timeout,
                                      log=log)
    if platform is None:
        if log:
            log("default backend unusable; falling back to cpu")
        return "cpu"
    return platform
