"""Flatten/unflatten dense tensor lists — the ``apex_C`` analog.

Behavioral spec: ``csrc/flatten_unflatten.cpp:15-17`` (pybind'd
``flatten``/``unflatten`` over torch ``_flatten_dense_tensors``) — the one
native extension every apex install builds (``setup.py:118``).

TPU-first split of responsibilities: on-device flattening is XLA's job
(donated buffers, fused reshapes — ``utils/tree.py``), so the native path
here serves the *host* side: assembling/splitting contiguous checkpoint
and host-transfer buffers.  The C kernel (``_native/flatcopy.c``,
OpenMP-parallel memcpy) is compiled on first use with the system
toolchain and loaded via ctypes; a pure-numpy path keeps the API working
when no compiler is available.

Measured honesty note: unlike the CUDA side the reference accelerates,
host numpy slicing is already memcpy-speed, so the native kernel only
*ties* numpy on large buffers and loses on many tiny tensors (ctypes
pointer-array setup dominates).  Routing therefore picks numpy for
many-small-tensor trees and the native kernel for few-large-buffer
gathers; the extension otherwise exists for apex_C API parity and as the
build scaffolding for future native host components.
"""

from __future__ import annotations

import ctypes
import threading
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["flatten_dense_tensors", "unflatten_dense_tensors",
           "native_available"]

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:  # lock-free fast path for the hot helpers
        return _LIB
    with _LOCK:
        if _TRIED:
            return _LIB
        try:
            from apex_tpu._native.build import build_and_load

            lib = build_and_load("flatcopy.c", "libflatcopy.so",
                                 ["-fopenmp"])
            if lib is not None:
                # inside the except: a loaded .so missing the expected
                # symbols (stale artifact) must also fall back to numpy
                lib.flat_gather.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
                lib.flat_scatter.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        except Exception:
            lib = None
        _LIB = lib
        _TRIED = True
        return _LIB


def native_available() -> bool:
    return _build_and_load() is not None


def flatten_dense_tensors(tensors: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate 1D-raveled host arrays into one contiguous buffer
    (``apex_C.flatten``).  All inputs must share a dtype."""
    arrs = [np.ascontiguousarray(t) for t in tensors]
    if not arrs:
        return np.empty((0,), np.float32)
    dtype = arrs[0].dtype
    if any(a.dtype != dtype for a in arrs):
        raise ValueError("flatten_dense_tensors requires a uniform dtype")
    total = sum(a.size for a in arrs)
    out = np.empty((total,), dtype)
    lib = _build_and_load()
    if lib is None or len(arrs) > 64:  # pointer-array setup dominates
        off = 0
        for a in arrs:
            out[off:off + a.size] = a.ravel()
            off += a.size
        return out
    n = len(arrs)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrs])
    lib.flat_gather(ctypes.c_void_p(out.ctypes.data), srcs, sizes, n)
    return out


def unflatten_dense_tensors(flat: np.ndarray,
                            like: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Split a flat buffer back into arrays shaped like ``like``
    (``apex_C.unflatten``)."""
    flat = np.ascontiguousarray(flat)
    total = sum(int(np.prod(t.shape)) for t in like)
    if flat.size != total:
        raise ValueError(
            f"flat buffer has {flat.size} elements, templates need {total}")
    outs = [np.empty(t.shape, flat.dtype) for t in like]
    lib = _build_and_load()
    if lib is None or len(outs) > 64:  # pointer-array setup dominates
        off = 0
        for o in outs:
            o.ravel()[:] = flat[off:off + o.size]
            off += o.size
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    sizes = (ctypes.c_int64 * n)(*[o.nbytes for o in outs])
    lib.flat_scatter(ctypes.c_void_p(flat.ctypes.data), dsts, sizes, n)
    return outs
