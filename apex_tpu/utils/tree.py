"""Pytree flatten/unflatten and norm helpers — the ``apex_C`` +
``multi_tensor_l2norm`` analog.

Reference: ``apex_C.flatten/unflatten`` (``csrc/flatten_unflatten.cpp:15-17``)
pack a tensor list into one contiguous buffer for bucketed NCCL all-reduce;
``amp_C.multi_tensor_l2norm`` (``csrc/multi_tensor_l2norm_kernel.cu``)
computes global and per-tensor L2 norms in one launch.

On TPU, XLA already fuses per-leaf elementwise work, so flattening is only
needed when an algorithm genuinely wants one buffer (ZeRO bucket sharding,
Pallas multi-tensor kernels).  These helpers provide it with static metadata
so the round-trip stays jit-compatible.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "flatten_to_buffer",
    "unflatten_from_buffer",
    "chunked_meta",
    "flatten_to_chunked",
    "unflatten_from_chunked",
    "chunked_per_leaf_max_abs",
    "chunked_per_leaf_sumsq",
    "tree_l2_norm",
    "per_leaf_l2_norms",
    "tree_size",
]


class _FlatMeta(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]  # element offsets into the flat buffer
    total: int
    pad_to: int


def flatten_to_buffer(
    tree, dtype=None, pad_to: int = 1
) -> Tuple[jnp.ndarray, _FlatMeta]:
    """Concatenate all leaves into one 1-D buffer (+ static metadata).

    ``pad_to`` rounds the total length up (ZeRO bucketing wants shard-divisible
    buffers, cf. fixed-size buckets in
    ``apex/contrib/optimizers/distributed_fused_adam.py:397``).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(np.shape(x)) for x in leaves)
    dtypes = tuple(jnp.asarray(x).dtype for x in leaves)
    if dtype is None and len(set(dtypes)) > 1:
        raise ValueError(
            "flatten_to_buffer on a mixed-dtype tree requires an explicit "
            f"dtype= (got leaf dtypes {sorted({str(d) for d in dtypes})}); "
            "an implicit cast would silently lose precision on the round-trip"
        )
    sizes = [int(np.prod(s)) for s in shapes]  # np.prod(()) == 1 for scalars
    offsets = tuple(int(x) for x in np.cumsum([0] + sizes[:-1]))
    total = int(sum(sizes))
    padded = ((total + pad_to - 1) // pad_to) * pad_to if total else pad_to
    out_dtype = dtype or (dtypes[0] if dtypes else jnp.float32)
    if leaves:
        flat = jnp.concatenate(
            [jnp.ravel(jnp.asarray(x, out_dtype)) for x in leaves]
        )
        if padded != total:
            flat = jnp.pad(flat, (0, padded - total))
    else:
        flat = jnp.zeros((padded,), out_dtype)
    meta = _FlatMeta(
        treedef=treedef,
        shapes=shapes,
        dtypes=dtypes,
        offsets=offsets,
        total=total,
        pad_to=padded,
    )
    return flat, meta


def unflatten_from_buffer(buf: jnp.ndarray, meta: _FlatMeta):
    """Inverse of :func:`flatten_to_buffer` (``apex_C.unflatten`` analog),
    restoring original shapes and dtypes."""
    leaves = []
    for shape, dt, off in zip(meta.shapes, meta.dtypes, meta.offsets):
        size = int(np.prod(shape))
        chunk = jax.lax.dynamic_slice_in_dim(buf, off, size)
        leaves.append(jnp.asarray(chunk.reshape(shape), dt))
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


class _ChunkMeta(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    row_offsets: Tuple[int, ...]   # first (T, chunk)-row of each leaf
    n_rows: int
    chunk: int
    leaf_ids: Any                  # np.int32 (n_rows,): row -> leaf index


def flatten_to_chunked(
    tree, chunk: int = 256, dtype=jnp.float32, pad_rows_to: int = 1
) -> Tuple[jnp.ndarray, _ChunkMeta]:
    """Pack all leaves into one 2-D ``(rows, chunk)`` buffer, each leaf
    padded (with zeros) to a whole number of rows so **no row spans two
    leaves** — the TPU-shaped ``multi_tensor_apply`` workspace
    (``csrc/multi_tensor_apply.cuh``'s chunking, minus the 320-tensor
    launch caps, which XLA has no analog of).

    With leaf boundaries row-aligned, per-tensor reductions become a cheap
    two-stage pass — a vectorized row reduction (VPU-friendly, lane
    dimension = ``chunk``) followed by a ``segment_sum`` over ``rows``
    scalars (see :func:`chunked_per_leaf_sumsq`) — and per-tensor scalars
    broadcast back as a ``(rows, 1)`` column, never a gather over
    elements.  ``meta.leaf_ids`` is a host-side ``np.int32`` constant of
    one entry per row (~4 bytes per 1 KiB of fp32 state).

    ``pad_rows_to`` rounds the row count up to a multiple (ZeRO flat
    buckets want shard- and bucket-divisible row counts, the TPU shape of
    ``distributed_fused_adam.py:397``'s fixed-size StateBuckets).  Pad
    rows hold zeros and carry the last leaf's id, so the segmented
    reductions stay exact (zero contributes nothing to a sum, and
    ``max|x|`` is already clamped at 0)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(np.shape(x)) for x in leaves)
    dtypes = tuple(jnp.asarray(x).dtype for x in leaves)
    meta = chunked_meta(treedef, shapes, dtypes, chunk=chunk,
                        pad_rows_to=pad_rows_to)
    if leaves:
        sizes = [int(np.prod(s)) for s in shapes]
        rows_per_leaf = [(s + chunk - 1) // chunk for s in sizes]
        parts = []
        for x, size, rows in zip(leaves, sizes, rows_per_leaf):
            flat = jnp.ravel(jnp.asarray(x, dtype))
            pad = rows * chunk - size
            if pad:
                flat = jnp.pad(flat, (0, pad))
            parts.append(flat)
        pad_rows = meta.n_rows - int(sum(rows_per_leaf))
        if pad_rows:
            parts.append(jnp.zeros((pad_rows * chunk,), dtype))
        buf = jnp.concatenate(parts).reshape(max(meta.n_rows, 1), chunk) \
            if meta.n_rows else jnp.zeros((0, chunk), dtype)
    else:
        buf = jnp.zeros((0, chunk), dtype)
    return buf, meta


def chunked_meta(treedef, shapes, dtypes, chunk: int = 256,
                 pad_rows_to: int = 1) -> _ChunkMeta:
    """Metadata-only half of :func:`flatten_to_chunked`: pure host math
    from static shapes/dtypes, no arrays touched.  Lets layout planners
    (ZeRO bucketing, checkpoint re-sharding) size buffers and build
    segment ids without tracing a flatten they would throw away."""
    sizes = [int(np.prod(s)) for s in shapes]
    rows_per_leaf = [(s + chunk - 1) // chunk for s in sizes]
    row_offsets = tuple(int(x) for x in np.cumsum([0] + rows_per_leaf[:-1]))
    n_rows = int(sum(rows_per_leaf))
    pad_rows = 0
    if pad_rows_to > 1 and shapes:
        pad_rows = -(-max(n_rows, 1) // pad_rows_to) * pad_rows_to - n_rows
    leaf_ids = np.repeat(
        np.arange(len(shapes), dtype=np.int32), rows_per_leaf)
    if pad_rows:
        leaf_ids = np.concatenate(
            [leaf_ids,
             np.full(pad_rows, max(len(shapes) - 1, 0), np.int32)])
    return _ChunkMeta(treedef=treedef, shapes=tuple(shapes),
                      dtypes=tuple(dtypes), row_offsets=row_offsets,
                      n_rows=n_rows + pad_rows, chunk=chunk,
                      leaf_ids=leaf_ids)


def unflatten_from_chunked(buf: jnp.ndarray, meta: _ChunkMeta):
    """Inverse of :func:`flatten_to_chunked`: slice each leaf's rows back
    out, drop its padding tail, restore shape and dtype."""
    flat = buf.reshape(-1)
    leaves = []
    for shape, dt, row_off in zip(meta.shapes, meta.dtypes,
                                  meta.row_offsets):
        size = int(np.prod(shape))
        if size == 0:
            # a zero-size leaf occupies no rows; slicing even one element
            # would step past a buffer that may itself be empty
            leaves.append(jnp.zeros(shape, dt))
            continue
        chunk = jax.lax.dynamic_slice_in_dim(flat, row_off * meta.chunk,
                                             size)
        leaves.append(jnp.asarray(chunk.reshape(shape), dt))
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def chunked_per_leaf_sumsq(buf: jnp.ndarray, meta: _ChunkMeta) -> jnp.ndarray:
    """Per-tensor sum-of-squares over a chunked buffer in two stages:
    row-reduce ``(rows, chunk) -> (rows,)`` then ``segment_sum`` the row
    partials by leaf — the ``multi_tensor_l2norm`` ``per_tensor=True``
    output (``csrc/multi_tensor_l2norm_kernel.cu:480-560``) computed with
    one large kernel instead of one small reduction per tensor.  Padding
    rows contribute exactly zero.  Returns fp32 ``(n_leaves,)``."""
    row_sq = jnp.sum(jnp.square(buf.astype(jnp.float32)), axis=1)
    # leaf_ids is non-decreasing by construction (rows are emitted leaf by
    # leaf), so the segment reduction lowers to contiguous slices instead
    # of a scatter — this is the optimizer hot path.
    return jax.ops.segment_sum(
        row_sq, jnp.asarray(meta.leaf_ids),
        num_segments=len(meta.shapes), indices_are_sorted=True)


def chunked_per_leaf_max_abs(buf: jnp.ndarray, meta: _ChunkMeta
                             ) -> jnp.ndarray:
    """Per-tensor Linf norm over a chunked buffer (row-reduce max|x| then
    ``segment_max`` — the ``multi_tensor_l2norm_kernel`` Linf mode).
    Padding zeros can only lower nothing: max|x| >= 0 exactly like the
    unpadded leaf (and a zero-size leaf reports 0).  Returns fp32
    ``(n_leaves,)``."""
    row_max = jnp.max(jnp.abs(buf.astype(jnp.float32)), axis=1)
    out = jax.ops.segment_max(
        row_max, jnp.asarray(meta.leaf_ids),
        num_segments=len(meta.shapes), indices_are_sorted=True)
    # segment_max fills empty segments with -inf; zero-size leaves have no
    # rows, and |x| >= 0 everywhere, so clamp to 0
    return jnp.maximum(out, 0.0)


def per_leaf_l2_norms(tree) -> List[jnp.ndarray]:
    """Per-tensor L2 norms in fp32 (``multi_tensor_l2norm`` with
    ``per_tensor=True``, ``csrc/multi_tensor_l2norm_kernel.cu:480-560``)."""
    return [
        jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(x, jnp.float32))))
        for x in jax.tree_util.tree_leaves(tree)
    ]


def tree_l2_norm(tree) -> jnp.ndarray:
    """Global L2 norm over a pytree in fp32 — one fused reduction
    (``multi_tensor_l2norm`` global output)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0)
    sq = [jnp.sum(jnp.square(jnp.asarray(x, jnp.float32))) for x in leaves]
    return jnp.sqrt(jnp.sum(jnp.stack(sq)))


def tree_size(tree) -> int:
    """Total element count of a pytree (host-side, static).

    Consistent with :func:`flatten_to_buffer`'s un-padded total, including
    zero-element leaves (``np.prod(()) == 1`` covers scalars)."""
    return int(sum(np.prod(np.shape(x)) for x in jax.tree_util.tree_leaves(tree)))
