"""apex_tpu.utils — RNG policy, tree/flatten helpers, timers, logging."""

from apex_tpu.utils.random import (  # noqa: F401
    RngPolicy,
    model_parallel_rngs,
    fold_in_axis,
)
from apex_tpu.utils.tree import (  # noqa: F401
    chunked_per_leaf_max_abs,
    chunked_per_leaf_sumsq,
    flatten_to_buffer,
    flatten_to_chunked,
    unflatten_from_buffer,
    unflatten_from_chunked,
    tree_l2_norm,
    per_leaf_l2_norms,
    tree_size,
)
from apex_tpu.utils.timers import Timers, get_timers  # noqa: F401
