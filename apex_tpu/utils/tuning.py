"""Hardware-gated tuned-default records (the sweep auto-land protocol).

A hardware sweep (``examples/tune_flash_blocks.py``,
``examples/tune_gpt_batch.py``) writes its winner to a small json under
``bench_results/``; consumers adopt it lazily at first use and ONLY when
the record's ``device_kind`` matches the attached TPU — a winner swept
on one TPU generation must not leak onto another with a different
VMEM/HBM budget.  Env knobs always take precedence at the call sites.
"""

from __future__ import annotations

import json
import os
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_tuned_record(filename: str, jax) -> Optional[dict]:
    """The parsed ``bench_results/<filename>`` record iff the attached
    device is a TPU whose ``device_kind`` matches; else None.  Any read/
    parse problem degrades to None (shipped defaults win)."""
    try:
        with open(os.path.join(_REPO, "bench_results", filename)) as f:
            rec = json.load(f)
        dev = jax.devices()[0]
        if (dev.platform == "tpu"
                and rec.get("device_kind")
                and rec["device_kind"] == getattr(dev, "device_kind", None)):
            return rec
    except Exception:
        pass
    return None
