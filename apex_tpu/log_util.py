"""Per-rank logging accessors — ``apex/transformer/log_util.py:5-18``
parity (``get_transformer_logger``, ``set_logging_level``), with the
handler-level propagation fix (``tests/test_log_util.py``).

The rank-stamped root handler itself lives in ``apex_tpu/__init__.py``
(``RankInfoFormatter`` — the ``apex/__init__.py:31-43`` analog, with
backend-init-safe rank lookup); this module only exposes the reference's
accessor surface, so importing it never adds a second handler.
"""

from __future__ import annotations

import logging
import os

__all__ = ["get_logger", "get_transformer_logger", "set_logging_level"]


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    """The library logger (children inherit the rank-stamped handler)."""
    import apex_tpu  # ensures the handler is installed

    del apex_tpu
    return logging.getLogger(name)


def get_transformer_logger(name: str) -> logging.Logger:
    """Reference ``get_transformer_logger`` — pass ``__name__`` (or a
    filename; the extension is stripped)."""
    base = os.path.splitext(name)[0]
    if not base.startswith("apex_tpu"):
        base = f"apex_tpu.{base}"
    return get_logger(base)


def set_logging_level(verbosity) -> None:
    """Reference ``set_logging_level`` (``log_util.py:12-18``), fixed to
    also set the **handler** level: the rank-stamped ``StreamHandler``
    installed by ``apex_tpu/__init__.py`` is the single emission point
    for the whole ``apex_tpu.*`` tree, and a handler left at a higher
    level than the logger silently filters records a child logger was
    explicitly configured to emit (set the library to INFO, set one
    child to DEBUG while debugging it — the child's DEBUG records must
    actually print).  Handlers therefore follow the logger DOWN and are
    reset to NOTSET (pass-through) when the logger is *loosened*, so the
    logger level remains the one knob (``tests/test_log_util.py``)."""
    logger = get_logger()
    logger.setLevel(verbosity)
    # Resolve "DEBUG"/10/logging.DEBUG uniformly for the comparison.
    resolved = logger.getEffectiveLevel()
    for handler in logger.handlers:
        if handler.level > resolved:
            # Tightening the logger: the handler must not keep filtering
            # below the old threshold...
            handler.setLevel(logging.NOTSET)
        # ...and a handler at/below the logger level already passes
        # everything the logger does (incl. louder child loggers).
