"""FusedAdagrad — the ``multi_tensor_adagrad`` analog.

Behavioral spec: ``apex/optimizers/fused_adagrad.py:44`` over
``csrc/multi_tensor_adagrad.cu:64-72``:

- ``ADAGRAD_MODE_0`` (L2, default): ``g += wd*p; h += g²;
  p -= lr * g/(√h + eps)``.
- ``adagrad_w_mode=True``: ``h += g²; p -= lr*(g/(√h+eps) + wd*p)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_multi,
    tree_zeros_f32,
)

__all__ = ["FusedAdagrad"]


class FusedAdagrad:
    def __init__(
        self,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        master_weights: bool = False,
    ):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.master_weights = master_weights

    def init(self, params) -> OptState:
        return OptState(
            step=jnp.int32(0),
            slots={"sum": tree_zeros_f32(params)},
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        wd, eps = self.weight_decay, self.eps
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)

        def leaf(p, g, h):
            if not self.adagrad_w_mode and wd != 0.0:
                g = g + wd * p
            h = h + g * g
            update = g / (jnp.sqrt(h) + eps)
            if self.adagrad_w_mode and wd != 0.0:
                update = update + wd * p
            return p - lr * update, h

        new_p32, new_h = tree_map_multi(leaf, 2, p32, g, state.slots["sum"])
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_h = apply_skip(skip_update, new_h, state.slots["sum"])

        new_params = finalize_params(new_p32, params, self.master_weights)
        return new_params, OptState(
            step=advance_step(state.step, skip_update),
            slots={"sum": new_h},
            master=new_p32 if self.master_weights else None,
        )
