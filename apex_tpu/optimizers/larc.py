"""LARC — layerwise adaptive rate control as a gradient transformation.

Behavioral spec: ``apex/parallel/LARC.py:5-107``.  The reference wraps an
optimizer and, in ``step``, mutates every grad:

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps)
    if clip: adaptive_lr = min(adaptive_lr / lr, 1)
    g = (g + wd*p) * adaptive_lr            # (LARC.py:92-102)

absorbing the wrapped optimizer's weight decay (zeroing it for the inner
step, ``LARC.py:81-85``).  Functionally that is a grad transform applied
before any optimizer's ``step`` — which is how it is expressed here::

    larc = LARC(trust_coefficient=0.02, clip=True, weight_decay=wd)
    grads = larc.transform_grads(grads, params, lr=lr)
    params, opt_state = opt.step(grads, opt_state, params, lr=lr)
    # construct the inner optimizer with weight_decay=0

There is also a :class:`LARC`-as-wrapper convenience matching the reference
constructor shape for drop-in migration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import f32, tree_map_multi
from apex_tpu.utils.tree import (
    chunked_per_leaf_sumsq,
    flatten_to_chunked,
    unflatten_from_chunked,
)

__all__ = ["LARC"]


class LARC:
    def __init__(
        self,
        optimizer=None,
        trust_coefficient: float = 0.02,
        clip: bool = True,
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        flat: bool = True,
    ):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        # flat=True computes all per-tensor ||p||/||g|| pairs with one
        # segmented reduction over a chunked buffer instead of two small
        # reductions per tensor (the multi_tensor_l2norm shape);
        # flat=False keeps the per-leaf form for A/B.
        self.flat = flat
        # reference absorbs wd from the wrapped optimizer (LARC.py:81-85);
        # here the inner optimizer must be built with weight_decay=0 and the
        # decay given to LARC directly.
        self.weight_decay = weight_decay
        if optimizer is not None and getattr(optimizer, "weight_decay", 0.0):
            self.weight_decay = optimizer.weight_decay
            optimizer.weight_decay = 0.0

    def transform_grads(self, grads, params, *, lr):
        """Scale each grad leaf by its LARC adaptive rate (LARC.py:92-102)."""
        lr = f32(lr)
        wd, eps, tc = self.weight_decay, self.eps, self.trust_coefficient

        if self.flat:
            pb, meta = flatten_to_chunked(params)
            gb, _ = flatten_to_chunked(grads)
            p_norm = jnp.sqrt(chunked_per_leaf_sumsq(pb, meta))
            g_norm = jnp.sqrt(chunked_per_leaf_sumsq(gb, meta))
            adaptive = tc * p_norm / (g_norm + p_norm * wd + eps)
            if self.clip:
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            # when either norm is zero the reference leaves the grad
            # untouched (no wd either), LARC.py:92
            keep = (p_norm != 0) & (g_norm != 0)
            ids = jnp.asarray(meta.leaf_ids)
            out = jnp.where(keep[ids][:, None],
                            (gb + wd * pb) * adaptive[ids][:, None], gb)
            # the per-leaf form returns fp32 grads whatever the input
            # dtype (the math runs in the fp32 workspace); match it
            f32_meta = meta._replace(
                dtypes=tuple(jnp.float32 for _ in meta.dtypes))
            return unflatten_from_chunked(out, f32_meta)

        def leaf(g, p):
            g0 = jnp.asarray(g, jnp.float32)
            p = jnp.asarray(p, jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g0)))
            adaptive = tc * p_norm / (g_norm + p_norm * wd + eps)
            if self.clip:
                adaptive = jnp.minimum(adaptive / lr, 1.0)
            # when either norm is zero the reference leaves the grad
            # untouched (no wd either), LARC.py:92
            g_out = jnp.where(
                (p_norm != 0) & (g_norm != 0), (g0 + wd * p) * adaptive, g0
            )
            return (g_out,)

        (out,) = tree_map_multi(leaf, 1, grads, params)
        return out

    # -- wrapper-style API (reference constructor shape) -------------------
    def init(self, params):
        assert self.optim is not None, "LARC used as wrapper needs an optimizer"
        return self.optim.init(params)

    def step(self, grads, state, params, *, lr=None, grad_scale=None, **kw):
        assert self.optim is not None, "LARC used as wrapper needs an optimizer"
        eff_lr = self.optim.lr if lr is None else lr
        if grad_scale is not None:
            # unscale BEFORE computing LARC norms — adaptive rates on
            # loss-scaled grads would collapse toward zero and wd would be
            # divided by the scale; the inner step gets already-unscaled grads
            inv = 1.0 / jnp.asarray(grad_scale, jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g: jnp.asarray(g, jnp.float32) * inv, grads
            )
        grads = self.transform_grads(grads, params, lr=eff_lr)
        return self.optim.step(grads, state, params, lr=lr, **kw)
