"""Shared machinery for the fused optimizer family.

The reference's optimizers are one CUDA ``multi_tensor_apply`` launch per
(dtype-group, op) — chunked kernels over tensor lists
(``csrc/multi_tensor_apply.cuh:16-33``, dispatcher
``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``) — because thousands
of separate small CUDA kernels would be launch-bound.  Under XLA all leaf
updates compile into one executable, so the *mechanism* dissolves; what we
keep is the *semantics*:

- update math in fp32 regardless of storage dtype (every functor casts to
  ``MATH_T=float``, e.g. ``csrc/multi_tensor_adam.cu:64-87``);
- optional fp32 master params carried in optimizer state
  (``FusedAdam(master_weights=True)``, ``apex/optimizers/fused_adam.py:71``);
- gradient unscaling folded into the update (``scale`` argument of
  ``FusedSGD.step`` / ``multi_tensor_adam``'s ``div_scale``);
- overflow skip as predication rather than a host branch (the ``noop_flag``
  short-circuit in every kernel).

Every optimizer here follows the same protocol::

    opt   = FusedFoo(lr=..., ...)
    state = opt.init(params)
    params, state = opt.step(grads, state, params,
                             lr=None,          # per-step override (schedules)
                             grad_scale=None,  # divide grads by this (loss scale)
                             skip_update=None) # bool scalar: keep old state/params

``step`` is pure — jit it (donating ``state``/``params``) at the call site,
or use :func:`apex_tpu.optimizers.fused_step` which does so with donation.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "f32",
    "adam_apply",
    "tree_f32",
    "tree_zeros_f32",
    "advance_step",
    "cast_like",
    "apply_skip",
    "resolve_master",
    "finalize_params",
    "tree_map_multi",
    "OptState",
]

Pytree = Any


def f32(x):
    return jnp.asarray(x, jnp.float32)


def adam_apply(p, g, m, v, *, lr, b1, b2, eps, wd, bc1, bc2, adam_w_mode):
    """One Adam/AdamW update on fp32 values — the elementwise core of
    ``csrc/multi_tensor_adam.cu:64-87`` (``ADAM_MODE_0`` folds ``wd*p``
    into the grad, ``ADAM_MODE_1`` decouples the decay into the update).
    Shape-agnostic: the fused optimizer maps it over leaves or chunked
    buffers, the ZeRO-sharded ones over per-leaf chunks or flat-bucket
    shards — one definition of the math, four call shapes."""
    if not adam_w_mode and wd != 0.0:
        g = g + wd * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and wd != 0.0:
        update = update + wd * p
    return p - lr * update, m, v


def tree_f32(tree):
    """fp32 master copy of ``params``.

    Always copies — even fp32 leaves — so the master state never aliases the
    model params' buffers (aliasing breaks ``donate_argnums`` train steps
    with "attempt to donate the same buffer twice").
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.array(x, dtype=jnp.float32, copy=True), tree
    )


def tree_zeros_f32(params):
    """fp32 zero slots shaped like ``params`` (optimizer state init)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.float32), params
    )


def advance_step(step, skip_update):
    """Advance the step counter unless the update is skipped — the reference
    predicates the counter on the overflow flag
    (``apex/optimizers/fused_adam.py:152``: ``group['step'] +=
    (self._dummy_overflow_buf != 1)``), keeping bias corrections aligned with
    the number of *applied* updates."""
    if skip_update is None:
        return step + 1
    return step + jnp.where(jnp.asarray(skip_update), 0, 1)


def cast_like(new, ref):
    """Cast ``new`` leaves to the dtypes of ``ref`` leaves."""
    return jax.tree_util.tree_map(
        lambda n, r: jnp.asarray(n, jnp.asarray(r).dtype), new, ref
    )


def apply_skip(skip_update, new_tree, old_tree):
    """Predicated state/param update: where ``skip_update`` is True keep the
    old values (the kernels' ``noop_flag`` early-out; the amp skip-step
    ``apex/amp/handle.py:128-154``)."""
    if skip_update is None:
        return new_tree
    keep_old = jnp.asarray(skip_update)
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(keep_old, o, n), new_tree, old_tree
    )


def scale_grads(grads, grad_scale):
    """Fold loss-scale division into the update (``div_scale`` arg of
    ``multi_tensor_adam_capturable``; ``scale`` of ``FusedSGD.step``)."""
    if grad_scale is None:
        return tree_f32(grads)
    inv = 1.0 / f32(grad_scale)
    return jax.tree_util.tree_map(lambda g: f32(g) * inv, grads)


def resolve_master(params, state_master, use_master: bool):
    """Pick the fp32 tree the update math runs on."""
    if use_master:
        return state_master
    return tree_f32(params)


def finalize_params(params_f32_new, model_params, use_master: bool):
    """Derive the model-dtype params from the stepped fp32 tree
    (``_master_params_to_model_params``, ``apex/amp/_process_optimizer.py:14``)."""
    return cast_like(params_f32_new, model_params)


def tree_map_multi(fn: Callable, n_out: int, *trees) -> Tuple[Pytree, ...]:
    """Map ``fn`` (returning an ``n_out``-tuple) over leaves of ``trees``,
    returning ``n_out`` trees.  Robust against tuple-valued leaves (unlike
    post-hoc unzipping with ``is_leaf=tuple``)."""
    leaves0, treedef = jax.tree_util.tree_flatten(trees[0])
    rest = [treedef.flatten_up_to(t) for t in trees[1:]]
    results = [fn(*args) for args in zip(leaves0, *rest)]
    return tuple(
        treedef.unflatten([r[i] for r in results]) for i in range(n_out)
    )


def tree_map_flat(fn: Callable, n_out: int, *trees) -> Tuple[Pytree, ...]:
    """Like :func:`tree_map_multi` for a purely **elementwise** ``fn``,
    but applied once over one chunked ``(rows, 256)`` buffer per tree —
    the ``multi_tensor_apply`` list-kernel shape (one wide kernel per op
    instead of one small kernel per tensor; ``csrc/multi_tensor_apply.cuh``).
    Elementwise means no reductions, so the result matches the per-leaf
    map to compiler instruction-fusion (fma) noise, ~1 ulp; outputs take
    the FIRST tree's structure/dtypes (inputs are cast to its fp32
    workspace).  For updates that also need
    per-tensor reductions, see ``FusedLAMB._flat_update``."""
    from apex_tpu.utils.tree import (
        flatten_to_chunked,
        unflatten_from_chunked,
    )

    bufs, meta = [], None
    for t in trees:
        b, m = flatten_to_chunked(t)
        if meta is None:
            meta = m
        bufs.append(b)
    outs = fn(*bufs)
    if n_out == 1:
        outs = (outs,)
    return tuple(unflatten_from_chunked(o, meta) for o in outs)


class OptState(NamedTuple):
    """Generic optimizer state: a step counter, named slot trees, and the
    optional fp32 master params."""

    step: jnp.ndarray
    slots: Any  # dict name -> pytree (same structure as params)
    master: Optional[Any]  # fp32 params pytree or None
