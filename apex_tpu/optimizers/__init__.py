"""apex_tpu.optimizers — the fused optimizer family.

TPU-native replacement for ``apex/optimizers`` (exports
``apex/optimizers/__init__.py:1-7``) plus ``apex/parallel/LARC.py`` and
``apex/contrib/clip_grad``.  Each optimizer is a pure ``init``/``step`` pair
whose whole update compiles to one XLA executable — the fusion that
``multi_tensor_apply`` (``apex/multi_tensor_apply/multi_tensor_apply.py:3``)
achieves with chunked CUDA launches comes from jit + buffer donation here
(:func:`fused_step`).

Common ``step`` extras (all traced, none incur host syncs):
``lr=`` per-step override (schedule), ``grad_scale=`` folds loss-scale
division into the update, ``skip_update=`` predicates the whole step on an
overflow flag.
"""

import functools

import jax

from apex_tpu.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.optimizers.fused_lamb import (  # noqa: F401
    FusedLAMB,
    FusedMixedPrecisionLamb,
)
from apex_tpu.optimizers.fused_lion import FusedLion  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.larc import LARC  # noqa: F401
from apex_tpu.optimizers.clip_grad import (  # noqa: F401
    clip_grad_norm,
    global_grad_norm,
)

__all__ = [
    "FusedAdam",
    "FusedSGD",
    "FusedLAMB",
    "FusedMixedPrecisionLamb",
    "FusedLion",
    "FusedAdagrad",
    "FusedNovoGrad",
    "LARC",
    "clip_grad_norm",
    "global_grad_norm",
    "fused_step",
]


def fused_step(optimizer):
    """Jit an optimizer's ``step`` with state+params donation.

    Donation lets XLA update parameters and optimizer slots in place — the
    memory behavior of the reference's in-place multi-tensor kernels::

        step = fused_step(opt)
        params, state = step(grads, state, params)
    """

    @functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=())
    def _step(grads, state, params, lr=None, grad_scale=None, skip_update=None):
        return optimizer.step(
            grads,
            state,
            params,
            lr=lr,
            grad_scale=grad_scale,
            skip_update=skip_update,
        )

    return _step
