"""FusedSGD — the ``multi_tensor_sgd`` analog.

Behavioral spec: ``apex/optimizers/fused_sgd.py`` over
``csrc/multi_tensor_sgd_kernel.cu`` (``SGDFunctor:30``).  Parity points:

- momentum with dampening: ``buf = momentum*buf + (1-dampening)*g``; on the
  first momentum application ``buf = g`` (torch semantics the kernel's
  ``first_run`` flag reproduces, ``multi_tensor_sgd_kernel.cu:90-100``).
- ``nesterov``: effective grad ``g + momentum*buf``.
- ``wd_after_momentum`` flag — reference applies weight decay either to the
  incoming grad (default) or after the momentum update
  (``fused_sgd.py:77-86``, kernel ``:60-75``).
- ``scale`` argument folds loss-scale division into the update — the amp
  master-weights fast path (``materialize_master_grads``,
  ``apex/amp/_process_optimizer.py:258-311``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_multi,
    tree_zeros_f32,
)

__all__ = ["FusedSGD"]


class FusedSGD:
    def __init__(
        self,
        lr: float = 1e-3,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        master_weights: bool = False,
    ):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening "
                "(parity with torch/apex SGD)"
            )
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.master_weights = master_weights

    def init(self, params) -> OptState:
        slots = {}
        if self.momentum != 0.0:
            slots["momentum_buffer"] = tree_zeros_f32(params)
        return OptState(
            step=jnp.int32(0),
            slots=slots,
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        mom, damp, wd = self.momentum, self.dampening, self.weight_decay
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)
        # first momentum application uses buf = g (kernel first_run flag)
        first_run = state.step == 0

        if mom != 0.0:
            buf = state.slots["momentum_buffer"]

            def leaf(p, g, b):
                if wd != 0.0 and not self.wd_after_momentum:
                    g = g + wd * p
                b_new = jnp.where(first_run, g, mom * b + (1.0 - damp) * g)
                d = g + mom * b_new if self.nesterov else b_new
                if wd != 0.0 and self.wd_after_momentum:
                    d = d + wd * p
                return p - lr * d, b_new

            new_p32, new_buf = tree_map_multi(leaf, 2, p32, g, buf)
            new_buf = apply_skip(skip_update, new_buf, buf)
            new_slots = {"momentum_buffer": new_buf}
        else:

            def leaf(p, g):
                d = g + wd * p if wd != 0.0 else g
                return (p - lr * d,)

            (new_p32,) = tree_map_multi(leaf, 1, p32, g)
            new_slots = {}

        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_params = finalize_params(new_p32, params, self.master_weights)
        return new_params, OptState(
            step=advance_step(state.step, skip_update),
            slots=new_slots,
            master=new_p32 if self.master_weights else None,
        )
