"""FusedNovoGrad — the ``multi_tensor_novograd`` analog.

Behavioral spec: ``apex/optimizers/fused_novograd.py`` (ctor ``:69-77``,
``step`` ``:108-214``) over ``csrc/multi_tensor_novograd.cu``:

- per-tensor gradient-norm second moment, blended each step
  (``multi_tensor_norm_out_cuda`` call ``:164``):
  L2:  ``gn = sqrt(beta2*gn² + (1-beta2)*n²)``;
  Linf: ``gn = beta2*gn + (1-beta2)*n``.
- norm state init: first-step norm (blend is then a no-op) unless
  ``init_zero`` (``fused_novograd.py:160-180``).
- bias corrections ``bc1 = 1-beta1^t``, ``bc2 = sqrt(1-beta2^t)``
  (``multi_tensor_novograd.cu:147-151``).
- ``MOMENT_MODE_0`` (``reg_inside_moment=True``): regularize inside momentum:
  ``g' = g/(gn/bc2+eps) + wd*p; m = beta1*m + beta3*g'; p -= lr*(m/bc1)``
  (``:99-104``).
- ``MOMENT_MODE_1`` (default): decoupled:
  ``m = beta1*m + beta3*g; p -= lr*((m/bc1)/(gn/bc2+eps) + wd*p)``
  (``:107-112``).
- ``grad_averaging`` → ``beta3 = 1-beta1`` (``:156-158``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_multi,
    tree_zeros_f32,
)
from apex_tpu.utils.tree import (
    chunked_per_leaf_max_abs,
    chunked_per_leaf_sumsq,
    flatten_to_chunked,
    unflatten_from_chunked,
)

__all__ = ["FusedNovoGrad"]


class FusedNovoGrad:
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        reg_inside_moment: bool = False,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        master_weights: bool = False,
        flat: bool = True,
    ):
        if amsgrad:
            raise RuntimeError(
                "FusedNovoGrad does not support the AMSGrad variant "
                "(parity with apex/optimizers/fused_novograd.py:83)"
            )
        if norm_type not in (0, 2):
            raise RuntimeError(
                "FusedNovoGrad only supports l2 (2) / inf (0) norms "
                "(parity with fused_novograd.py:174)"
            )
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.moment_mode = 0 if reg_inside_moment else 1
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero
        self.master_weights = master_weights
        # flat=True: one chunked-buffer pass with segmented per-tensor
        # grad norms (multi_tensor_novograd's list-kernel shape) instead
        # of one small norm reduction per tensor; flat=False keeps the
        # per-leaf form for A/B.
        self.flat = flat

    def _leaf_norm(self, g):
        if self.norm_type == 0:
            return jnp.max(jnp.abs(g))
        return jnp.sqrt(jnp.sum(jnp.square(g)))

    def init(self, params) -> OptState:
        # exp_avg_sq (per-tensor norm) lazily initialized on first step when
        # init_zero=False; represented as -1 sentinel so the first step can
        # substitute the first-step norm (fused_novograd.py:166-180).
        norms = jax.tree_util.tree_map(
            lambda x: (
                jnp.float32(0.0) if self.init_zero else jnp.float32(-1.0)
            ),
            params,
        )
        return OptState(
            step=jnp.int32(0),
            slots={"exp_avg": tree_zeros_f32(params), "exp_avg_sq": norms},
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)

        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = jnp.sqrt(1.0 - b2 ** f32(t))
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, gn):
            n = self._leaf_norm(g)
            # lazy init: sentinel -1 → adopt first-step norm (blend no-op)
            gn = jnp.where(gn < 0, n, gn)
            if self.norm_type == 0:
                gn = b2 * gn + (1.0 - b2) * n
            else:
                gn = jnp.sqrt(b2 * gn * gn + (1.0 - b2) * n * n)
            denom = gn / bc2 + eps
            if self.moment_mode == 0:
                g2 = g / denom
                if wd != 0.0:
                    g2 = g2 + wd * p
                m = b1 * m + beta3 * g2
                update = m / bc1
            else:
                m = b1 * m + beta3 * g
                update = (m / bc1) / denom
                if wd != 0.0:
                    update = update + wd * p
            return p - lr * update, m, gn

        def flat():
            # Same math with the norm pass vectorized: per-tensor grad
            # norms land as an (n_leaves,) vector via one segmented
            # reduction (multi_tensor_novograd's norm launch), the norm
            # state stays a scalar-leaf tree, and the elementwise work
            # runs over one chunked buffer.
            gn_leaves = jax.tree_util.tree_leaves(state.slots["exp_avg_sq"])
            gn_vec = (jnp.stack([f32(x) for x in gn_leaves])
                      if gn_leaves else jnp.zeros((0,), jnp.float32))
            pb, meta = flatten_to_chunked(p32)
            gb, _ = flatten_to_chunked(g)
            mb, _ = flatten_to_chunked(m_tree)
            if self.norm_type == 0:
                n = chunked_per_leaf_max_abs(gb, meta)
                gn_new = jnp.where(gn_vec < 0, n, gn_vec)
                gn_new = b2 * gn_new + (1.0 - b2) * n
            else:
                n = jnp.sqrt(chunked_per_leaf_sumsq(gb, meta))
                gn_new = jnp.where(gn_vec < 0, n, gn_vec)
                gn_new = jnp.sqrt(b2 * gn_new * gn_new
                                  + (1.0 - b2) * n * n)
            denom = (gn_new / bc2 + eps)[jnp.asarray(meta.leaf_ids)][:, None]
            if self.moment_mode == 0:
                g2 = gb / denom
                if wd != 0.0:
                    g2 = g2 + wd * pb
                mb_new = b1 * mb + beta3 * g2
                update = mb_new / bc1
            else:
                mb_new = b1 * mb + beta3 * gb
                update = (mb_new / bc1) / denom
                if wd != 0.0:
                    update = update + wd * pb
            pb_new = pb - lr * update
            gn_tree = jax.tree_util.tree_unflatten(
                meta.treedef, [gn_new[i] for i in range(len(gn_leaves))])
            return (unflatten_from_chunked(pb_new, meta),
                    unflatten_from_chunked(mb_new, meta),
                    gn_tree)

        m_tree = state.slots["exp_avg"]
        if self.flat:
            new_p32, new_m, new_gn = flat()
        else:
            new_p32, new_m, new_gn = tree_map_multi(
                leaf, 3, p32, g, m_tree, state.slots["exp_avg_sq"]
            )
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_gn = apply_skip(skip_update, new_gn, state.slots["exp_avg_sq"])

        new_params = finalize_params(new_p32, params, self.master_weights)
        return new_params, OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_gn},
            master=new_p32 if self.master_weights else None,
        )
