"""FusedAdam / FusedAdamW — the ``multi_tensor_adam`` analog.

Behavioral spec: ``apex/optimizers/fused_adam.py`` (class ``:4``, ``step``
``:216-301``) over ``csrc/multi_tensor_adam.cu`` (``AdamFunctor:23-38``,
mode enum ``ADAM_MODE_0`` = L2 regularization into the gradient,
``ADAM_MODE_1`` = decoupled AdamW decay).  Points of parity:

- ``adam_w_mode=True`` (default) is AdamW: ``p -= lr*(update + wd*p)``;
  ``False`` folds ``wd*p`` into the gradient before the moments.
- ``bias_correction`` via ``1-beta^t`` exactly as ``fused_adam.py:241-247``.
- fp32 math for any param/grad dtype; optional fp32 masters in state
  (``master_weights=True``, ``fused_adam.py:71-104``).
- ``capturable`` mode (GPU-resident lr/step for CUDA graphs,
  ``fused_adam.py:128-214``) is meaningless under jit — every ``step`` is
  already a compiled program with traced ``lr``; the ``lr`` argument of
  :meth:`FusedAdam.step` provides the same capability.
- AMSGrad is rejected exactly like the reference (``fused_adam.py:80-81``).

The whole update is one XLA executable over the param pytree — the
multi-tensor fusion the CUDA kernel exists for comes from jit + donation
(see :func:`apex_tpu.optimizers.fused_step`).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    adam_apply,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_flat,
    tree_map_multi,
    tree_zeros_f32,
)

__all__ = ["FusedAdam"]


class FusedAdam:
    """Adam/AdamW with the Apex constructor surface
    (``apex/optimizers/fused_adam.py:4-70``)."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        master_weights: bool = False,
        flat: bool = False,
    ):
        if amsgrad:
            raise RuntimeError(
                "FusedAdam does not support the AMSGrad variant "
                "(parity with apex/optimizers/fused_adam.py:80)"
            )
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.master_weights = master_weights
        # flat=True applies the purely elementwise update over one chunked
        # buffer instead of per-leaf (equal to ~1 ulp of fma contraction) — one wide
        # kernel per op vs one small kernel per tensor, at the cost of a
        # pack/unpack copy.  Which side wins depends on how fragmented
        # the tree is; bench_fused_adam_step measures both.
        self.flat = flat

    def init(self, params) -> OptState:
        return OptState(
            step=jnp.int32(0),
            slots={
                "exp_avg": tree_zeros_f32(params),
                "exp_avg_sq": tree_zeros_f32(params),
            },
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)

        if self.bias_correction:
            # identical correction factors to fused_adam.py:241-247
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            return adam_apply(p, g, m, v, lr=lr, b1=b1, b2=b2, eps=eps,
                              wd=wd, bc1=bc1, bc2=bc2,
                              adam_w_mode=self.adam_w_mode)

        tmap = tree_map_flat if self.flat else tree_map_multi
        new_p32, new_m, new_v = tmap(
            leaf, 3, p32, g, state.slots["exp_avg"], state.slots["exp_avg_sq"]
        )

        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        new_params = finalize_params(new_p32, params, self.master_weights)
        new_state = OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_p32 if self.master_weights else None,
        )
        return new_params, new_state
