"""Fused gradient clipping — the ``apex.contrib.clip_grad`` analog.

Behavioral spec: ``apex/contrib/clip_grad/clip_grad.py:16-50``
(``clip_grad_norm_`` drop-in): total norm via ``multi_tensor_l2norm`` (or
inf-norm reduction), then ``multi_tensor_scale`` by ``max_norm/(total+1e-6)``
only when the coefficient < 1.  Here both phases are one fused jit program.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_l2_norm

__all__ = ["clip_grad_norm", "global_grad_norm"]


def global_grad_norm(grads, norm_type: float = 2.0) -> jnp.ndarray:
    """Global norm over a grad pytree (fp32)."""
    leaves = [
        jnp.asarray(x, jnp.float32) for x in jax.tree_util.tree_leaves(grads)
    ]
    if not leaves:
        return jnp.float32(0.0)
    if norm_type == float("inf"):
        return jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in leaves]))
    if norm_type == 2.0:
        return tree_l2_norm(grads)
    acc = jnp.sum(
        jnp.stack([jnp.sum(jnp.abs(x) ** norm_type) for x in leaves])
    )
    return acc ** (1.0 / norm_type)


def clip_grad_norm(
    grads, max_norm: float, norm_type: float = 2.0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Clip grads to ``max_norm`` globally; returns ``(clipped, total_norm)``.

    Matches ``clip_grad.py:40-49``: coefficient ``max_norm/(total+1e-6)``,
    applied only when < 1 (expressed branchlessly for jit).
    """
    total = global_grad_norm(grads, norm_type)
    coef = jnp.minimum(jnp.float32(max_norm) / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (jnp.asarray(g, jnp.float32) * coef).astype(
            jnp.asarray(g).dtype
        ),
        grads,
    )
    return clipped, total
