"""FusedLion — the ``multi_tensor_lion`` analog.

Behavioral spec: ``apex/optimizers/fused_lion.py`` (ctor ``:9``,
``lion_w_mode`` default True ``:22``) over ``csrc/multi_tensor_lion.cu``:

- ``LION_MODE_0`` (L2): ``g += wd*p``; ``u = sign(beta1*m + (1-beta1)*g)``;
  ``p -= lr*u``; ``m = beta2*m + (1-beta2)*g`` (``multi_tensor_lion.cu:87-99``).
- ``LION_MODE_1`` (decoupled, default): same but
  ``u = sign(...) + wd*p`` (``:101-110``).
- the kernel's sign maps 0 → -1 (``if(update<=0) update=-1``) — reproduced
  exactly for bitwise parity of the zero-gradient edge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_multi,
    tree_zeros_f32,
)

__all__ = ["FusedLion"]


def _apex_sign(u):
    # csrc/multi_tensor_lion.cu:91-92 — u<=0 → -1, else +1 (not jnp.sign)
    return jnp.where(u <= 0, -1.0, 1.0)


class FusedLion:
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        lion_w_mode: bool = True,
        weight_decay: float = 0.0,
        master_weights: bool = False,
    ):
        # bias_correction/eps accepted for ctor parity (fused_lion.py:8-9);
        # the reference kernel ignores both (commented out in
        # multi_tensor_lion.cu:93-96), as does this implementation.
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.lion_w_mode = lion_w_mode
        self.weight_decay = weight_decay
        self.master_weights = master_weights

    def init(self, params) -> OptState:
        return OptState(
            step=jnp.int32(0),
            slots={"exp_avg": tree_zeros_f32(params)},
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        b1, b2, wd = self.beta1, self.beta2, self.weight_decay
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)

        def leaf(p, g, m):
            if wd != 0.0 and not self.lion_w_mode:
                g = g + wd * p
            u = _apex_sign(b1 * m + (1.0 - b1) * g)
            if wd != 0.0 and self.lion_w_mode:
                u = u + wd * p
            return p - lr * u, b2 * m + (1.0 - b2) * g

        new_p32, new_m = tree_map_multi(leaf, 2, p32, g, state.slots["exp_avg"])
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])

        new_params = finalize_params(new_p32, params, self.master_weights)
        return new_params, OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m},
            master=new_p32 if self.master_weights else None,
        )
