"""FusedLAMB / FusedMixedPrecisionLamb — the ``multi_tensor_lamb`` analog.

Behavioral spec: ``apex/optimizers/fused_lamb.py`` (``step`` ``:116-207``)
over ``csrc/multi_tensor_lamb.cu`` (``LAMBStage1Functor:41``,
``LAMBStage2Functor:234``).  Parity points:

- global grad-norm clipping: ``step`` computes the global L2 norm over *all*
  grads with ``multi_tensor_l2norm`` (``fused_lamb.py:151-164``) and passes
  ``global_grad_norm / max_grad_norm`` (when > 1) as ``clipped_ratio`` into
  stage 1, which divides every grad by it (``multi_tensor_lamb.cu:65-80``).
- stage 1: Adam-style moments on the clipped grad; ``adam_w_mode=True``
  (``MODE_1``) decouples weight decay into the update
  (``update = m̂/(√v̂+eps) + wd*p``), ``adam_w_mode=False`` (``MODE_0``) folds
  ``wd*p`` into the clipped grad before the moments with no decay term in
  the update (``multi_tensor_lamb.cu:110-140``).
- stage 2: per-tensor trust ratio ``||p|| / ||update||`` (both fp32,
  ``multi_tensor_lamb.cu:245-270``), applied only when both norms are
  nonzero; with ``use_nvlamb=True`` the trust ratio is applied even for
  zero-weight-decay tensors (``fused_lamb.py:109-114`` NVLAMB note).
- ``grad_averaging``: ``(1-beta1)`` factor on the grad term
  (``fused_lamb.py:86``).

``FusedMixedPrecisionLamb`` (``apex/optimizers/fused_mixed_precision_lamb.py:8``)
keeps all state fp32 while model params are half — here that is just
``master_weights=True``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    OptState,
    advance_step,
    apply_skip,
    f32,
    finalize_params,
    resolve_master,
    scale_grads,
    tree_f32,
    tree_map_multi,
    tree_zeros_f32,
)
from apex_tpu.utils.tree import (
    chunked_per_leaf_sumsq,
    flatten_to_chunked,
    tree_l2_norm,
    unflatten_from_chunked,
)

__all__ = ["FusedLAMB", "FusedMixedPrecisionLamb", "lamb_flat_update"]


def lamb_flat_update(p32, g, m, v, *, lr, b1, b2, eps, wd, beta3, bc1, bc2,
                     adam_w_mode, use_nvlamb, clip_ratio, reduce=None):
    """Both LAMB stages over one chunked buffer — THE flat LAMB math,
    shared by :class:`FusedLAMB` (``reduce=None``) and the ZeRO-sharded
    ``DistributedFusedLAMB`` (``reduce=psum`` over the dp axis, applied to
    the shard-local global-norm partial and to the single stacked vector
    of per-tensor norm partials, so the distributed form still issues
    exactly one norm collective per step).

    The elementwise pass is a handful of (rows, 256) kernels, and the
    global grad norm and per-tensor trust-ratio norms are each ONE
    row-reduce (+ a segment_sum over row partials for the per-tensor
    ones) — the shape ``multi_tensor_lamb.cu:41,234`` gives the GPU (two
    list-kernels), re-expressed as XLA-friendly wide ops (r4 VERDICT
    weak #3: the per-leaf form was hundreds of small reductions).
    Padding rows hold zeros, so every norm is exact; results round-trip
    back to the original tree/dtypes, leaving state and checkpoint
    layouts unchanged.  ``clip_ratio`` maps the (already cross-replica)
    global grad norm to the clip divisor."""
    pb, meta = flatten_to_chunked(p32)
    gb, _ = flatten_to_chunked(g)
    mb, _ = flatten_to_chunked(m)
    vb, _ = flatten_to_chunked(v)

    g_sq = jnp.sum(jnp.square(gb))
    if reduce is not None:
        g_sq = reduce(g_sq)
    gb = gb / clip_ratio(jnp.sqrt(g_sq))
    if wd != 0.0 and not adam_w_mode:
        gb = gb + wd * pb  # MODE_0: L2 into the clipped grad
    mb = b1 * mb + beta3 * gb
    vb = b2 * vb + (1.0 - b2) * gb * gb
    ub = (mb / bc1) / (jnp.sqrt(vb / bc2) + eps)
    if wd != 0.0 and adam_w_mode:
        ub = ub + wd * pb  # MODE_1: decoupled decay
    if wd != 0.0 or use_nvlamb:
        # stage 2: per-tensor trust ratios (multi_tensor_lamb.cu:245-270)
        partial = jnp.concatenate([chunked_per_leaf_sumsq(pb, meta),
                                   chunked_per_leaf_sumsq(ub, meta)])
        if reduce is not None:
            partial = reduce(partial)
        n_leaves = len(meta.shapes)
        w_sq, u_sq = partial[:n_leaves], partial[n_leaves:]
        ratio_leaf = jnp.where(
            (w_sq > 0) & (u_sq > 0),
            jnp.sqrt(w_sq) / jnp.sqrt(jnp.where(u_sq > 0, u_sq, 1.0)),
            1.0,
        )
        # per-tensor scalar -> per-row column: broadcast, not gather
        ratio = ratio_leaf[jnp.asarray(meta.leaf_ids)][:, None]
    else:
        ratio = jnp.float32(1.0)
    pb = pb - lr * ratio * ub
    return (unflatten_from_chunked(pb, meta),
            unflatten_from_chunked(mb, meta),
            unflatten_from_chunked(vb, meta))


class FusedLAMB:
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas=(0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        master_weights: bool = False,
        flat: bool = True,
    ):
        if amsgrad:
            raise RuntimeError(
                "FusedLAMB does not support the AMSGrad variant "
                "(parity with apex/optimizers/fused_lamb.py:75)"
            )
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.master_weights = master_weights
        # flat=True runs the whole update over one chunked (rows, 256)
        # buffer — the multi_tensor_lamb list-kernel analog (r4 VERDICT
        # weak #3: the per-leaf form was hundreds of small reductions and
        # measured 3.4x off SGD on chip).  flat=False keeps the per-leaf
        # form for A/B diagnosis.
        self.flat = flat

    def init(self, params) -> OptState:
        return OptState(
            step=jnp.int32(0),
            slots={
                "exp_avg": tree_zeros_f32(params),
                "exp_avg_sq": tree_zeros_f32(params),
            },
            master=tree_f32(params) if self.master_weights else None,
        )

    def step(
        self,
        grads,
        state: OptState,
        params,
        *,
        lr=None,
        grad_scale=None,
        skip_update=None,
    ):
        lr = f32(self.lr if lr is None else lr)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = state.step + 1
        g = scale_grads(grads, grad_scale)
        p32 = resolve_master(params, state.master, self.master_weights)

        beta3 = 1.0 - b1 if self.grad_averaging else 1.0
        if self.bias_correction:
            bc1 = 1.0 - b1 ** f32(t)
            bc2 = 1.0 - b2 ** f32(t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        update = self._flat_update if self.flat else self._per_leaf_update
        new_p32, new_m, new_v = update(
            p32, g, state.slots["exp_avg"], state.slots["exp_avg_sq"],
            lr, beta3, bc1, bc2)
        new_p32 = apply_skip(skip_update, new_p32, p32)
        new_m = apply_skip(skip_update, new_m, state.slots["exp_avg"])
        new_v = apply_skip(skip_update, new_v, state.slots["exp_avg_sq"])

        new_params = finalize_params(new_p32, params, self.master_weights)
        return new_params, OptState(
            step=advance_step(state.step, skip_update),
            slots={"exp_avg": new_m, "exp_avg_sq": new_v},
            master=new_p32 if self.master_weights else None,
        )

    def _clip_ratio(self, global_norm):
        """clip divisor from the global grad norm (fused_lamb.py:151-170)."""
        if self.max_grad_norm and self.max_grad_norm > 0:
            return jnp.maximum(global_norm / self.max_grad_norm, 1.0)
        return jnp.float32(1.0)

    def _flat_update(self, p32, g, m, v, lr, beta3, bc1, bc2):
        return lamb_flat_update(
            p32, g, m, v, lr=lr, b1=self.beta1, b2=self.beta2, eps=self.eps,
            wd=self.weight_decay, beta3=beta3, bc1=bc1, bc2=bc2,
            adam_w_mode=self.adam_w_mode, use_nvlamb=self.use_nvlamb,
            clip_ratio=self._clip_ratio)

    def _per_leaf_update(self, p32, g, m, v, lr, beta3, bc1, bc2):
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        clip = self._clip_ratio(tree_l2_norm(g))

        def leaf(p, g, m, v):
            g = g / clip
            if wd != 0.0 and not self.adam_w_mode:
                g = g + wd * p  # MODE_0: L2 into the clipped grad
            m = b1 * m + beta3 * g
            v = b2 * v + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if wd != 0.0 and self.adam_w_mode:
                update = update + wd * p  # MODE_1: decoupled decay
            # stage 2: per-tensor trust ratio (multi_tensor_lamb.cu:245-270)
            w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
            if wd != 0.0 or self.use_nvlamb:
                ratio = jnp.where(
                    (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
                )
            else:
                ratio = jnp.float32(1.0)
            return p - lr * ratio * update, m, v

        return tree_map_multi(leaf, 3, p32, g, m, v)


class FusedMixedPrecisionLamb(FusedLAMB):
    """LAMB with fp32 state for half-precision models
    (``apex/optimizers/fused_mixed_precision_lamb.py:8``): exactly
    ``FusedLAMB(master_weights=True)``; ``lr`` may be a traced array
    (the reference keeps lr as a GPU tensor, ``:43-48``) — pass it per step."""

    def __init__(self, *args, **kwargs):
        kwargs["master_weights"] = True
        super().__init__(*args, **kwargs)
