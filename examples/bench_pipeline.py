"""Pipeline-schedule throughput harness.

Measures the rotation pipeline (pp>1, optional vpp) against no-pipelining
at equal global batch and model size, and reports the measured efficiency
next to the schedule's analytic bubble prediction
(:func:`apex_tpu.transformer.pipeline_parallel.pipeline_bubble_fraction`)
— the round-1 VERDICT's "scalability is asserted, not measured" item.

NB on virtual CPU devices all mesh "devices" share the host's cores, so
wall-clock speedups are NOT meaningful there (the analytic bubble check
still is); run on real multi-chip hardware for throughput numbers.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/bench_pipeline.py --pp 4 --vpp 2 -m 16
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--vpp", type=int, default=1)
    ap.add_argument("-m", "--num-microbatches", type=int, default=16)
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--microbatch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
    import jax
    import jax.numpy as jnp

    from apex_tpu import parallel
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_apply,
        pipeline_bubble_fraction,
        split_into_microbatches,
        stack_stage_params,
    )

    pp, vpp, m = args.pp, args.vpp, args.num_microbatches
    width = args.width
    n_layers = pp * vpp
    mesh = parallel.initialize_model_parallel(
        pipeline_model_parallel_size=pp)

    ks = jax.random.split(jax.random.PRNGKey(0), n_layers)
    stages = [{"w": jax.random.normal(k, (width, width)) * 0.1,
               "b": jnp.zeros((width,))} for k in ks]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (m * args.microbatch, width))
    mbs = split_into_microbatches(x, m)

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    @jax.jit
    def piped(params, mbs):
        def loss(params):
            out = pipeline_apply(stage_fn, params, mbs, num_chunks=vpp,
                                 mesh=mesh, shard_microbatches=True)
            return jnp.sum(out ** 2)
        return jax.grad(loss)(params)

    @jax.jit
    def serial(params, x):
        def loss(params):
            h = x
            for i in range(n_layers):
                p = jax.tree_util.tree_map(lambda l, i=i: l[i], params)
                h = stage_fn(p, h)
            return jnp.sum(h ** 2)
        return jax.grad(loss)(params)

    def timeit(f, *a):
        out = f(*a)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = f(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps

    t_pipe = timeit(piped, stacked, mbs)
    t_serial = timeit(serial, stacked, x)

    def temp_mb(remat_ticks):
        def fb(params, mbs):
            def loss(params):
                out = pipeline_apply(stage_fn, params, mbs, num_chunks=vpp,
                                     mesh=mesh, remat_ticks=remat_ticks)
                return jnp.sum(out ** 2)
            return jax.grad(loss)(params)
        ma = jax.jit(fb).lower(stacked, mbs).compile().memory_analysis()
        return round(ma.temp_size_in_bytes / 1e6, 2)

    bubble = pipeline_bubble_fraction(m, pp, vpp)
    record = {
        "pp": pp, "vpp": vpp, "m": m, "width": width,
        "t_pipeline_s": round(t_pipe, 5),
        "t_serial_1dev_s": round(t_serial, 5),
        "analytic_bubble": round(bubble, 4),
        "ideal_speedup_vs_1dev": round(pp * (1 - bubble), 3),
        "measured_speedup_vs_1dev": round(t_serial / t_pipe, 3),
        "temp_mem_mb_flat": temp_mb(None),
        "temp_mem_mb_grouped_remat": temp_mb(True),
        "platform": jax.devices()[0].platform,
        "note": ("wall-clock meaningless on virtual CPU devices"
                 if jax.devices()[0].platform == "cpu" else ""),
    }
    print(json.dumps(record))
    parallel.destroy_model_parallel()


if __name__ == "__main__":
    main()
