"""Toy distributed mixed-precision training — the analog of
``examples/simple/distributed/distributed_data_parallel.py``.

The reference wraps a 2-layer model in apex DDP + amp O1 and runs
``python -m torch.distributed.launch``.  Here the same workload is one SPMD
program over the device mesh: no launcher, no process groups.

Run (CPU demo):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/simple_distributed.py
Run (TPU): python examples/simple_distributed.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp, parallel
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import data_parallel_train_step, dp_shard_batch, replicate


def main(steps: int = 40):
    mesh = parallel.initialize_model_parallel()  # all devices on dp
    print(parallel.mesh.get_rank_info())

    D, H = 64, 128
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(D, H).astype(np.float32) / np.sqrt(D)),
        "w2": jnp.asarray(rng.randn(H, D).astype(np.float32) / np.sqrt(H)),
    }
    policy = amp.policy("O1")  # bf16 compute, fp32 params

    def loss_fn(p, batch):
        x, y = batch
        h = jax.nn.relu(x.astype(policy.compute_dtype) @ p["w1"].astype(policy.compute_dtype))
        out = (h @ p["w2"].astype(policy.compute_dtype)).astype(jnp.float32)
        return jnp.mean((out - y) ** 2)

    opt = FusedSGD(lr=0.3, momentum=0.9)
    params = replicate(params, mesh)
    opt_state = replicate(opt.init(params), mesh)
    step = data_parallel_train_step(loss_fn, opt, mesh=mesh)

    for i in range(steps):
        x = rng.randn(64, D).astype(np.float32)
        y = x  # identity target
        batch = dp_shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)
        params, opt_state, loss = step(params, opt_state, batch)
        if i % 5 == 0 or i == steps - 1:
            print(f"step {i:3d} loss {float(loss):.5f}")
    return float(loss)


if __name__ == "__main__":
    main()
