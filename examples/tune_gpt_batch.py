"""Per-chip batch-size sweep for the flagship gpt_flash workload.

The r4 first TPU window measured gpt_flash MFU 0.4155 at the shipped
batch 8 while BERT-large crossed 0.5059 on the same stack — batch is the
one shape knob the block sweep (tune_flash_blocks.py) does not touch,
and at 124M params the activation memory for batch 16/32 is far inside
a v5e's HBM.  This harness times the real train step
(``bench.gpt_flash_setup`` via ``APEX_TPU_GPT_BATCH``) across a batch
grid, each point in its own subprocess with the persistent compile
cache on.

    python examples/tune_gpt_batch.py                # 8, 16, 32
    python examples/tune_gpt_batch.py --batches 16 48 --seq 8192

Results append to ``bench_results/gpt_batch_sweep.jsonl``; each record
carries both the requested ``base_batch`` (the knob) and the effective
``batch`` (above seq 1024 the workload token-budget-rescales it).  MFU
is batch-honest, so a better point justifies bumping the shipped
default *with* the recorded sweep as provenance — the policy the
``APEX_TPU_GPT_BATCH`` comment in bench.py states.

Off-TPU the knob is inert (``gpt_flash_setup`` pins tiny CPU smoke
shapes), so the driver runs a single smoke point and says so.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "bench_results", "gpt_batch_sweep.jsonl")
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from examples._sweep import run_sweep  # noqa: E402


def run_point(base_batch: int, seq: int, steps: int) -> None:
    """Child: one batch point of the exact gpt_flash workload.  The knob
    is set here too, so a hand-run child honors its argv."""
    os.environ["APEX_TPU_GPT_BATCH"] = str(base_batch)

    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()

    import bench

    bench.enable_compilation_cache(jax)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if not on_tpu:
        steps = min(steps, 2)

    cfg, step, st, got_batch, seq, n_params = bench.gpt_flash_setup(
        jax, on_tpu, seq=seq)

    t0 = time.perf_counter()
    st = step(*st)
    jax.block_until_ready(st)
    compile_s = time.perf_counter() - t0

    dt, _ = bench._timeit(jax, step, st, steps)
    tps = got_batch * seq * steps / dt
    flops = bench._lm_train_flops(cfg, n_params, got_batch, seq) * steps / dt
    rec = {
        "base_batch": base_batch, "batch": got_batch, "seq": seq,
        "tokens_per_sec": round(tps, 1),
        "mfu": round(flops / bench._peak_flops(dev), 4) if on_tpu else None,
        "compile_s": round(compile_s, 1),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(rec), flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batches", nargs="+", type=int, default=[8, 16, 32])
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--no-land", action="store_true",
                   help="exploratory sweep: never write "
                        "bench_results/gpt_batch_tuned.json (by default a "
                        "TPU sweep at seq 1024 with >1 surviving point "
                        "auto-lands its winner as the bench default)")
    args = p.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        print("CPU pin detected: the batch knob is inert off-TPU "
              "(gpt_flash_setup uses fixed smoke shapes); running a "
              "single smoke point", file=sys.stderr, flush=True)
        batches = args.batches[:1]
    else:
        # dedupe points whose *effective* batch collapses (above seq 1024
        # the workload rescales base*1024//seq)
        batches, seen = [], set()
        for b in args.batches:
            eff = b if args.seq <= 1024 else max(1, b * 1024 // args.seq)
            if eff in seen:
                print(f"--- batch={b}: effective batch {eff} duplicates "
                      f"an earlier point; skipped",
                      file=sys.stderr, flush=True)
                continue
            seen.add(eff)
            batches.append(b)

    def eff(b):
        return b if args.seq <= 1024 else max(1, b * 1024 // args.seq)

    best, records = run_sweep(
        batches,
        env_for=lambda b: {"APEX_TPU_GPT_BATCH": str(b)},
        child_args_for=lambda b: [
            os.path.abspath(__file__), "--child",
            str(b), str(args.seq), str(args.steps)],
        label_for=lambda b: (
            f"batch={b} seq={args.seq}" if eff(b) == b
            else f"batch={b} (effective {eff(b)}) seq={args.seq}"),
        out_path=OUT, timeout=args.timeout)
    if best:
        print(json.dumps({"best": best}))
        # Auto-land the winner (flash-blocks pattern): a TPU sweep at the
        # flagship seq writes the tuned file bench.gpt_flash_setup
        # consults, gated on device_kind (env override still wins) — so
        # an unattended capture upgrades the bench batch with the sweep
        # itself as recorded provenance.  Gated on >1 *successful* point:
        # a lone survivor (others wedged/OOMed) is no comparison.
        if (best["platform"] == "tpu" and args.seq == 1024
                and len(records) > 1 and not args.no_land):
            tuned = os.path.join(REPO, "bench_results",
                                 "gpt_batch_tuned.json")
            with open(tuned, "w") as f:
                json.dump(best, f)
            print(f"tuned batch written to {tuned}",
                  file=sys.stderr, flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 5 and sys.argv[1] == "--child":
        run_point(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    else:
        main()
