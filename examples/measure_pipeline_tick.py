"""Single-chip pipeline tick anchor (round-3 VERDICT stretch item 9).

The rotation schedule's bubble model says a pp-stage pipeline with m
microbatches spends ``pipeline_bubble_fraction(m, pp, vpp)`` of its ticks
idle, so its step time is ``ticks(m, pp, vpp) * T_tick`` where ``T_tick``
is one stage's fwd+bwd on one microbatch.  The virtual-CPU-mesh records
(``bench_results/pipeline_virtual_mesh.jsonl``) validate the *tick
counts* but their wall clock is meaningless (all "devices" share the
host's cores).  This harness supplies the missing real-clock anchor: it
times ``T_tick`` for the same stage shape on the one attached chip and
prints the projected pp-pipeline step times next to the analytic bubble,
so the model has one hardware-measured constant per configuration.

Reference capability anchored: 1F1B's warmup+cooldown bubble
(``fwd_bwd_pipelining_without_interleaving.py``: (pp-1)/(m+pp-1)).

    python examples/measure_pipeline_tick.py          # TPU if attached
    JAX_PLATFORMS=cpu python examples/measure_pipeline_tick.py   # smoke
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=256,
                    help="stage width (matches pipeline_virtual_mesh rows)")
    ap.add_argument("--microbatch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
    import jax
    import jax.numpy as jnp

    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_bubble_fraction,
        pipeline_total_ticks,
    )

    width = args.width
    params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                     (width, width)) * 0.1,
              "b": jnp.zeros((width,))}
    h = jax.random.normal(jax.random.PRNGKey(1), (args.microbatch, width))

    # one tick = one stage fwd+bwd on one microbatch (the schedule's unit
    # of work; the same stage_fn bench_pipeline.py pipelines)
    @jax.jit
    def tick(params, h):
        def loss(p):
            return jnp.sum(jnp.tanh(h @ p["w"] + p["b"]) ** 2)
        return jax.grad(loss)(params)

    import bench  # shared timing methodology (bench._timeit)

    step = lambda p, h: (tick(p, h), h)  # noqa: E731  carry drives timing
    st = step(params, h)
    jax.block_until_ready(st)
    dt, _ = bench._timeit(jax, step, st, args.steps)
    t_tick = dt / args.steps

    dev = jax.devices()[0]
    projections = []
    for pp, vpp, m in ((4, 1, 16), (4, 2, 16), (8, 1, 32), (8, 2, 32)):
        ticks = pipeline_total_ticks(m, pp, vpp)
        bubble = pipeline_bubble_fraction(m, pp, vpp)
        projections.append({
            "pp": pp, "vpp": vpp, "m": m,
            "schedule_ticks": ticks,
            "analytic_bubble": round(bubble, 4),
            "projected_step_s": round(ticks * t_tick, 6),
            "projected_ideal_s": round(m * vpp * t_tick, 6),
        })
    record = {
        "width": width, "microbatch": args.microbatch,
        "t_tick_s": round(t_tick, 7),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "projections": projections,
        "note": ("real-clock anchor for the virtual-mesh tick-count "
                 "records in bench_results/pipeline_virtual_mesh.jsonl"),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(record))
    if dev.platform == "tpu":
        out_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench_results", "pipeline_tick_tpu.jsonl")
        with open(out_path, "a") as f:
            f.write(json.dumps(record) + "\n")


if __name__ == "__main__":
    main()
