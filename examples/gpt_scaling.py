"""GPT parallel-grid scaling harness.

Behavioral spec: ``tests/L0/run_transformer/gpt_scaling_test.py`` — run the
standalone GPT across (tp, pp) grids and report per-config step time and
memory.  Here each grid runs the full 3D train step
(:func:`apex_tpu.transformer.testing.gpt_parallel_train.build_gpt_3d`)
over the attached devices (virtual CPU mesh or real chips).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/gpt_scaling.py --grids 1x1 2x1 1x2 2x2 4x2
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_grid(tp, pp, args):
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import mesh as mesh_lib
    from apex_tpu.transformer.testing import TransformerConfig
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d

    n = len(jax.devices())
    if n % (tp * pp):
        return {"tp": tp, "pp": pp, "error": f"{n} devices not divisible"}
    vpp = 2 if pp > 1 else 1
    mesh = mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        virtual_pipeline_model_parallel_size=vpp if vpp > 1 else None)
    try:
        dp = mesh.shape["dp"]
        cfg = TransformerConfig(
            hidden_size=args.hidden, num_layers=pp * vpp,
            num_attention_heads=max(4, args.hidden // 32),
            padded_vocab_size=args.vocab,
            max_position_embeddings=args.seq,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp" if tp > 1 else None,
            sequence_parallel=tp > 1,
            dtype=jnp.bfloat16 if jax.devices()[0].platform == "tpu"
            else jnp.float32,
        )
        m = args.microbatches
        init_fn, _, make_train_step = build_gpt_3d(
            cfg, num_chunks=vpp, num_microbatches=m, mesh=mesh)
        batch = dp * m * args.microbatch
        tokens = jax.random.randint(jax.random.PRNGKey(0),
                                    (batch, args.seq), 0, args.vocab)
        params, specs = init_fn(jax.random.PRNGKey(1), tokens)
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)
        step = jax.jit(make_train_step(opt, specs))

        t0 = time.perf_counter()
        params, state, loss = step(params, state, tokens)
        jax.block_until_ready(loss)
        compile_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, state, loss = step(params, state, tokens)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / args.steps

        mem = None
        try:
            stats = jax.devices()[0].memory_stats()
            if stats:
                mem = int(stats.get("peak_bytes_in_use", 0))
        except Exception:
            pass
        return {
            "tp": tp, "pp": pp, "vpp": vpp, "dp": dp,
            "tokens_per_step": batch * args.seq,
            "step_time_s": round(dt, 4),
            "tokens_per_sec": round(batch * args.seq / dt, 1),
            "compile_s": round(compile_s, 1),
            "peak_bytes": mem,
            "loss": float(loss),
        }
    finally:
        mesh_lib.destroy_model_parallel()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grids", nargs="+", default=["1x1", "2x1", "1x2",
                                                   "2x2", "4x2"],
                    help="TPxPP grid list")
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--microbatch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
    results = []
    for grid in args.grids:
        tp, pp = (int(x) for x in grid.split("x"))
        try:
            rec = run_grid(tp, pp, args)
        except Exception as e:  # one bad grid must not kill the sweep
            rec = {"tp": tp, "pp": pp, "error": repr(e)}
        print(json.dumps(rec), flush=True)
        results.append(rec)


if __name__ == "__main__":
    main()
