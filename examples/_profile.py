"""Shared jax.profiler capture harness for the profile_* examples.

One place for the backend bring-up (CPU pin honor, persistent compile
cache), the warm-compile convention, the timestamped
``bench_results/profiles/<workload>_<stamp>/`` trace layout, and the
``summary.jsonl`` record schema (every row carries ``workload`` so
consumers never field-sniff).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def init_bench_backend():
    """Backend + bench module with the tuning harnesses' conventions.
    Returns ``(jax, bench, dev, on_tpu)``."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()

    import bench

    bench.enable_compilation_cache(jax)
    dev = jax.devices()[0]
    return jax, bench, dev, dev.platform == "tpu"


def profile_capture(workload: str, jax, bench, step_fn, st0, steps: int,
                    record_fields: dict) -> dict:
    """Warm-compile ``step_fn`` (two calls), trace ``steps`` timed steps,
    append the summary record, and return it.

    ``record_fields``: workload-specific fields merged into the record
    (callables receive the measured ``dt`` — e.g. MFU derivations)."""
    st = step_fn(*st0)
    st = step_fn(*st)
    jax.block_until_ready(st)

    stamp = time.strftime("%Y%m%d_%H%M%S")
    trace_dir = os.path.join(REPO, "bench_results", "profiles",
                             f"{workload}_{stamp}")
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        dt, st = bench._timeit(jax, step_fn, st, steps)

    dev = jax.devices()[0]
    rec = {
        "workload": workload,
        "trace_dir": os.path.relpath(trace_dir, REPO),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "steps": steps,
        "step_ms": round(dt / steps * 1e3, 2),
        "ts": stamp,
    }
    for k, v in record_fields.items():
        rec[k] = v(dt) if callable(v) else v
    out = os.path.join(REPO, "bench_results", "profiles", "summary.jsonl")
    with open(out, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return rec
