"""DCGAN with mixed precision — the analog of
``examples/dcgan/main_amp.py``.

The reference trains the classic 64x64 DCGAN with
``amp.initialize([netD, netG], [optD, optG], num_losses=3)`` and a
separate ``loss_id`` per backward (D-real=0, D-fake=1, G=2;
``main_amp.py:218-276``) so each loss owns an independent dynamic scaler.
Here the same three-scaler structure drives one jitted D step and one
jitted G step:

    # synthetic data (the reference's ``--dataset fake`` / FakeData path):
    python examples/dcgan_amp.py --steps 200

    # folder dataset (the reference's ``--dataset folder``):
    python examples/dcgan_amp.py --dataroot /path/to/images --steps 2000

TPU-first notes: both networks are NHWC Flax modules (XLA's native conv
layout); the two optimizers are FusedAdam(betas=(0.5, 0.999)) like the
reference; generator/discriminator losses stay finite in bf16, but the
per-loss scaler plumbing is exercised exactly as the reference exercises
it (scale -> grad -> unscale -> finite-check -> update/adjust).
"""

import argparse
import time
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp, parallel
from apex_tpu.optimizers import FusedAdam

NC = 3  # image channels


class Generator(nn.Module):
    """z -> 64x64x3, the reference netG (``main_amp.py:125-153``):
    ConvTranspose 4x4 stack, BN+ReLU, tanh output."""

    nz: int = 100
    ngf: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        # z: [B, nz] -> [B, 1, 1, nz]
        x = z.reshape(z.shape[0], 1, 1, self.nz).astype(self.dtype)
        widths = (self.ngf * 8, self.ngf * 4, self.ngf * 2, self.ngf)
        for i, w in enumerate(widths):
            x = nn.ConvTranspose(
                w, (4, 4),
                strides=(1, 1) if i == 0 else (2, 2),
                padding="VALID" if i == 0 else "SAME",
                use_bias=False, dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(NC, (4, 4), strides=(2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        return jnp.tanh(x)  # [B, 64, 64, 3]


class Discriminator(nn.Module):
    """64x64x3 -> logit, the reference netD (``main_amp.py:166-190``):
    strided 4x4 convs, LeakyReLU(0.2), BN on the middle blocks."""

    ndf: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        widths = (self.ndf, self.ndf * 2, self.ndf * 4, self.ndf * 8)
        for i, w in enumerate(widths):
            x = nn.Conv(w, (4, 4), strides=(2, 2), padding="SAME",
                        use_bias=False, dtype=self.dtype)(x)
            if i > 0:
                x = nn.BatchNorm(use_running_average=not train,
                                 dtype=self.dtype)(x)
            x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(1, (4, 4), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)  # [B, 1, 1, 1]
        return x.reshape(x.shape[0])


def bce_with_logits(logits, target: float):
    """``BCEWithLogitsLoss`` against a constant label, in fp32."""
    logits = logits.astype(jnp.float32)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * target
        + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def folder_batches(root, batch_size, image_size=64, seed=0):
    """Real-image stream through apex_tpu.data (uint8 -> [-1, 1])."""
    from apex_tpu.data import ImageFolder, ImageFolderLoader

    loader = ImageFolderLoader(ImageFolder(root), local_batch=batch_size,
                               image_size=image_size, seed=seed)
    try:
        while True:
            for x, _ in loader:  # labels unused (unconditional GAN)
                yield x.astype(np.float32) / 127.5 - 1.0
    finally:
        loader.close()  # generator finalization reclaims decode threads


def fake_batches(batch_size, image_size=64, seed=0):
    """The reference's ``--dataset fake`` (FakeData) path."""
    rng = np.random.RandomState(seed)
    while True:
        yield rng.uniform(-1.0, 1.0,
                          (batch_size, image_size, image_size, NC)
                          ).astype(np.float32)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dataroot", default=None,
                   help="image folder; synthetic FakeData when omitted")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--seed", type=int, default=2809)  # reference default
    args = p.parse_args(argv)

    parallel.initialize_model_parallel()
    conf, state = amp.initialize(opt_level=args.opt_level, num_losses=3)
    scalers = (state.scaler if isinstance(state.scaler, tuple)
               else (state.scaler,) * 3)
    s_real, s_fake, s_gen = scalers
    policy = conf.policy

    netG = Generator(nz=args.nz, ngf=args.ngf, dtype=policy.compute_dtype)
    netD = Discriminator(ndf=args.ndf, dtype=policy.compute_dtype)

    key = jax.random.PRNGKey(args.seed)
    kG, kD, key = jax.random.split(key, 3)
    z0 = jnp.zeros((2, args.nz))
    x0 = jnp.zeros((2, 64, 64, NC))
    vG = netG.init(kG, z0)
    vD = netD.init(kD, x0)
    pG, bsG = vG["params"], vG["batch_stats"]
    pD, bsD = vD["params"], vD["batch_stats"]

    optD = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))
    optG = FusedAdam(lr=args.lr, betas=(args.beta1, 0.999))
    osD, osG = optD.init(pD), optG.init(pG)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def d_step(pD, bsD, osD, pG, bsG, real, z, s_real, s_fake):
        """D update: two backwards with per-loss scalers (loss_id 0 and 1,
        ``main_amp.py:231-244``), summed unscaled grads, one Adam step."""
        fake, _ = netG.apply({"params": pG, "batch_stats": bsG}, z,
                             train=True, mutable=["batch_stats"])
        fake = jax.lax.stop_gradient(fake)  # fake.detach()

        def loss_real(pD, bsD):
            out, mut = netD.apply({"params": pD, "batch_stats": bsD}, real,
                                  train=True, mutable=["batch_stats"])
            return amp.scale_loss(bce_with_logits(out, 1.0), s_real), (
                mut["batch_stats"], jnp.mean(jax.nn.sigmoid(out)))

        def loss_fake(pD, bsD):
            out, mut = netD.apply({"params": pD, "batch_stats": bsD}, fake,
                                  train=True, mutable=["batch_stats"])
            return amp.scale_loss(bce_with_logits(out, 0.0), s_fake), (
                mut["batch_stats"], jnp.mean(jax.nn.sigmoid(out)))

        (lr_s, (bsD, d_x)), g_real = jax.value_and_grad(
            loss_real, has_aux=True)(pD, bsD)
        (lf_s, (bsD, d_g1)), g_fake = jax.value_and_grad(
            loss_fake, has_aux=True)(pD, bsD)

        g_real = conf.loss_scaler.unscale(g_real, s_real)
        g_fake = conf.loss_scaler.unscale(g_fake, s_fake)
        # report with the scales the losses were scaled by (pre-update)
        errD = lr_s / s_real.scale + lf_s / s_fake.scale
        # independent per-loss overflow checks (the loss_id 0/1 contract);
        # the shared optimizer step skips if either backward overflowed
        finite_real = amp.all_finite(g_real)
        finite_fake = amp.all_finite(g_fake)
        g = jax.tree_util.tree_map(jnp.add, g_real, g_fake)
        new_pD, new_osD = optD.step(
            g, osD, pD, skip_update=~(finite_real & finite_fake))
        s_real = conf.loss_scaler.update(s_real, finite_real)
        s_fake = conf.loss_scaler.update(s_fake, finite_fake)
        return (new_pD, bsD, new_osD, s_real, s_fake, errD, d_x, d_g1)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def g_step(pG, bsG, osG, pD, bsD, z, s_gen):
        """G update: maximize log(D(G(z))) with loss_id 2
        (``main_amp.py:262-270``)."""
        def loss(pG, bsG):
            fake, mutG = netG.apply({"params": pG, "batch_stats": bsG}, z,
                                    train=True, mutable=["batch_stats"])
            out, _ = netD.apply({"params": pD, "batch_stats": bsD}, fake,
                                train=True, mutable=["batch_stats"])
            return amp.scale_loss(bce_with_logits(out, 1.0), s_gen), (
                mutG["batch_stats"], jnp.mean(jax.nn.sigmoid(out)))

        (l_s, (bsG, d_g2)), g = jax.value_and_grad(
            loss, has_aux=True)(pG, bsG)
        g = conf.loss_scaler.unscale(g, s_gen)
        errG = l_s / s_gen.scale  # pre-update scale
        finite = amp.all_finite(g)
        new_pG, new_osG = optG.step(g, osG, pG, skip_update=~finite)
        s_gen = conf.loss_scaler.update(s_gen, finite)
        return new_pG, bsG, new_osG, s_gen, errG, d_g2

    from apex_tpu.data import prefetch_to_device

    host_it = (folder_batches(args.dataroot, args.batch_size)
               if args.dataroot else fake_batches(args.batch_size))
    # H2D transfers run 2 batches ahead of the D/G steps (the reference
    # data_prefetcher role).  Plain device_put placement: this example's
    # jitted steps use default sharding (the GAN batch is not dp-sharded).
    it = prefetch_to_device(host_it, depth=2, place=jax.device_put)
    rng = np.random.RandomState(args.seed)
    t0 = time.perf_counter()
    errD = errG = None
    for i in range(args.steps):
        real = next(it)
        z = jnp.asarray(rng.randn(args.batch_size, args.nz), np.float32)
        (pD, bsD, osD, s_real, s_fake, errD, d_x, d_g1) = d_step(
            pD, bsD, osD, pG, bsG, real, z, s_real, s_fake)
        z = jnp.asarray(rng.randn(args.batch_size, args.nz), np.float32)
        pG, bsG, osG, s_gen, errG, d_g2 = g_step(
            pG, bsG, osG, pD, bsD, z, s_gen)
        if i == 0:
            jax.block_until_ready(errG)
            t0 = time.perf_counter()
        if i % 10 == 0 or i == args.steps - 1:
            print(f"[{i}/{args.steps}] Loss_D {float(errD):.4f} "
                  f"Loss_G {float(errG):.4f} D(x) {float(d_x):.3f} "
                  f"D(G(z)) {float(d_g1):.3f}/{float(d_g2):.3f} "
                  f"scales {float(s_real.scale):.0f}/"
                  f"{float(s_fake.scale):.0f}/{float(s_gen.scale):.0f}")
    jax.block_until_ready(errG)
    dt = time.perf_counter() - t0
    if args.steps > 1:
        print(f"{args.batch_size * (args.steps - 1) / dt:.1f} images/sec")
    return float(errD), float(errG)


if __name__ == "__main__":
    main()
