"""Capture a jax.profiler trace of a resnet50 bench workload on the
attached chip.

The r4 first TPU window measured resnet50_o2 at 8824 img/s/chip but
resnet50_lamb_syncbn at 2567 — a 3.4x gap whose CPU A/B
(`bench.py --one resnet50_{sgd_syncbn,lamb_nosync}`) points at the
FusedLAMB step.  This trace shows where the slow step's time actually
goes (the r2 VERDICT's "a profile, not a guess" rule).

    python examples/profile_resnet.py --optimizer lamb --sync-bn
    python examples/profile_resnet.py --optimizer sgd

Writes a TensorBoard/XPlane trace under ``bench_results/profiles/`` plus
a one-line JSON summary (shared harness: ``examples/_profile.py``).
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from examples._profile import init_bench_backend, profile_capture  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--optimizer", default="lamb", choices=["sgd", "lamb"])
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    jax, bench, dev, on_tpu = init_bench_backend()
    train_step, st0, meta = bench.resnet_setup(
        jax, on_tpu, args.optimizer, sync_bn=args.sync_bn)
    try:
        profile_capture(
            f"rn50_{args.optimizer}{'_syncbn' if args.sync_bn else ''}",
            jax, bench, train_step, st0, args.steps,
            {
                "optimizer": args.optimizer,
                "sync_bn": args.sync_bn,
                "batch": meta["batch"],
                "image_size": meta["image_size"],
                "images_per_sec_chip": lambda dt: round(
                    meta["batch"] * args.steps / dt / meta["n_chips"], 1),
            })
    finally:
        meta["mesh_cleanup"]()


if __name__ == "__main__":
    main()
