"""Capture a jax.profiler trace of the flagship GPT train step on the
attached chip (VERDICT r2 item 3: the MFU gap "needs a profile, not a
guess").

    python examples/profile_gpt.py [--seq 1024] [--steps 5]

Writes a TensorBoard/XPlane trace under ``bench_results/profiles/`` plus
a one-line JSON summary (shared harness: ``examples/_profile.py``).
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from examples._profile import init_bench_backend, profile_capture  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    jax, bench, dev, on_tpu = init_bench_backend()

    # exactly the bench/sweep workload (one shared definition, so the
    # trace explains the numbers those harnesses record)
    cfg, step, st0, batch, seq, n_params = bench.gpt_flash_setup(
        jax, on_tpu, seq=args.seq)

    profile_capture(
        "gpt_flash", jax, bench, step, st0, args.steps,
        {
            "batch": batch,
            "seq": seq,
            "tokens_per_sec": lambda dt: round(
                batch * seq * args.steps / dt, 1),
            "mfu": (lambda dt: round(
                bench._lm_train_flops(cfg, n_params, batch, seq)
                * args.steps / dt / bench._peak_flops(dev), 4))
            if on_tpu else None,
        })


if __name__ == "__main__":
    main()
