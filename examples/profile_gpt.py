"""Capture a jax.profiler trace of the flagship GPT train step on the
attached chip (VERDICT r2 item 3: the MFU gap "needs a profile, not a
guess").

    python examples/profile_gpt.py [--seq 1024] [--steps 5]

Writes a TensorBoard/XPlane trace directory under
``bench_results/profiles/<stamp>/`` plus a one-line JSON summary of
step time and MFU for the profiled configuration.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()

    import bench

    bench.enable_compilation_cache(jax)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    # exactly the bench/sweep workload (one shared definition, so the
    # trace explains the numbers those harnesses record)
    cfg, step, st, batch, seq, n_params = bench.gpt_flash_setup(
        jax, on_tpu, seq=args.seq)

    st = step(*st)  # compile + warm
    st = step(*st)
    jax.block_until_ready(st)

    stamp = time.strftime("%Y%m%d_%H%M%S")
    trace_dir = os.path.join(REPO, "bench_results", "profiles", stamp)
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        dt, st = bench._timeit(jax, step, st, args.steps)

    flops = bench._lm_train_flops(cfg, n_params, batch, seq) * args.steps / dt
    rec = {
        "trace_dir": os.path.relpath(trace_dir, REPO),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "batch": batch, "seq": seq, "steps": args.steps,
        "step_ms": round(dt / args.steps * 1e3, 2),
        "tokens_per_sec": round(batch * seq * args.steps / dt, 1),
        "mfu": round(flops / bench._peak_flops(dev), 4) if on_tpu else None,
        "ts": stamp,
    }
    out = os.path.join(REPO, "bench_results", "profiles", "summary.jsonl")
    with open(out, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
