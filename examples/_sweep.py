"""Shared subprocess-sweep driver for the hardware tuning harnesses.

Each grid point runs the real flagship train step in its own subprocess
(fresh backend: a wedge/OOM cannot kill the sweep) with the persistent
XLA compile cache on; the child prints one JSON record line, which the
driver appends to a jsonl and ranks by ``tokens_per_sec``.  Used by
``tune_flash_blocks.py`` (block_q/block_k knob) and ``tune_gpt_batch.py``
(batch knob).
"""

import json
import os
import subprocess
import sys


def run_sweep(points, *, env_for, child_args_for, label_for, out_path,
              timeout):
    """Run each point; return ``(best, records)`` — the top record by
    ``tokens_per_sec`` (None if every point failed) and the list of all
    successful records, so callers can gate decisions (e.g. auto-landing
    a tuned default) on how many points actually survived.

    ``env_for(pt)``: extra env vars for the child;
    ``child_args_for(pt)``: argv after ``sys.executable``;
    ``label_for(pt)``: stderr progress label.
    """
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    best, records = None, []
    for pt in points:
        env = dict(os.environ)
        env.update(env_for(pt))
        print(f"--- {label_for(pt)}", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(
                [sys.executable] + child_args_for(pt),
                env=env, capture_output=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            print(f"    timeout after {timeout:.0f}s",
                  file=sys.stderr, flush=True)
            continue
        if proc.returncode != 0:
            print("    rc=%d %s" % (
                proc.returncode,
                proc.stderr.decode(errors="replace")[-400:]),
                file=sys.stderr, flush=True)
            continue
        lines = proc.stdout.decode(errors="replace").strip().splitlines()
        if not lines:
            print("    rc=0 but empty stdout", file=sys.stderr, flush=True)
            continue
        line = lines[-1]
        try:
            rec = json.loads(line)
        except ValueError:
            print(f"    unparseable record: {line[-200:]}",
                  file=sys.stderr, flush=True)
            continue
        with open(out_path, "a") as f:
            f.write(line + "\n")
        print(f"    {rec.get('tokens_per_sec')} tok/s  mfu={rec.get('mfu')}",
              file=sys.stderr, flush=True)
        records.append(rec)
        if best is None or (rec.get("tokens_per_sec") or 0) > (
                best.get("tokens_per_sec") or 0):
            best = rec
    return best, records
