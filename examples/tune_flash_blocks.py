"""Flash-attention block-size sweep on hardware (VERDICT r2 item 3).

Round 2 shipped DEFAULT_BLOCK_Q=256 / DEFAULT_BLOCK_K=512 unswept; GPT-124M
MFU stalled at 0.436 while BERT hit 0.488.  This harness times the *actual
flagship train step* (the ``gpt_flash`` bench config) across a
(block_q, block_k) grid, each point in its own subprocess (fresh backend —
a wedge or OOM cannot kill the sweep) with the persistent compilation
cache on.

    python examples/tune_flash_blocks.py                 # full grid
    python examples/tune_flash_blocks.py --seq 2048      # long-seq grid
    python examples/tune_flash_blocks.py --one 256 512   # single point

Results append to ``bench_results/flash_block_sweep.jsonl``.  A TPU
sweep at the flagship seq (1024) auto-lands its winner in
``bench_results/flash_blocks_tuned.json``, which the kernel consults
lazily at first call and adopts only on a matching ``device_kind`` —
no manual default-picking needed (env overrides still win).
"""

import argparse
import itertools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "bench_results", "flash_block_sweep.jsonl")
if REPO not in sys.path:  # runnable as `python examples/tune_flash_blocks.py`
    sys.path.insert(0, REPO)

from examples._sweep import run_sweep  # noqa: E402

# jax's reference TPU flash kernel defaults to 128/128 (BlockSizes.
# get_default, with an open TODO for a real heuristic); cover that corner
# of the space as well as the larger tiles our defaults use.
GRID_Q = (128, 256, 512)
GRID_K = (128, 256, 512, 1024)


def run_point(block_q: int, block_k: int, seq: int, steps: int) -> None:
    """Child: one grid point — compile + time the gpt_flash train step
    (the exact workload of ``bench.gpt_flash_setup``, so sweep results
    transfer 1:1 to the bench/profile numbers)."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()

    import bench

    bench.enable_compilation_cache(jax)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if not on_tpu:  # CPU smoke: tiny shapes, still exercises the plumbing
        steps = min(steps, 2)

    cfg, step, st, batch, seq, n_params = bench.gpt_flash_setup(
        jax, on_tpu, seq=seq)

    t0 = time.perf_counter()
    st = step(*st)
    jax.block_until_ready(st)
    compile_s = time.perf_counter() - t0

    dt, _ = bench._timeit(jax, step, st, steps)

    tps = batch * seq * steps / dt
    flops = bench._lm_train_flops(cfg, n_params, batch, seq) * steps / dt
    rec = {
        "block_q": block_q, "block_k": block_k, "seq": seq,
        "batch": batch, "tokens_per_sec": round(tps, 1),
        "mfu": round(flops / bench._peak_flops(dev), 4) if on_tpu else None,
        "compile_s": round(compile_s, 1),
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(rec), flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--one", nargs=2, type=int, default=None,
                   metavar=("BLOCK_Q", "BLOCK_K"))
    p.add_argument("--timeout", type=float, default=420.0)
    args = p.parse_args()

    if args.one:
        grid = [tuple(args.one)]
    else:
        grid = list(itertools.product(GRID_Q, GRID_K))

    best, records = run_sweep(
        grid,
        env_for=lambda p: {"APEX_TPU_FLASH_BLOCK_Q": str(p[0]),
                           "APEX_TPU_FLASH_BLOCK_K": str(p[1])},
        child_args_for=lambda p: [
            os.path.abspath(__file__), "--child",
            str(p[0]), str(p[1]), str(args.seq), str(args.steps)],
        label_for=lambda p: (
            f"block_q={p[0]} block_k={p[1]} seq={args.seq}"),
        out_path=OUT, timeout=args.timeout)
    if best:
        print(json.dumps({"best": best}))
        # Land the winner automatically: a TPU sweep at the flagship seq
        # (1024) writes the tuned-defaults file that
        # apex_tpu.ops.flash_attention consults lazily at first kernel
        # call, gated on matching device_kind (env overrides still win) —
        # so an unattended chip-return capture upgrades the shipped
        # defaults without a source edit.
        # >1 successful point required: a lone survivor (others
        # wedged/OOMed) is no comparison.
        if (best["platform"] == "tpu" and args.seq == 1024
                and not args.one and len(records) > 1):
            tuned_path = os.path.join(REPO, "bench_results",
                                      "flash_blocks_tuned.json")
            with open(tuned_path, "w") as f:
                json.dump(best, f)
            print(f"tuned defaults written to {tuned_path}",
                  file=sys.stderr, flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        run_point(int(sys.argv[2]), int(sys.argv[3]),
                  int(sys.argv[4]), int(sys.argv[5]))
    else:
        main()
