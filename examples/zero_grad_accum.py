"""Flat-bucket ZeRO training with gradient accumulation — the analog of
the reference's ``DistributedFusedAdam`` examples
(``apex/contrib/test/optimizers/test_dist_adam.py`` usage shape).

One SPMD program: params replicated, optimizer state sharded 1/dp
(ZeRO-2), batch sharded on the data axes.  Each step accumulates
``MICROBATCHES`` local microbatch grads with NO collective, then the
optimizer's single flat-bucket reduce-scatter + all-gather runs once —
on a multi-slice mesh the reduction is hierarchical (reduce-scatter over
ICI ``dp``, shard all-reduce over DCN).

Run (CPU demo):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/zero_grad_accum.py
Run (TPU): python examples/zero_grad_accum.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import parallel
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.parallel import (
    dp_shard_batch,
    replicate,
    zero_data_parallel_train_step,
    zero_init,
)

MICROBATCHES = 4


def main(steps: int = 40):
    mesh = parallel.initialize_model_parallel()  # all devices on dp
    print(parallel.mesh.get_rank_info())

    D, H = 64, 128
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(D, H).astype(np.float32) / np.sqrt(D)),
        "w2": jnp.asarray(rng.randn(H, D).astype(np.float32) / np.sqrt(H)),
    }

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((jax.nn.relu(x @ p["w1"]) @ p["w2"] - y) ** 2)

    # n_buckets=2: XLA can overlap bucket 0's all-gather with bucket 1's
    # update tail; outer_axis="dcn" (default) makes the same config
    # hierarchical the moment the mesh spans slices.
    opt = DistributedFusedAdam(lr=1e-3, weight_decay=1e-2, n_buckets=2)
    params = replicate(params, mesh)
    opt_state = zero_init(opt, params, mesh)
    step = zero_data_parallel_train_step(
        loss_fn, opt, mesh=mesh, microbatches=MICROBATCHES)

    for i in range(steps):
        x = rng.randn(64 * MICROBATCHES, D).astype(np.float32)
        y = x  # identity target
        batch = dp_shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)
        params, opt_state, loss = step(params, opt_state, batch)
        if i % 5 == 0 or i == steps - 1:
            print(f"step {i:3d} loss {float(loss):.5f}")
    return float(loss)


if __name__ == "__main__":
    main()
