"""Flash-attention vs XLA-softmax attention microbenchmark.

Times the Pallas flash kernels against the unfused BMM+softmax+BMM core
(what ``CoreAttention`` uses when ``use_flash_attention=False``) for
causal training shapes, fwd+bwd — the evidence for flipping the
``use_flash_attention`` default (round-1 VERDICT "flash is never
exercised where it matters").

    python examples/bench_flash_attention.py            # current device
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.flash_attention import flash_attention

    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = jnp.dtype(args.dtype)
    shapes = ([(8, 12, 1024, 64), (4, 16, 2048, 64), (2, 16, 4096, 128)]
              if on_tpu else [(1, 2, 256, 32)])
    steps = args.steps if on_tpu else 3

    def xla_attn(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        s = s / (d ** 0.5)
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    results = []
    for b, h, s, d in shapes:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, s, d), dtype) for kk in ks)

        def bench(fn):
            loss = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(
                    fn(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))
            out = loss(q, k, v)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(steps):
                out = loss(q, k, v)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / steps

        t_flash = bench(lambda q, k, v: flash_attention(q, k, v,
                                                        causal=True))
        try:
            t_xla = bench(xla_attn)
        except Exception as e:  # O(s^2) scores can OOM at long seqlens
            t_xla = None
            print(f"xla path failed at s={s}: {e!r}", file=sys.stderr)
        results.append({
            "shape": [b, h, s, d],
            "t_flash_ms": round(t_flash * 1e3, 3),
            "t_xla_ms": round(t_xla * 1e3, 3) if t_xla else None,
            "speedup": round(t_xla / t_flash, 3) if t_xla else None,
        })

    print(json.dumps({
        "platform": jax.devices()[0].platform,
        "dtype": str(dtype),
        "fwd_bwd": True,
        "results": results,
    }))


if __name__ == "__main__":
    main()
