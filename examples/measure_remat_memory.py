"""Grouped-remat memory measurement on the real chip (VERDICT r2 item 9).

Round 2's 4-10x live-memory cut for ``pipeline_apply(remat_ticks=...)``
was measured only on the virtual CPU mesh
(``tests/test_pipeline_perf.py::test_grouped_remat_cuts_live_memory``).
This harness compiles the same interleaved forward+backward program **for
the attached TPU** (pp=1 on a single chip — the rotation scan, virtual
stages, and remat grouping are all still present) and records the
compiled executable's XLA memory analysis.  Compile-only: nothing runs,
so one wedge-free backend init is enough.

    python examples/measure_remat_memory.py            # default shapes
    python examples/measure_remat_memory.py --width 1024 --m 64

Appends to ``bench_results/remat_memory.jsonl`` (every record carries
its ``platform`` — the r4 VERDICT flagged a CPU record living under a
``_tpu``-suffixed filename as misleading artifact naming).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--mb", type=int, default=8)
    p.add_argument("--vpp", type=int, default=8)
    p.add_argument("--m", type=int, default=32)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        from apex_tpu.utils.platform import pin_cpu

        pin_cpu()

    from apex_tpu import parallel
    from apex_tpu.transformer.pipeline_parallel import stack_stage_params
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving as fb_interleaved,
    )

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    width, mb, vpp, m = args.width, args.mb, args.vpp, args.m
    if not on_tpu:
        width, m = min(width, 128), min(m, 8)

    parallel.initialize_model_parallel(
        pipeline_model_parallel_size=1, devices=jax.devices()[:1])

    def stage_fn(params, x):
        h = jnp.tanh(x @ params["w1"])
        return h @ params["w2"] + x

    ks = jax.random.split(jax.random.PRNGKey(0), vpp)
    stages = [
        {"w1": jax.random.normal(k, (width, width)) * 0.1,
         "w2": jax.random.normal(jax.random.fold_in(k, 1),
                                 (width, width)) * 0.1}
        for k in ks
    ]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, width))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (m, mb, width))

    def loss_fn(o, t):
        return jnp.sum((o - t) ** 2)

    def analyze(remat_ticks):
        def fb(params):
            _, grads = fb_interleaved(
                stage_fn, loss_fn, params, x, tgt, num_chunks=vpp,
                remat_ticks=remat_ticks)
            return grads

        t0 = time.perf_counter()
        ma = jax.jit(fb).lower(stacked).compile().memory_analysis()
        return {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "compile_s": round(time.perf_counter() - t0, 1),
        }

    flat = analyze(None)
    grouped = analyze(True)
    rec = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "width": width, "mb": mb, "vpp": vpp, "m": m,
        "flat": flat, "grouped": grouped,
        "temp_cut": round(flat["temp_bytes"]
                          / max(grouped["temp_bytes"], 1), 2),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = os.path.join(REPO, "bench_results", "remat_memory.jsonl")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
