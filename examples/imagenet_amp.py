"""ImageNet ResNet training — the analog of ``examples/imagenet/main_amp.py``.

The reference trains torchvision ResNet-50 with ``amp.initialize(opt_level)``,
``FusedSGD``/``FusedLAMB``, apex ``DistributedDataParallel`` and optional
``--sync_bn``, reading an ImageFolder tree with DistributedSampler DP
sharding (``main_amp.py:207-232``).  Here the same configuration space is
flags over one SPMD train step:

    # synthetic data (CI / smoke test):
    python examples/imagenet_amp.py --arch resnet50 --opt-level O2 \
        --optimizer sgd --sync-bn --batch-size 256 --steps 100

    # real data (directory of class subfolders, e.g. ImageNet train/):
    python examples/imagenet_amp.py --data /path/to/imagenet/train \
        --opt-level O2 --batch-size 256 --steps 500

Input pipeline (``apex_tpu.data``): PIL decode + RandomResizedCrop/flip in
a thread pool, Megatron-sampler DP sharding, and **uint8 batches** that are
normalized on-device inside the jitted step (the reference's
``fast_collate`` + CUDA prefetcher normalize, done the XLA way — the
divide/subtract fuses into the first conv).
"""

import argparse
import time

import jax
import jax.numpy as jnp

from apex_tpu import amp, parallel
from apex_tpu.data import (
    ImageFolder,
    ImageFolderLoader,
    PackedImageDataset,
    PackedLoader,
    normalize_on_device,
    pack_image_folder,
    prefetch_to_device,
    synthetic_image_batches,
)
from apex_tpu.data.packed import random_crop_flip
from apex_tpu.models import ResNet18, ResNet50, ResNet101
from apex_tpu.optimizers import FusedLAMB, FusedSGD
from apex_tpu.parallel import replicate

ARCHS = {"resnet18": ResNet18, "resnet50": ResNet50, "resnet101": ResNet101}


def _check_num_classes(classes, args):
    """Labels >= num_classes would be silently clamped by XLA's gather,
    training garbage with no diagnostic — reject up front."""
    if len(classes) > args.num_classes:
        raise SystemExit(
            f"dataset has {len(classes)} classes > --num-classes "
            f"{args.num_classes}")


def _split_dir(root, split):
    """The reference's layout: ``root/train`` + ``root/val``
    (``main_amp.py:205-206``).  A flat class-dir root (no ``train/`` AND
    no ``val/``) is used as-is for both splits (handy for smoke runs);
    a *partial* layout (one split dir present, the other missing) is an
    error — falling back silently would scan the wrong directory level
    and mislabel or crash after training."""
    import os

    have = {s: os.path.isdir(os.path.join(root, s))
            for s in ("train", "val")}
    if not any(have.values()):
        return root  # flat layout
    if not have[split]:
        raise SystemExit(
            f"--data {root!r} has a {'train' if have['train'] else 'val'}/ "
            f"subdirectory but no {split}/ — partial split layouts are "
            "ambiguous (reference layout: root/train + root/val)")
    return os.path.join(root, split)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, metavar="DIR",
                   help="ImageFolder root (class subdirectories); "
                        "synthetic data when omitted")
    p.add_argument("--arch", default="resnet50", choices=sorted(ARCHS))
    p.add_argument("--opt-level", default="O2", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--optimizer", default="sgd", choices=["sgd", "lamb"])
    p.add_argument("--sync-bn", action="store_true")
    p.add_argument("--batch-size", type=int, default=64,
                   help="global batch (all dp shards)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--backend", default="thread",
                   choices=["thread", "process"],
                   help="decode worker backend for the --data (JPEG) "
                        "path: 'process' is the true DataLoader("
                        "num_workers) analog (no GIL), 'thread' the "
                        "lower-fixed-cost fallback (docs/data.md)")
    p.add_argument("--packed", default=None, metavar="PREFIX",
                   help="train from a packed (decode-free) shard at "
                        "PREFIX (apex_tpu.data.packed). Missing shard + "
                        "--data: packs the train split there first. The "
                        "random crop/flip then runs on-device inside the "
                        "jitted step. Use when host decode can't feed "
                        "the chip (the reference recipe's DALI role).")
    p.add_argument("--evaluate", action="store_true",
                   help="run a validation pass (top-1/top-5) after "
                        "training — the reference's validate() loop "
                        "(main_amp.py:284-342); requires --data")
    args = p.parse_args(argv)
    if args.evaluate and args.data is None and args.packed is None:
        p.error("--evaluate requires --data or --packed")
    if args.evaluate and args.data is not None:
        _split_dir(args.data, "val")  # fail fast on partial layouts
    if args.evaluate and args.packed is not None:
        _packed_val_shard(args)  # pack/validate now, not after training

    mesh = parallel.initialize_model_parallel()
    print(parallel.mesh.get_rank_info())
    policy = amp.policy(args.opt_level)

    # Under the pjit train step the batch is a global dp-sharded array, so
    # BN statistics are global (SyncBN) regardless; axis_name would only be
    # needed in a shard_map-style loop. --sync-bn is accepted for CLI parity.
    model = ARCHS[args.arch](
        num_classes=args.num_classes,
        axis_name=None,
        dtype=policy.compute_dtype,
    )

    fake_x = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), fake_x, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    params = policy.cast_to_param(params)  # O2: half except norms

    if args.optimizer == "sgd":
        opt = FusedSGD(lr=args.lr, momentum=0.9, weight_decay=1e-4,
                       master_weights=policy.master_weights)
    else:
        opt = FusedLAMB(lr=args.lr, weight_decay=1e-4,
                        master_weights=policy.master_weights)
    opt_state = opt.init(params)

    def loss_fn(params, batch_stats, batch, key):
        x_uint8, y = batch
        if args.packed is not None:
            # packed records are stored at side > image_size: the train
            # crop + flip + normalize all happen here, on device, fused
            # into the step (packed.py module docstring)
            x = random_crop_flip(x_uint8, key, args.image_size,
                                 dtype=policy.compute_dtype)
        else:
            x = normalize_on_device(x_uint8, dtype=policy.compute_dtype)
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(logp[jnp.arange(y.shape[0]), y])
        return loss, mutated["batch_stats"]

    @jax.jit
    def train_step(params, batch_stats, opt_state, batch, key):
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch_stats, batch, key
        )
        params, opt_state = opt.step(grads, opt_state, params)
        return params, new_stats, opt_state, loss

    params = replicate(params, mesh)
    batch_stats = replicate(batch_stats, mesh)
    opt_state = replicate(opt_state, mesh)

    dp = parallel.mesh.get_data_parallel_world_size()
    if args.batch_size % dp != 0:
        raise SystemExit(
            f"--batch-size {args.batch_size} must be divisible by the "
            f"data-parallel world size ({dp})")
    # Per-host input sharding: each process decodes only the dp shards
    # its own devices hold (no redundant global decode) and places them
    # with dp_shard_batch(local_ranks=...).  Single-process: all ranks,
    # identical to the plain global placement.
    host_ranks = parallel.host_dp_ranks(mesh)
    host_sharded = len(host_ranks) < dp
    place = None
    if host_sharded:
        from apex_tpu.parallel import dp_shard_batch

        place = lambda b: dp_shard_batch(  # noqa: E731
            b, mesh, local_ranks=host_ranks)
        print(f"per-host input sharding: this process decodes dp ranks "
              f"{host_ranks} of {dp}")
    loader = None
    if args.packed is not None:
        import os

        if not os.path.exists(args.packed + ".json"):
            if args.data is None:
                raise SystemExit(
                    f"--packed {args.packed}: shard not found and no "
                    f"--data folder to pack it from")
            # store records slightly larger than the train crop so the
            # on-device random crop keeps translation augmentation (232
            # for the standard 224 recipe; small-image runs pack small so
            # the crop fraction — and H2D bytes — stay proportionate)
            side = (args.image_size + 8 if args.image_size < 224
                    else max(232, args.image_size + 8))
            print(f"packing {args.data} -> {args.packed} "
                  f"(one-time, side={side})")
            pds = pack_image_folder(
                _split_dir(args.data, "train"), args.packed, side=side,
                workers=args.workers)
        else:
            pds = PackedImageDataset(args.packed)
        if pds.side < args.image_size:
            # fail before training (and before a fresh multi-hour pack
            # would have): this shard cannot produce the requested crop
            raise SystemExit(
                f"--packed shard stores side={pds.side} < --image-size "
                f"{args.image_size}; re-pack with a larger side")
        _check_num_classes(pds.classes, args)
        print(f"Packed shard: {len(pds)} samples at side {pds.side}, "
              f"{len(pds.classes)} classes, dp={dp}")
        loader = PackedLoader(pds, local_batch=args.batch_size // dp,
                              data_parallel_size=dp,
                              dp_ranks=host_ranks if host_sharded else None)
    elif args.data is not None:
        dataset = ImageFolder(_split_dir(args.data, "train"))
        _check_num_classes(dataset.classes, args)
        print(f"ImageFolder: {len(dataset)} samples, "
              f"{len(dataset.classes)} classes, dp={dp}, "
              f"backend={args.backend}")
        loader = ImageFolderLoader(
            dataset, local_batch=args.batch_size // dp,
            data_parallel_size=dp, image_size=args.image_size,
            workers=args.workers, backend=args.backend,
            dp_ranks=host_ranks if host_sharded else None)
    else:
        synth = synthetic_image_batches(args.batch_size, args.image_size,
                                        args.num_classes)

    # H2D transfers run on the prefetcher's dedicated thread, 2 batches
    # ahead of the step loop (the reference data_prefetcher's side-stream
    # role; device_put is async under JAX), while the loader's decode
    # pool fills the batch after — stalls land in the data/stall_ms gauge.
    # The composition contract (docs/data.md): the prefetcher wraps the
    # LOADER directly — it is one epoch like the loader, so on epoch end
    # it is re-wrapped (close(close_source=False) keeps the decode pool).
    # The local_ranks placement applies ONLY to the loader branches (they
    # were built with dp_ranks=host_ranks); the synthetic stream yields
    # the GLOBAL batch on every host and uses the default placement.
    def wrap():
        if loader is not None:
            return prefetch_to_device(loader, mesh, depth=2, place=place)
        return prefetch_to_device(synth, mesh, depth=2)

    dev_it = wrap()

    def next_batch():
        nonlocal dev_it
        while True:
            try:
                return next(dev_it)
            except StopIteration:  # epoch end: next epoch's permutation
                dev_it.close(close_source=False)
                dev_it = wrap()

    t0 = time.perf_counter()
    loss = None
    try:
        aug_key = jax.random.PRNGKey(17)
        for i in range(args.steps):
            batch = next_batch()
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, batch,
                jax.random.fold_in(aug_key, i)
            )
            if i == 0:
                jax.block_until_ready(loss)
                t0 = time.perf_counter()  # exclude compile
            if i % 10 == 0 or i == args.steps - 1:
                print(f"step {i:4d} loss {float(loss):.4f}")
        jax.block_until_ready(loss)
    finally:
        dev_it.close()  # passthrough reclaims the decode pool too
    dt = time.perf_counter() - t0
    ips = args.batch_size * (args.steps - 1) / dt if args.steps > 1 else 0.0
    print(f"throughput: {ips:.1f} images/sec ({dt:.2f}s for {args.steps-1} steps)")
    # in-run input-stall telemetry (docs/data.md stall cookbook): the
    # prefetcher recorded every next() block into the default registry
    from apex_tpu.observability import default_registry

    hist = default_registry().histogram("span_ms/data/next_wait")
    if hist.count:
        print(f"input stall: mean {hist.mean:.2f} ms/step "
              f"(max {hist.max:.2f} ms over {hist.count} steps)")

    if args.evaluate:
        prec1, preck, k = validate(model, params, batch_stats, policy,
                                   mesh, args)
        print(f"validation: prec@1 {prec1:.3f}  prec@{k} {preck:.3f}")
    return ips


def _packed_val_shard(args):
    """Load (or pack, one-time) the eval shard at ``<packed>_val``.

    Packed at side == --image-size with the reference's proportional
    pre-resize, so the stored pixels are identical to the online JPEG
    eval transform (the on-device center crop degenerates to identity).
    Called from main() before training starts — a missing/mismatched
    shard must not cost a whole training run — and again from
    validate(), where the cached checks are instant.
    """
    import os

    vprefix = args.packed + "_val"
    if not os.path.exists(vprefix + ".json"):
        if args.data is None:
            raise SystemExit(
                f"--evaluate with --packed: val shard {vprefix} not "
                f"found and no --data folder to pack it from")
        val_dir = _split_dir(args.data, "val")
        if val_dir == args.data:
            print("warning: flat --data layout (no val/ split); the "
                  "packed 'val' shard will hold the training images "
                  "(train accuracy, not validation) — and will be "
                  "reused by later runs until deleted")
        print(f"packing val split -> {vprefix} (one-time)")
        pds_val = pack_image_folder(
            val_dir, vprefix, side=args.image_size, workers=args.workers)
    else:
        pds_val = PackedImageDataset(vprefix)
    if pds_val.side < args.image_size:
        raise SystemExit(
            f"val shard side={pds_val.side} < --image-size "
            f"{args.image_size}; re-pack it")
    _check_num_classes(pds_val.classes, args)
    return pds_val


def validate(model, params, batch_stats, policy, mesh, args):
    """One pass over the eval split: center-crop transform, running BN
    stats, top-1/top-5 accuracy — the reference's ``validate()`` +
    ``accuracy(output, target, topk=(1, 5))`` (``main_amp.py:284-342,
    391-403``), as a jitted eval step over the dp mesh.

    Covers **every** sample (the reference's non-drop_last val loader):
    images are walked in order and the final partial batch is padded to
    the fixed batch shape with a validity mask, so no tail is dropped,
    shapes stay static for jit, and sets smaller than one batch work.

    With ``--packed`` the val split is also packed (``PREFIX_val``,
    one-time, at side == --image-size with the reference's proportional
    pre-resize) and evaluated decode-free: sequential memmap slices,
    pixel-identical to the JPEG path's transform (the on-device center
    crop degenerates to identity at matching side).
    """
    import numpy as np

    from apex_tpu.data import center_crop_resize
    from apex_tpu.data.packed import center_crop as packed_center_crop
    from apex_tpu.parallel import dp_shard_batch

    k = min(5, args.num_classes)
    use_packed = args.packed is not None
    if use_packed:
        pds_val = _packed_val_shard(args)
        n_total = len(pds_val)
    else:
        val_dir = _split_dir(args.data, "val")
        if val_dir == args.data:
            print("warning: flat --data layout (no val/ split); evaluating "
                  "over the full folder (train accuracy, not validation)")
        dataset = ImageFolder(val_dir)
        n_total = len(dataset)

    @jax.jit
    def eval_step(params, batch_stats, batch):
        x_uint8, y, valid = batch
        if use_packed:
            # stored at shard side; crop + normalize on device
            x = packed_center_crop(x_uint8, args.image_size,
                                   dtype=policy.compute_dtype)
        else:
            x = normalize_on_device(x_uint8, dtype=policy.compute_dtype)
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=False)
        topk = jax.lax.top_k(logits.astype(jnp.float32), k)[1]
        hit1 = (topk[:, 0] == y) & valid
        hitk = (topk == y[:, None]).any(axis=1) & valid
        return jnp.sum(hit1), jnp.sum(hitk)

    batch = args.batch_size
    n = 0
    c1 = c5 = jnp.int32(0)  # device accumulators: no per-batch host sync

    def pad_batch(xs, ys):
        real = len(ys)
        pad = batch - real
        if pad:
            xs = np.concatenate([xs, np.repeat(xs[-1:], pad, axis=0)])
            ys = np.concatenate([ys, np.repeat(ys[-1:], pad)])
        valid = np.arange(batch) < real
        return dp_shard_batch(
            (xs, np.asarray(ys, np.int32), valid), mesh), real

    def batches():
        if use_packed:
            # sequential full-coverage slices (no sampler: eval must not
            # drop the tail, and order doesn't matter)
            for start in range(0, n_total, batch):
                stop = min(start + batch, n_total)
                yield pad_batch(np.asarray(pds_val.images[start:stop]),
                                np.asarray(pds_val.labels[start:stop]))
            return

        from concurrent.futures import ThreadPoolExecutor

        def decode(i):
            img, label = dataset.load(i)
            return center_crop_resize(img, args.image_size), label

        with ThreadPoolExecutor(max_workers=args.workers) as pool:
            starts = list(range(0, n_total, batch))
            submit = lambda s: [  # noqa: E731
                pool.submit(decode, i)
                for i in range(s, min(s + batch, n_total))]
            pending = submit(starts[0])
            for j in range(len(starts)):
                futs = pending
                if j + 1 < len(starts):
                    # submit j+1 BEFORE blocking on j's stragglers: freed
                    # workers roll straight into the next batch
                    pending = submit(starts[j + 1])
                decoded = [f.result() for f in futs]
                yield pad_batch(np.stack([d[0] for d in decoded]),
                                np.asarray([d[1] for d in decoded]))

    for batch_dev, n_real in batches():
        h1, h5 = eval_step(params, batch_stats, batch_dev)
        c1 = c1 + h1
        c5 = c5 + h5
        n += n_real
    return (int(c1) / max(n, 1), int(c5) / max(n, 1), k)


if __name__ == "__main__":
    main()
