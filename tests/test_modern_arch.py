"""Modern-architecture options: RoPE / NoPE, grouped-query attention,
SwiGLU (parity-plus — the reference's testing GPT is learned-positions/
MHA/GeLU only; these come from its Megatron lineage).

Contracts tested:
- defaults reproduce the reference stack exactly (GQA with
  groups == heads is bit-identical to the old MHA layout);
- RoPE numerics match a direct implementation, and attention under RoPE
  is a function of relative position only;
- each option trains, agrees between the flash and fused-softmax
  attention paths, and is TP-exact (tp=8 shard_map loss == the same
  global params on the tp=1 model);
- RoPE composes with context parallelism (ring attention) — the shard
  offset feeds each rank global positions.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import collectives as cc
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.rope import apply_rotary, rotary_cos_sin
from apex_tpu.transformer.testing import GPTModel, TransformerConfig

VOCAB, SEQ, BATCH = 64, 16, 4


def small_cfg(**kw):
    base = dict(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens_for(seed):
    return jax.random.randint(jax.random.PRNGKey(seed), (BATCH, SEQ), 0,
                              VOCAB)


def train_a_bit(cfg, steps=25, seed=0):
    model = GPTModel(cfg)
    tokens = tokens_for(seed)
    params = model.init(jax.random.PRNGKey(seed + 1), tokens)["params"]
    opt = FusedAdam(lr=2e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            return jnp.mean(model.apply({"params": p}, tokens,
                                        labels=tokens))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    return params, losses


# ---------------------------------------------------------------- RoPE unit

def test_rotary_matches_direct_implementation():
    s, b, n, d = 6, 2, 3, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (s, b, n, d))
    pos = jnp.arange(s)
    cos, sin = rotary_cos_sin(pos, d, base=10000.0)
    got = apply_rotary(x, cos, sin)

    inv = 1.0 / 10000.0 ** (np.arange(0, d, 2) / d)
    ang = np.asarray(pos)[:, None] * inv[None, :]  # [s, d/2]
    xn = np.asarray(x)
    x1, x2 = xn[..., : d // 2], xn[..., d // 2:]
    c = np.cos(ang)[:, None, None, :]
    sn = np.sin(ang)[:, None, None, :]
    want = np.concatenate([x1 * c - x2 * sn, x2 * c + x1 * sn], -1)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, atol=1e-6)


def test_rotary_partial_dim_passthrough():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 2, 8))
    cos, sin = rotary_cos_sin(jnp.arange(4), 4)  # rotate 4 of 8 channels
    out = apply_rotary(x, cos, sin)
    np.testing.assert_array_equal(np.asarray(out[..., 4:]),
                                  np.asarray(x[..., 4:]))
    assert not np.allclose(np.asarray(out[..., 1:4]),
                           np.asarray(x[..., 1:4]))


def test_rotary_scores_depend_on_relative_position_only():
    """q_i . k_j after rotation must be invariant to a global shift of
    both positions — the property that makes RoPE RoPE."""
    d = 16
    q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, d))
    k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, d))

    def score(qi, kj):
        cq = rotary_cos_sin(jnp.array([qi]), d)
        ck = rotary_cos_sin(jnp.array([kj]), d)
        return float(jnp.sum(apply_rotary(q, *cq) * apply_rotary(k, *ck)))

    for delta in (1, 7, 100):
        np.testing.assert_allclose(score(5, 3), score(5 + delta, 3 + delta),
                                   rtol=1e-5)


def test_rotary_rejects_odd_dim():
    with pytest.raises(ValueError, match="even"):
        rotary_cos_sin(jnp.arange(4), 5)


# ------------------------------------------------- defaults stay reference

def test_gqa_groups_equal_heads_is_bit_identical_to_mha():
    """num_query_groups == heads must produce the SAME param tree and the
    SAME logits as the default — the group-major fused-QKV layout
    degenerates to the per-head [q|k|v] triples."""
    tokens = tokens_for(7)
    logits = {}
    shapes = {}
    for name, cfg in [("default", small_cfg()),
                      ("explicit", small_cfg(num_query_groups=4))]:
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(8), tokens)["params"]
        logits[name] = model.apply({"params": params}, tokens)
        shapes[name] = jax.tree_util.tree_map(jnp.shape, params)
    assert shapes["default"] == shapes["explicit"]
    np.testing.assert_array_equal(np.asarray(logits["default"]),
                                  np.asarray(logits["explicit"]))


def test_config_validation():
    with pytest.raises(ValueError, match="num_query_groups"):
        small_cfg(num_query_groups=3)  # does not divide 4 heads
    with pytest.raises(ValueError, match="num_query_groups"):
        small_cfg(num_query_groups=0)
    with pytest.raises(ValueError, match="position_embedding_type"):
        small_cfg(position_embedding_type="alibi")
    with pytest.raises(ValueError, match="rotary_percent"):
        small_cfg(rotary_percent=1.5)
    with pytest.raises(ValueError, match="rotary_percent"):
        small_cfg(rotary_percent=0.0)


def test_rope_rejects_custom_position_ids():
    """Silently dropping caller position_ids under rope would mis-rotate
    packed sequences — must raise instead."""
    cfg = small_cfg(position_embedding_type="rope")
    model = GPTModel(cfg)
    tokens = tokens_for(20)
    params = model.init(jax.random.PRNGKey(21), tokens)["params"]
    pos = jnp.zeros_like(tokens)
    with pytest.raises(NotImplementedError, match="position_ids"):
        model.apply({"params": params}, tokens, position_ids=pos)


# ------------------------------------------------------- each option works

@pytest.mark.parametrize("opts", [
    dict(position_embedding_type="rope"),
    dict(position_embedding_type="rope", rotary_percent=0.5),
    dict(position_embedding_type="none"),
    dict(num_query_groups=2),
    dict(num_query_groups=1),  # MQA
    dict(swiglu=True),
    dict(position_embedding_type="rope", num_query_groups=2, swiglu=True),
])
def test_option_trains(opts):
    params, losses = train_a_bit(small_cfg(**opts))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.8 * losses[0]
    layer0 = params["language_model"]["encoder"]["layers_0"]
    if opts.get("swiglu"):
        assert "dense_h_to_4h_gate" in layer0["mlp"]
    if opts.get("position_embedding_type") in ("rope", "none"):
        assert "position_embeddings" not in params["language_model"][
            "embedding"]
    g = opts.get("num_query_groups")
    if g:
        d = 32 // 4
        kern = layer0["self_attention"]["query_key_value"]["kernel"]
        assert kern.shape[0] == (4 + 2 * g) * d


@pytest.mark.parametrize("opts", [
    dict(position_embedding_type="rope"),
    dict(num_query_groups=2),
    dict(position_embedding_type="rope", num_query_groups=1, swiglu=True),
])
def test_flash_matches_softmax_path(opts):
    """Flash and fused-softmax attention agree under each option (RoPE and
    the GQA broadcast happen upstream of the core, so both cores must see
    equivalent q/k/v)."""
    tokens = tokens_for(9)
    cfg = small_cfg(**opts)
    model_ref = GPTModel(cfg)
    params = model_ref.init(jax.random.PRNGKey(10), tokens)["params"]
    logits_ref = model_ref.apply({"params": params}, tokens)
    model_fl = GPTModel(dataclasses.replace(cfg, use_flash_attention=True))
    logits_fl = model_fl.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(logits_fl),
                               np.asarray(logits_ref), rtol=5e-5, atol=5e-5)


# --------------------------------------------------------------- TP parity

@pytest.mark.slow
def test_modern_stack_tp_parity_and_trains():
    """rope + GQA (2 heads/group) + swiglu under tp=8 shard_map: loss
    matches the same global params on the tp=1 model, and training
    decreases it (the test_gpt_tensor_parallel_trains harness with the
    modern options on)."""
    TP = 8
    parallel.initialize_model_parallel(tensor_model_parallel_size=TP)
    cfg = small_cfg(tensor_axis="tp", num_attention_heads=16,
                    num_query_groups=8, swiglu=True,
                    position_embedding_type="rope")
    model = GPTModel(cfg)
    tokens = tokens_for(11)

    def tp_init(tokens):
        return model.init(jax.random.PRNGKey(12), tokens)["params"]

    param_specs = tp.infer_param_specs(jax.eval_shape(tp_init, tokens))
    # the swiglu gate must be column-sharded, not silently replicated
    gate_spec = param_specs["language_model"]["encoder"]["layers_0"][
        "mlp"]["dense_h_to_4h_gate"]["kernel"]
    assert gate_spec == P("tp", None)
    params = cc.shard_over(tp_init, in_specs=P(),
                           out_specs=param_specs)(tokens)

    def tp_loss(p, t):
        return jax.lax.pmean(
            jnp.mean(model.apply({"params": p}, t, labels=t)), "tp")

    loss_f = cc.shard_over(tp_loss, in_specs=(param_specs, P()),
                           out_specs=P())
    loss0 = float(loss_f(params, tokens))

    cfg1 = dataclasses.replace(cfg, tensor_axis=None)
    losses1 = GPTModel(cfg1).apply(
        {"params": jax.device_get(params)}, tokens, labels=tokens)
    np.testing.assert_allclose(loss0, float(jnp.mean(losses1)), rtol=1e-5)

    opt = FusedAdam(lr=1e-3)
    state0 = jax.eval_shape(opt.init, params)
    state_specs = type(state0)(
        step=P(),
        slots={k: param_specs for k in state0.slots},
        master=param_specs if state0.master is not None else None,
    )
    state = cc.shard_over(opt.init, in_specs=(param_specs,),
                          out_specs=state_specs)(params)

    @jax.jit
    def step(params, state, t):
        def local(p, s, t):
            g = jax.grad(tp_loss)(p, t)
            new_p, new_s = opt.step(g, s, p)
            return new_p, new_s, tp_loss(p, t)
        return cc.shard_over(
            local, in_specs=(param_specs, state_specs, P()),
            out_specs=(param_specs, state_specs, P()),
        )(params, state, t)

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------- CP + GQA

@pytest.mark.slow
@pytest.mark.parametrize("impl,g", [
    ("ring", 2),
    ("ulysses", 2),   # g % cp != 0: expand-before-a2a fallback
    ("ulysses", 4),   # g % cp == 0: compact g-head a2a + post-broadcast
])
def test_cp_attention_grouped_kv_matches_expanded(impl, g):
    """ring/ulysses accept compact g-head K/V (only the grouped K/V
    travels the interconnect) — output and q/k/v grads must match the
    same attention fed pre-broadcast h-head K/V."""
    from apex_tpu.transformer import context_parallel as cp_lib

    CP, b, h, s, d = 4, 2, 8, 32, 8
    parallel.initialize_model_parallel(context_parallel_size=CP)
    ks = jax.random.split(jax.random.PRNGKey(15), 3)
    q = jax.random.normal(ks[0], (b, h, s, d))
    k = jax.random.normal(ks[1], (b, g, s, d))
    v = jax.random.normal(ks[2], (b, g, s, d))
    k_exp = jnp.repeat(k, h // g, axis=1)
    v_exp = jnp.repeat(v, h // g, axis=1)
    attn = cp_lib.ring_attention if impl == "ring" \
        else cp_lib.ulysses_attention

    def run(fn):
        # sequence dim sharded over cp (dim 2 of [b, h, s, d])
        spec = P(None, None, "cp", None)
        return cc.shard_over(
            fn, in_specs=(spec,) * 3, out_specs=P(None, None, "cp", None))

    def loss_grouped(q, k, v):
        return jnp.sum(attn(q, k, v, axis="cp", causal=True) ** 2)

    out_g = run(lambda q, k, v: attn(q, k, v, axis="cp", causal=True))(
        q, k, v)
    out_e = run(lambda q, k, v: attn(q, k, v, axis="cp", causal=True))(
        q, k_exp, v_exp)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e),
                               rtol=2e-5, atol=2e-5)

    gq, gk, gv = cc.shard_over(
        jax.grad(loss_grouped, argnums=(0, 1, 2)),
        in_specs=(P(None, None, "cp", None),) * 3,
        out_specs=(P(None, None, "cp", None),) * 3)(q, k, v)
    eq, ek, ev = cc.shard_over(
        jax.grad(loss_grouped, argnums=(0, 1, 2)),
        in_specs=(P(None, None, "cp", None),) * 3,
        out_specs=(P(None, None, "cp", None),) * 3)(q, k_exp, v_exp)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(eq),
                               rtol=2e-5, atol=2e-5)
    # grouped k/v grads are the group-sums of the expanded ones
    np.testing.assert_allclose(
        np.asarray(gk),
        np.asarray(ek).reshape(b, g, h // g, s, d).sum(2),
        rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(gv),
        np.asarray(ev).reshape(b, g, h // g, s, d).sum(2),
        rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_gqa_under_cp_gpt_matches_serial():
    """End-to-end: GQA GPT under ring context parallelism matches the
    same params on the full sequence (grouped K/V on the ring vs the
    repeat in the single-device core)."""
    from apex_tpu.transformer.testing.gpt_cp_train import build_gpt_cp

    CP, seq = 4, 32
    mesh = parallel.initialize_model_parallel(context_parallel_size=CP)
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
        use_flash_attention=True, context_axis="cp", num_query_groups=2,
    )
    init_fn, make_loss_fn, _ = build_gpt_cp(cfg, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(16), (4, seq), 0, VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(17), tokens)
    l_cp = float(jax.jit(make_loss_fn(specs))(params, tokens))
    l_serial = float(_serial_gpt_loss(cfg, params, tokens, seq))
    np.testing.assert_allclose(l_cp, l_serial, rtol=1e-5)


def _serial_gpt_loss(cfg, params, tokens, seq):
    """Same modules/params, context_axis off, full sequence."""
    from apex_tpu.ops.softmax import AttnMaskType
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        Embedding,
        ParallelTransformerLayer,
        parallel_lm_logits,
    )

    scfg = dataclasses.replace(cfg, context_axis=None)
    h = Embedding(scfg).apply({"params": params["embedding"]}, tokens)
    layer = ParallelTransformerLayer(
        scfg, self_attn_mask_type=AttnMaskType.causal)
    for i in range(scfg.num_layers):
        h = layer.apply({"params": params[f"layer_{i}"]}, h, None)
    h = FusedLayerNorm(scfg.hidden_size, eps=scfg.layernorm_epsilon).apply(
        {"params": params["final_ln"]}, h)
    logits = parallel_lm_logits(
        h, params["embedding"]["word_embeddings"]["embedding"], scfg)
    per_tok = softmax_cross_entropy_loss(
        jnp.transpose(logits[:-1], (1, 0, 2)).reshape(-1, VOCAB)
        .astype(jnp.float32),
        tokens[:, 1:].reshape(-1), padding_idx=-1)
    return jnp.mean(per_tok)


# --------------------------------------------------------------- CP + RoPE

@pytest.mark.slow
def test_rope_under_context_parallel_matches_serial():
    """Ring attention with RoPE: each cp rank rotates its local shard
    with GLOBAL positions (axis_index offset) — parity against the same
    params on the full sequence, single device, proves the offsets."""
    from apex_tpu.transformer.testing.gpt_cp_train import build_gpt_cp

    CP = 4
    seq = 32
    mesh = parallel.initialize_model_parallel(context_parallel_size=CP)
    cfg = TransformerConfig(
        hidden_size=32, num_layers=2, num_attention_heads=4,
        padded_vocab_size=VOCAB, max_position_embeddings=seq,
        hidden_dropout=0.0, attention_dropout=0.0, tensor_axis=None,
        use_flash_attention=True, context_axis="cp",
        position_embedding_type="rope",
    )
    init_fn, make_loss_fn, _ = build_gpt_cp(cfg, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(13), (4, seq), 0, VOCAB)
    params, specs = init_fn(jax.random.PRNGKey(14), tokens)
    l_cp = float(jax.jit(make_loss_fn(specs))(params, tokens))
    l_serial = float(_serial_gpt_loss(cfg, params, tokens, seq))
    np.testing.assert_allclose(l_cp, l_serial, rtol=1e-5)
