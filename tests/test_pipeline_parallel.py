"""Pipeline-schedule numerics on the virtual CPU mesh.

Mirrors the reference's
``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py:99-170``:
forward/backward parity of no-pipelining vs 1F1B vs interleaved across
pp grids, checked against a single-device sequential reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import parallel
from apex_tpu.transformer import pipeline_parallel as pp_lib
from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)

pytestmark = pytest.mark.slow

HID = 8
MB = 2  # microbatch size


def stage_fn(params, x):
    """One homogeneous stage: linear + gelu + linear (same structure every
    virtual stage, the rotation contract)."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + x


def make_stage_params(key, n_stages):
    ks = jax.random.split(key, n_stages)
    per_stage = [
        {
            "w1": jax.random.normal(k, (HID, HID)) * 0.3,
            "b1": jnp.zeros((HID,)),
            "w2": jax.random.normal(jax.random.fold_in(k, 1), (HID, HID)) * 0.3,
        }
        for k in ks
    ]
    return pp_lib.stack_stage_params(per_stage), per_stage


def sequential_reference(per_stage, x_mb, targets):
    """Ground truth: apply the stages in order per microbatch, sum losses."""
    def full(per_stage, x_mb):
        outs = []
        for i in range(x_mb.shape[0]):
            h = x_mb[i]
            for p in per_stage:
                h = stage_fn(p, h)
            outs.append(h)
        return jnp.stack(outs)

    def loss(per_stage):
        outs = full(per_stage, x_mb)
        return jnp.sum((outs - targets) ** 2), outs

    grads, outs = jax.grad(loss, has_aux=True)(per_stage)
    return outs, grads


def loss_fn(out, tgt):
    return jnp.sum((out - tgt) ** 2)


@pytest.mark.parametrize("pp,vpp,m", [(4, 1, 4), (4, 1, 8), (2, 2, 4),
                                      (2, 2, 6), (4, 2, 8), (2, 3, 4)])
def test_pipeline_matches_sequential(pp, vpp, m):
    parallel.initialize_model_parallel(pipeline_model_parallel_size=pp)
    n_virtual = pp * vpp
    key = jax.random.PRNGKey(0)
    stacked, per_stage = make_stage_params(key, n_virtual)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, MB, HID))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (m, MB, HID))

    ref_outs, ref_grads = sequential_reference(per_stage, x, tgt)

    outs = pp_lib.pipeline_apply(stage_fn, stacked, x, num_chunks=vpp)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref_outs),
                               rtol=1e-5, atol=1e-5)

    fwd_bwd = pp_lib.get_forward_backward_func(
        vpp if vpp > 1 else None, pp
    )
    losses, grads = fwd_bwd(stage_fn, loss_fn, stacked, x, tgt)
    ref_losses = jax.vmap(loss_fn)(ref_outs, tgt)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref_losses),
                               rtol=1e-5, atol=1e-5)
    ref_stacked = pp_lib.stack_stage_params(
        [ref_grads[v] for v in range(n_virtual)]
    )
    for name in ("w1", "b1", "w2"):
        np.testing.assert_allclose(
            np.asarray(grads[name]), np.asarray(ref_stacked[name]),
            rtol=1e-4, atol=1e-4,
        )


def test_no_pipelining_matches_single_backward():
    """fwd_bwd_no_pipelining.py:23 — grad accumulation over microbatches."""
    key = jax.random.PRNGKey(3)
    stacked, per_stage = make_stage_params(key, 2)
    m = 4
    x = jax.random.normal(jax.random.PRNGKey(4), (m, MB, HID))
    tgt = jax.random.normal(jax.random.PRNGKey(5), (m, MB, HID))

    def model_fn(params, inp):
        h = stage_fn(jax.tree_util.tree_map(lambda l: l[0], params), inp)
        return stage_fn(jax.tree_util.tree_map(lambda l: l[1], params), h)

    fwd_bwd = pp_lib.get_forward_backward_func(None, 1)
    losses, grads = fwd_bwd(model_fn, loss_fn, stacked, x, tgt)

    def total(params):
        outs = jax.vmap(lambda i, t: loss_fn(model_fn(params, i), t))(x, tgt)
        return jnp.sum(outs), outs

    ref_grads, ref_losses = jax.grad(total, has_aux=True)(stacked)
    np.testing.assert_allclose(np.asarray(losses), np.asarray(ref_losses),
                               rtol=1e-5)
    for name in ("w1", "b1", "w2"):
        np.testing.assert_allclose(np.asarray(grads[name]),
                                   np.asarray(ref_grads[name]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_under_jit_and_loss_scale():
    """Whole fwd_bwd must be jittable (the production path) and honor
    loss_scale (GradScaler interop, transformer/amp/grad_scaler.py:21)."""
    pp, m = 4, 4
    parallel.initialize_model_parallel(pipeline_model_parallel_size=pp)
    stacked, per_stage = make_stage_params(jax.random.PRNGKey(6), pp)
    x = jax.random.normal(jax.random.PRNGKey(7), (m, MB, HID))
    tgt = jax.random.normal(jax.random.PRNGKey(8), (m, MB, HID))

    @jax.jit
    def run(stacked):
        return pp_lib.forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, stacked, x, tgt, loss_scale=8.0
        )

    losses, grads = run(stacked)
    _, ref_grads = pp_lib.forward_backward_pipelining_without_interleaving(
        stage_fn, loss_fn, stacked, x, tgt
    )
    np.testing.assert_allclose(np.asarray(grads["w1"]),
                               8.0 * np.asarray(ref_grads["w1"]),
                               rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# microbatch calculators (reference tests/L0/run_transformer/test_microbatches.py)
# ---------------------------------------------------------------------------


def test_constant_microbatches():
    c = ConstantNumMicroBatches(32, 2, 4)
    assert c.get() == 4
    assert c.get_current_global_batch_size() == 32
    with pytest.raises(ValueError):
        ConstantNumMicroBatches(30, 2, 4)


def test_rampup_microbatches():
    c = RampupBatchsizeNumMicroBatches(
        start_batch_size=4, batch_size_increment=4, ramup_samples=64,
        global_batch_size=16, micro_batch_size=1, data_parallel_size=2,
    )
    assert c.get_current_global_batch_size() == 4
    c.update(0, True)
    assert c.get() == 2
    c.update(32, True)
    # 3 increments over 64 samples -> one increment per 21.33 samples;
    # int(32/21.33) = 1 step -> 4 + 4 = 8 (microbatches.py:112-194 math).
    assert c.get_current_global_batch_size() == 8
    c.update(64, True)
    assert c.get_current_global_batch_size() == 16
    c.update(1000, True)
    assert c.get_current_global_batch_size() == 16
    assert c.get() == 8


def test_rampup_degenerate_cases():
    """start == global and ramup_samples == 0 must not divide by zero."""
    c = RampupBatchsizeNumMicroBatches(16, 4, 64, 16, 1, 2)
    assert c.get_current_global_batch_size() == 16
    c = RampupBatchsizeNumMicroBatches(4, 4, 0, 16, 1, 2)
    c.update(0, True)
    assert c.get_current_global_batch_size() == 16


def test_ltor_masks_reset_semantics():
    """utils.py:303-355: EOD keeps its in-document position; positions reset
    only after it; attention blocked across documents."""
    from apex_tpu.transformer.pipeline_parallel.utils import (
        get_ltor_masks_and_position_ids,
    )
    data = jnp.array([[10, 11, 99, 12, 13]])
    am, lm, pid = get_ltor_masks_and_position_ids(
        data, eod_token=99, reset_position_ids=True,
        reset_attention_mask=True, eod_mask_loss=True,
    )
    np.testing.assert_array_equal(np.asarray(pid[0]), [0, 1, 2, 0, 1])
    assert float(lm[0, 2]) == 0.0 and float(lm[0, 1]) == 1.0
    # position 3 (doc 2) must not attend to position 1 (doc 1)
    assert bool(am[0, 0, 3, 1]) is True
    # causal within doc: position 4 attends to 3
    assert bool(am[0, 0, 4, 3]) is False


def test_build_factory():
    c = build_num_microbatches_calculator(
        0, None, global_batch_size=8, micro_batch_size=2,
        data_parallel_size=2,
    )
    assert isinstance(c, ConstantNumMicroBatches)
    c = build_num_microbatches_calculator(
        0, [4, 4, 64], global_batch_size=16, micro_batch_size=1,
        data_parallel_size=2,
    )
    assert isinstance(c, RampupBatchsizeNumMicroBatches)


def test_split_into_microbatches():
    batch = {"x": jnp.arange(24.0).reshape(12, 2)}
    mbs = pp_lib.split_into_microbatches(batch, 4)
    assert mbs["x"].shape == (4, 3, 2)
    with pytest.raises(ValueError):
        pp_lib.split_into_microbatches(batch, 5)


@pytest.mark.parametrize("pp,vpp,m,g", [(4, 1, 8, True), (2, 2, 6, 3),
                                        (4, 2, 8, True), (4, 1, 4, 5)])
def test_grouped_remat_matches_flat(pp, vpp, m, g):
    """remat_ticks (two-level checkpointed tick groups, incl. a group size
    that does not divide the tick count) must be numerically identical to
    the flat scan, forward and backward."""
    parallel.initialize_model_parallel(pipeline_model_parallel_size=pp)
    n_virtual = pp * vpp
    stacked, per_stage = make_stage_params(jax.random.PRNGKey(0), n_virtual)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, MB, HID))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (m, MB, HID))

    outs_flat = pp_lib.pipeline_apply(stage_fn, stacked, x, num_chunks=vpp)
    outs_grp = pp_lib.pipeline_apply(stage_fn, stacked, x, num_chunks=vpp,
                                     remat_ticks=g)
    np.testing.assert_allclose(np.asarray(outs_grp), np.asarray(outs_flat),
                               rtol=1e-6, atol=1e-6)

    def run(remat_ticks):
        if vpp > 1:
            return pp_lib.forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, stacked, x, tgt, num_chunks=vpp,
                remat_ticks=remat_ticks)
        return pp_lib.forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, stacked, x, tgt, remat_ticks=remat_ticks)

    losses_flat, grads_flat = run(None)
    losses_grp, grads_grp = run(g)
    np.testing.assert_allclose(np.asarray(losses_grp),
                               np.asarray(losses_flat),
                               rtol=1e-6, atol=1e-6)
    for name in ("w1", "b1", "w2"):
        np.testing.assert_allclose(
            np.asarray(grads_grp[name]), np.asarray(grads_flat[name]),
            rtol=1e-5, atol=1e-5,
        )


def test_grouped_remat_cache_miss_warning():
    """Fresh stage_fn closures per call (same code object, new identity)
    defeat the identity-keyed grouped-remat jit cache; after
    _GROUPED_JIT_MISS_WARN_AT identity-driven misses a warning tells the
    caller to hoist stage_fn.  A stable stage_fn never warns (ADVICE r2:
    schedules.py _GROUPED_JIT_CACHE identity-keying footgun)."""
    import warnings

    parallel.initialize_model_parallel(pipeline_model_parallel_size=4)
    stacked, _ = make_stage_params(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, MB, HID))

    pp_lib.schedules._GROUPED_JIT_CACHE.clear()
    pp_lib.schedules._GROUPED_JIT_MISSES.clear()

    # stable stage_fn, varying shapes: legitimate misses, no warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for m in (2, 4, 6, 8, 10):
            xi = jax.random.normal(jax.random.PRNGKey(2), (m, MB, HID))
            pp_lib.pipeline_apply(stage_fn, stacked, xi, remat_ticks=True)

    # fresh closure per call, same everything else: warns at the threshold
    with pytest.warns(UserWarning, match="hoist it out of the step loop"):
        for _ in range(pp_lib.schedules._GROUPED_JIT_MISS_WARN_AT + 1):
            fresh = lambda p, h: stage_fn(p, h)  # noqa: E731
            pp_lib.pipeline_apply(fresh, stacked, x, remat_ticks=True)


@pytest.mark.parametrize("pp,m", [(4, 8)])
def test_grouped_remat_with_sharded_microbatches(pp, m):
    """remat_ticks composes with shard_microbatches (1/pp input/output
    buffers AND O(T/G) boundary residuals) — forward *and* backward: the
    owner-masked exit psum lives inside the checkpointed group, so its
    transpose is replayed during group recompute."""
    parallel.initialize_model_parallel(pipeline_model_parallel_size=pp)
    stacked, per_stage = make_stage_params(jax.random.PRNGKey(0), pp)
    x = jax.random.normal(jax.random.PRNGKey(1), (m, MB, HID))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (m, MB, HID))
    ref_outs, ref_grads = sequential_reference(per_stage, x, tgt)

    def total_loss(params, remat_ticks):
        outs = pp_lib.pipeline_apply(stage_fn, params, x,
                                     remat_ticks=remat_ticks,
                                     shard_microbatches=True)
        return jnp.sum((outs - tgt) ** 2), outs

    @jax.jit
    def run(params):
        grads, outs = jax.grad(lambda p: total_loss(p, True),
                               has_aux=True)(params)
        return grads, outs

    grads, outs = run(stacked)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref_outs),
                               rtol=1e-5, atol=1e-5)
    ref_stacked = pp_lib.stack_stage_params(
        [ref_grads[v] for v in range(pp)])
    for name in ("w1", "b1", "w2"):
        np.testing.assert_allclose(
            np.asarray(grads[name]), np.asarray(ref_stacked[name]),
            rtol=1e-4, atol=1e-4,
        )
