"""Smoke-test the driver entry's multichip staging path (ISSUE 2 satellite).

``__graft_entry__.dryrun_multichip`` regressed silently for a full round:
it runs only via the driver, so a jax-version-specific staging failure (the
old-shard_map ``_SpecError`` on a scalar loss under ``value_and_grad``)
never showed up in the test suite.  This fast-tier test runs the real
dryrun in a subprocess — exactly how the driver does, and required anyway
because ``--xla_force_host_platform_device_count`` must precede backend
init — so the 3D trainer's staging can never silently regress again.
"""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_8_exits_zero():
    env = dict(os.environ)
    # A clean slate for the child: the parent's test flags must not leak
    # (the dryrun pins CPU and sets its own device count).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=_REPO, env=env, capture_output=True, timeout=540,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) rc={proc.returncode}\n"
        f"stderr tail:\n{proc.stderr.decode(errors='replace')[-2000:]}"
    )
