"""Crash-safe checkpointing, driven by the fault-injection harness.

Every claim the resilience layer makes is proven here by injecting the
actual failure (``apex_tpu.testing.faults``), fast-tier: checksummed
atomic writes, ``verify_checkpoint`` catching bit flips and torn files,
``CheckpointManager`` retention / retry-with-backoff /
``restore_latest`` fallback past corruption with bit-identical resumed
training, async-writer failure re-raise (a dropped handle cannot fake
durability), the concurrent-sharded-save cleanup race, and SIGTERM
preemption drain.  The full save→SIGKILL→resume path through the 3D GPT
trainer lives in ``tests/test_crash_resume.py``.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu import parallel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import CheckpointManager, PreemptionGuard
from apex_tpu.testing import faults


def _leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# verify_checkpoint
# ---------------------------------------------------------------------------


def test_manifest_carries_checksums(tmp_path):
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, {"w": jnp.arange(8.0), "n": np.arange(4)},
                         step=3)
    manifest = ckpt.verify_checkpoint(path)
    assert manifest["step"] == 3
    assert set(manifest["checksums"]) == {"leaf_0", "leaf_1"}


@pytest.mark.parametrize("mode", ["bitflip", "truncate"])
def test_verify_detects_corruption(tmp_path, mode):
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, {"w": jnp.arange(512.0)}, step=1)
    faults.corrupt_checkpoint(path, mode=mode)
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.verify_checkpoint(path)


def test_verify_detects_checksum_mismatch_with_valid_zip(tmp_path):
    """A well-formed archive whose recorded checksum disagrees (e.g. an
    array swapped wholesale) is caught by the manifest crc32 even though
    zipfile's own CRC is happy."""
    path = str(tmp_path / "c.npz")
    ckpt.save_checkpoint(path, {"w": jnp.arange(4.0)}, step=1)
    with np.load(path, allow_pickle=False) as data:
        manifest = json.loads(str(data["__manifest__"]))
        arrays = {k: data[k] for k in data.files if k != "__manifest__"}
    manifest["checksums"]["leaf_0"] ^= 0xFFFF  # recorded sum now lies
    with open(path, "wb") as f:
        np.savez(f, __manifest__=json.dumps(manifest), **arrays)
    with pytest.raises(ckpt.CheckpointCorruptError, match="checksum"):
        ckpt.verify_checkpoint(path)


def test_verify_sharded(tmp_path):
    mesh = parallel.initialize_model_parallel()
    from jax.sharding import NamedSharding, PartitionSpec as P

    d = str(tmp_path / "s")
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh, P(("dcn", "dp"), None)))
    ckpt.save_checkpoint_sharded(d, {"w": w}, step=5)
    manifest = ckpt.verify_checkpoint_sharded(d)
    assert manifest["step"] == 5
    faults.corrupt_checkpoint(d)  # hits shard_0.npz
    with pytest.raises(ckpt.CheckpointCorruptError):
        ckpt.verify_checkpoint_sharded(d)


# ---------------------------------------------------------------------------
# CheckpointManager: retention, retry, fallback, bit-exact resume
# ---------------------------------------------------------------------------


def test_manager_retention_keeps_last_k(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "m"), keep=2)
    for s in range(5):
        mgr.save({"w": jnp.full((4,), float(s))}, s)
    assert mgr.all_steps() == [3, 4]


def test_manager_retry_with_backoff(tmp_path):
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=2, retries=3, backoff_s=0.01)
    with faults.transient_os_errors(2, path_prefix=root) as counter:
        mgr.save({"w": jnp.ones(3)}, 0)
    assert counter.failed == 2
    mgr.verify(0)

    with faults.transient_os_errors(10, path_prefix=root):
        with pytest.raises(OSError):
            mgr.save({"w": jnp.ones(3)}, 1)  # budget exhausted


@pytest.mark.parametrize("mode", ["bitflip", "truncate"])
def test_restore_latest_falls_back_and_resumes_bit_exact(tmp_path, mode):
    """Corrupt the newest checkpoint: ``restore_latest`` detects it by
    checksum, falls back to the previous intact one, and training
    resumed from there is bit-identical to the uninterrupted run."""
    opt = FusedAdam(lr=1e-2)
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 4))}
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(
            lambda q: jnp.mean((x @ q["w"]) ** 2))(p)
        p, s = opt.step(g, s, p)
        return p, s, loss

    state = opt.init(params)
    mgr = CheckpointManager(str(tmp_path / "m"), keep=3)
    losses = []
    for i in range(4):
        params, state, loss = step(params, state)
        losses.append(np.asarray(loss))
        mgr.save({"p": params, "s": state}, i)
    p_final, s_final = params, state

    faults.corrupt_checkpoint(mgr._path(3), mode=mode)
    like = {"p": params, "s": state}
    restored, at = mgr.restore_latest(like)
    assert at == 2  # fell back past the damaged step 3
    rp, rs = restored["p"], restored["s"]
    rp, rs, rloss = step(rp, rs)
    np.testing.assert_array_equal(np.asarray(rloss), losses[3])
    _leaves_equal(rp, p_final)
    _leaves_equal(rs, s_final)


def test_restore_latest_sharded_falls_back(tmp_path):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = parallel.initialize_model_parallel()
    sharding = NamedSharding(mesh, P(("dcn", "dp"), None))
    mgr = CheckpointManager(str(tmp_path / "m"), keep=3, sharded=True)
    for s in range(2):
        w = jax.device_put(jnp.full((8, 4), float(s)), sharding)
        mgr.save({"w": w}, s)
    faults.corrupt_checkpoint(mgr._path(1))
    like = {"w": jax.device_put(jnp.zeros((8, 4)), sharding)}
    restored, at = mgr.restore_latest(like)
    assert at == 0
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.zeros((8, 4)))


def test_restore_latest_no_intact_checkpoint_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "m"), keep=3)
    with pytest.raises(FileNotFoundError):
        mgr.restore_latest({"w": jnp.zeros(2)})
    mgr.save({"w": jnp.ones(2)}, 0)
    faults.corrupt_checkpoint(mgr._path(0))
    with pytest.raises(FileNotFoundError, match="no intact"):
        mgr.restore_latest({"w": jnp.zeros(2)})


def test_zero_sharded_optimizer_state_rides_manager(tmp_path):
    """ZeRO flat-bucket optimizer state (global arrays) checkpoints and
    falls back through the manager like any tree — the ISSUE 3 'ZeRO
    included' clause."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel import collectives as cc
    from apex_tpu.parallel.distributed import zero_init

    mesh = parallel.initialize_model_parallel()
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (13, 7))}
    opt = DistributedFusedAdam(lr=1e-2, flat_bucket=True, n_buckets=2)
    state = zero_init(opt, params, mesh)
    grads = {"w": jnp.full((13, 7), 1e-3)}
    step = jax.jit(cc.shard_over(
        lambda g, s, p: opt.step(g, s, p), mesh=mesh,
        in_specs=(P(), opt.state_partition_specs(params), P()),
        out_specs=(P(), opt.state_partition_specs(params))))

    mgr = CheckpointManager(str(tmp_path / "m"), keep=2, sharded=True)
    params1, state1 = step(grads, state, params)
    mgr.save({"p": params1, "s": state1}, 0)
    params2, state2 = step(grads, state1, params1)
    mgr.save({"p": params2, "s": state2}, 1)

    faults.corrupt_checkpoint(mgr._path(1))
    restored, at = mgr.restore_latest({"p": params2, "s": state2})
    assert at == 0
    _leaves_equal(restored["s"], state1)
    # resume: stepping the restored state reproduces step-1 state exactly
    rp, rs = step(grads, restored["s"], restored["p"])
    _leaves_equal(rp, params2)
    _leaves_equal(rs, state2)


# ---------------------------------------------------------------------------
# Async writer failures (satellite: no silent non-durable saves)
# ---------------------------------------------------------------------------


def _wait_done(handle, timeout=30.0):
    t0 = time.monotonic()
    while not handle.done():
        if time.monotonic() - t0 > timeout:
            raise TimeoutError("async write never finished")
        time.sleep(0.01)


def test_async_write_failure_reraised_on_next_save(tmp_path):
    path = str(tmp_path / "a.npz")
    tree = {"w": jnp.arange(4.0)}
    with faults.transient_os_errors(1, path_prefix=str(tmp_path)):
        fut = ckpt.save_checkpoint_async(path, tree, step=0)
        _wait_done(fut)  # failed in the background; handle dropped
    with pytest.raises(RuntimeError, match="NOT durable"):
        ckpt.save_checkpoint_async(path, tree, step=1)
    # the failure is consumed: the save after that succeeds
    fut = ckpt.save_checkpoint_async(path, tree, step=2)
    assert fut.result(timeout=30) == path
    assert ckpt.verify_checkpoint(path)["step"] == 2


def test_async_sharded_write_failure_reraised_on_next_save(tmp_path):
    d = str(tmp_path / "s")
    tree = {"w": jnp.arange(4.0)}
    with faults.transient_os_errors(1, path_prefix=d):
        handle = ckpt.save_checkpoint_sharded_async(d, tree, step=0)
        _wait_done(handle)
    with pytest.raises(RuntimeError, match="NOT durable"):
        ckpt.save_checkpoint_sharded_async(d, tree, step=1)
    handle = ckpt.save_checkpoint_sharded_async(d, tree, step=2)
    handle.finalize(timeout=30)
    assert ckpt.verify_checkpoint_sharded(d)["step"] == 2


def test_manager_async_failure_raises_on_wait_and_falls_back(tmp_path):
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3, retries=0)
    mgr.save({"w": jnp.ones(3)}, 0)
    with faults.transient_os_errors(1, path_prefix=root):
        handle = mgr.save_async({"w": jnp.full((3,), 2.0)}, 1)
        _wait_done(handle)
        with pytest.raises(OSError):
            mgr.wait()
    # the torn step-1 attempt was discarded; latest intact is step 0
    restored, at = mgr.restore_latest({"w": jnp.zeros(3)})
    assert at == 0
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(3))
    # the failure was OBSERVED (raised from wait): a legitimate retry of
    # the same step must not trip the dropped-handle guard
    mgr.save_async({"w": jnp.full((3,), 2.0)}, 1)
    mgr.wait()
    restored, at = mgr.restore_latest({"w": jnp.zeros(3)})
    assert at == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((3,), 2.0))


def test_dropped_handle_failure_fires_across_step_paths(tmp_path):
    """Step-indexed layouts never revisit a failed step's exact path:
    the dropped-handle guard must fire on the NEXT save to a sibling
    destination (same parent dir), or the guarantee is vacuous for the
    normal checkpointing pattern."""
    tree = {"w": jnp.arange(4.0)}
    with faults.transient_os_errors(1, path_prefix=str(tmp_path)):
        fut = ckpt.save_checkpoint_async(
            str(tmp_path / "step_7.npz"), tree, step=7)
        _wait_done(fut)  # failed; handle dropped, failure unobserved
    with pytest.raises(RuntimeError, match="NOT durable"):
        ckpt.save_checkpoint_async(str(tmp_path / "step_8.npz"), tree,
                                   step=8)


def test_sync_save_surfaces_then_supersedes_async_failure(tmp_path):
    """A SYNC save also surfaces a dropped async failure (raising once),
    and once it has been surfaced a durable sync save supersedes it —
    later saves run clean."""
    path = str(tmp_path / "a.npz")
    tree = {"w": jnp.arange(4.0)}
    with faults.transient_os_errors(1, path_prefix=str(tmp_path)):
        fut = ckpt.save_checkpoint_async(path, tree, step=0)
        _wait_done(fut)  # failed; handle dropped, failure unobserved
    with pytest.raises(RuntimeError, match="NOT durable"):
        ckpt.save_checkpoint(path, tree, step=1)
    ckpt.save_checkpoint(path, tree, step=1)  # surfaced: retry is clean
    fut = ckpt.save_checkpoint_async(path, tree, step=2)  # must not raise
    assert fut.result(timeout=30) == path
    assert ckpt.verify_checkpoint(path)["step"] == 2


def test_hung_writer_leaves_no_torn_checkpoint(tmp_path):
    """Kill/abandon an async writer mid-flight: while it hangs, nothing
    of the new save is visible and the previous checkpoint restores."""
    root = str(tmp_path / "m")
    mgr = CheckpointManager(root, keep=3)
    mgr.save({"w": jnp.ones(3)}, 0)
    with faults.hung_writes(path_prefix=root) as gate:
        handle = mgr.save_async({"w": jnp.full((3,), 9.0)}, 1)
        assert gate.entered.wait(timeout=30)
        # writer parked mid-flight: step 1 must not be visible/intact
        restored, at = mgr.restore_latest({"w": jnp.zeros(3)})
        assert at == 0
        gate.release()
        handle.result(timeout=30)
    mgr.wait()
    restored, at = mgr.restore_latest({"w": jnp.zeros(3)})
    assert at == 1


# ---------------------------------------------------------------------------
# Concurrent sharded saves vs stale-shard cleanup (satellite)
# ---------------------------------------------------------------------------


def test_cleanup_spares_in_flight_shards_and_temps(tmp_path):
    """The concurrent-writer race: cleanup must only remove shard files
    unreferenced by the committed manifest AND older than it — never a
    file (or temp) a second in-flight save just wrote."""
    d = str(tmp_path / "s")
    tree = {"w": jnp.arange(8.0)}
    ckpt.save_checkpoint_sharded(d, tree, step=0)  # commits manifest.json

    # simulate a second in-flight save from a (larger) job: a fresh
    # shard file and a temp, both YOUNGER than the committed manifest
    import shutil

    shutil.copy(os.path.join(d, "shard_0.npz"),
                os.path.join(d, "shard_3.npz"))
    tmp_name = os.path.join(d, "shard_0.npz.tmp.deadbeef")
    with open(tmp_name, "wb") as f:
        f.write(b"partial bytes")

    ckpt._clean_stale_shards(d)
    assert os.path.exists(os.path.join(d, "shard_3.npz")), \
        "cleanup deleted a shard an in-flight save just wrote"
    assert os.path.exists(tmp_name), "cleanup touched a young temp file"

    # once genuinely stale (older than the committed manifest), it goes
    manifest_mtime = os.path.getmtime(os.path.join(d, "manifest.json"))
    os.utime(os.path.join(d, "shard_3.npz"),
             (manifest_mtime - 10, manifest_mtime - 10))
    ckpt._clean_stale_shards(d)
    assert not os.path.exists(os.path.join(d, "shard_3.npz"))
    os.unlink(tmp_name)


def test_two_overlapping_sharded_handles(tmp_path):
    """Two in-flight ``ShardedSaveHandle``s to the same dir: the cleanup
    at the second save's start must not eat the first save's output;
    in-order finalize yields a consistent checkpoint; an OUT-of-order
    finalize (commit says step 1, surviving shard bytes are step 2) is
    detected by verify rather than silently blended — the ambiguity
    ``CheckpointManager`` serializes saves to avoid."""
    d = str(tmp_path / "s")
    t1 = {"w": jnp.full((4,), 1.0)}
    t2 = {"w": jnp.full((4,), 2.0)}
    with faults.hung_writes(path_prefix=d) as gate:
        h1 = ckpt.save_checkpoint_sharded_async(d, t1, step=1)
        assert gate.entered.wait(timeout=30)
        gate.release()  # let h1's write land...
        h1.result(timeout=30)
    # ...but do NOT finalize h1 yet: its manifest is uncommitted while
    # the second save starts (runs _clean_stale_shards) and completes.
    h2 = ckpt.save_checkpoint_sharded_async(d, t2, step=2)
    h2.finalize(timeout=30)
    assert ckpt.verify_checkpoint_sharded(d)["step"] == 2
    restored, at = ckpt.restore_checkpoint_sharded(d, {"w": jnp.zeros(4)})
    assert at == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((4,), 2.0))
    h1.finalize(timeout=30)  # stale commit over newer shard bytes
    with pytest.raises(ckpt.CheckpointCorruptError, match="overlapping"):
        ckpt.verify_checkpoint_sharded(d)


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------


def test_preemption_guard_catches_sigterm_and_drains(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "m"), keep=2)
    with PreemptionGuard() as guard:
        assert not guard.triggered
        mgr.save_async({"w": jnp.ones(3)}, 0)
        faults.simulate_sigterm()
        assert guard.triggered
        # the drain protocol: wait for in-flight, final sync save
        mgr.wait()
        mgr.save({"w": jnp.full((3,), 2.0)}, 1)
    restored, at = mgr.restore_latest({"w": jnp.zeros(3)})
    assert at == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((3,), 2.0))


def test_preemption_guard_off_main_thread_falls_back():
    """CPython forbids signal.signal off the main thread; a guard built
    there (fleet-router health threads, replica children off-main) must
    degrade to the programmatic trigger() path, not raise (ISSUE 11
    satellite)."""
    import threading

    out = {}

    def build():
        try:
            guard = PreemptionGuard()
        except BaseException as e:  # the pre-fix behavior
            out["error"] = e
            return
        out["guard"] = guard

    t = threading.Thread(target=build)
    t.start()
    t.join(timeout=10)
    assert "error" not in out, repr(out.get("error"))
    guard = out["guard"]
    assert guard.signals_installed is False
    assert not guard.triggered
    guard.trigger()                 # the fallback path still works
    assert guard.triggered
    guard.uninstall()               # idempotent no-op: nothing installed
    # a main-thread guard keeps full signal installation
    with PreemptionGuard() as main_guard:
        assert main_guard.signals_installed is True
    # the fallback is for thread-affinity ONLY: an invalid/uncatchable
    # signal on the main thread is a caller bug and must keep raising
    # (ValueError or OSError depending on the libc), not yield a guard
    # that silently never fires
    import signal as _signal

    with pytest.raises((ValueError, OSError)):
        PreemptionGuard(signals=(_signal.SIGKILL,))
