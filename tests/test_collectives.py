"""Collective wrapper tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import collectives as cc


def _mesh(tp=8):
    return parallel.initialize_model_parallel(tensor_model_parallel_size=tp)


def test_all_reduce_sum():
    _mesh()
    x = jnp.arange(8.0)

    f = cc.shard_over(
        lambda x: cc.all_reduce(x, "tp"), in_specs=P("tp"), out_specs=P("tp")
    )
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


@pytest.mark.parametrize("op,expect", [("max", 7.0), ("min", 0.0), ("mean", 3.5)])
def test_all_reduce_ops(op, expect):
    _mesh()
    x = jnp.arange(8.0)
    f = cc.shard_over(
        lambda x: cc.all_reduce(x, "tp", op=op), in_specs=P("tp"), out_specs=P("tp")
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, expect))


def test_all_gather_tiled():
    _mesh()
    x = jnp.arange(16.0).reshape(8, 2)
    f = cc.shard_over(
        lambda s: cc.all_gather(s, "tp", concat_axis=0),
        in_specs=P("tp", None),
        out_specs=P(None, None),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))


def test_reduce_scatter_roundtrip():
    """reduce_scatter(all_gather(x)) == world_size * x."""
    _mesh()
    x = jnp.arange(16.0).reshape(8, 2)

    def fn(s):
        full = cc.all_gather(s, "tp", concat_axis=0)
        return cc.reduce_scatter(full, "tp", scatter_axis=0)

    f = cc.shard_over(fn, in_specs=P("tp", None), out_specs=P("tp", None))
    np.testing.assert_allclose(np.asarray(f(x)), 8 * np.asarray(x))


def test_ppermute_ring():
    _mesh()
    x = jnp.arange(8.0).reshape(8, 1)
    f = cc.shard_over(
        lambda s: cc.send_recv_next(s, "tp"),
        in_specs=P("tp", None),
        out_specs=P("tp", None),
    )
    out = np.asarray(f(x)).ravel()
    # rank i receives from rank i-1 (wrapping)
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_broadcast():
    _mesh()
    x = jnp.arange(8.0).reshape(8, 1)
    f = cc.shard_over(
        lambda s: cc.broadcast(s, "tp", root=3),
        in_specs=P("tp", None),
        out_specs=P("tp", None),
    )
    np.testing.assert_allclose(np.asarray(f(x)).ravel(), np.full(8, 3.0))


def test_all_to_all():
    _mesh()
    x = jnp.arange(64.0).reshape(8, 8)
    f = cc.shard_over(
        lambda s: cc.all_to_all(s, "tp", split_axis=1, concat_axis=0),
        in_specs=P("tp", None),
        out_specs=P("tp", None),
    )
    out = np.asarray(f(x))
    # per-shard (1,8) → (8,1): splits the 8 columns across ranks and stacks the
    # received rows, i.e. a shard transpose; globally the column dim collapses.
    assert out.shape == (64, 1)
    np.testing.assert_allclose(out.ravel(), np.asarray(x).T.ravel())


def test_axis_index_and_size():
    _mesh()
    f = cc.shard_over(
        lambda s: s + cc.axis_index("tp") * 0 + cc.axis_size("tp"),
        in_specs=P("tp"),
        out_specs=P("tp"),
    )
    np.testing.assert_allclose(np.asarray(f(jnp.zeros(8))), np.full(8, 8.0))
