"""Flat-bucket ZeRO numerics: the bucketed exchange must be a pure
re-plumbing of the per-leaf port.

Parity chain (each link within fp32 fusion noise):

    flat-bucket ZeRO step  ==  per-leaf ZeRO step  ==  replicated
    FusedAdam/FusedLAMB on the mean gradients

exercised on the virtual 8-device host mesh, on a 2x2 ``(dcn, dp)`` mesh
through the hierarchical ICI/DCN reduction, and through
``zero_data_parallel_train_step`` with gradient accumulation N > 1
(reduce-scatter folded into the last microbatch).  Mirrors
``apex/contrib/test/optimizers/test_dist_adam.py`` and the bucketed
``StateBucket`` layout of ``distributed_fused_adam.py:397``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.parallel import (
    collectives as cc,
    dp_shard_batch,
    grad_accumulation,
    replicate,
    zero_data_parallel_train_step,
    zero_init,
)


def make_params(key, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(ks[0], (13, 7), dtype),   # 91 elems: pad path
        "b": jax.random.normal(ks[1], (8,), dtype),
        "e": jax.random.normal(ks[2], (4, 4, 2), dtype),
    }


def per_rank_grads(params, key, n):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [jax.tree_util.tree_unflatten(treedef, [
        jax.random.normal(jax.random.fold_in(key, r * 1000 + i), leaf.shape)
        for i, leaf in enumerate(leaves)
    ]) for r in range(n)]


def run_sharded(opt, params, grads_by_rank, steps=3, rank_fn=None,
                **step_kw):
    """Each replica steps with its own grads; returns final params."""
    grads_stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls), *grads_by_rank)

    def local(params, gs):
        r = rank_fn() if rank_fn is not None else cc.axis_index("dp")
        g = jax.tree_util.tree_map(
            lambda l: jax.lax.dynamic_index_in_dim(l, r, 0, keepdims=False),
            gs)
        state = opt.init(params)
        p = params
        for _ in range(steps):
            p, state = opt.step(g, state, p, **step_kw)
        return p

    return cc.shard_over(
        local, in_specs=(P(), P()), out_specs=P())(params, grads_stacked)


def run_replicated(opt, params, grads_by_rank, steps=3):
    mean_g = jax.tree_util.tree_map(
        lambda *ls: sum(ls) / len(grads_by_rank), *grads_by_rank)
    state = opt.init(params)
    p = params
    for _ in range(steps):
        p, state = opt.step(mean_g, state, p)
    return p


def assert_tree_close(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# Fast tier: collective primitives + the accumulation transform (no
# multi-step shard_map compiles)
# ---------------------------------------------------------------------------


def test_hierarchical_reduce_scatter_matches_flat():
    """RS(ICI dp) + shard all-reduce(DCN) == one flat RS over (dcn, dp),
    after gathering back: both are the full cross-replica sum."""
    mesh = parallel.initialize_model_parallel(
        dcn_data_parallel_size=2, devices=jax.devices()[:4])
    x = jnp.arange(4 * 8 * 4, dtype=jnp.float32).reshape(4, 32)

    def hier(x):
        r = cc.axis_index("dcn") * cc.axis_size("dp") + cc.axis_index("dp")
        mine = jax.lax.dynamic_index_in_dim(x, r, 0, keepdims=False)
        shard = cc.hierarchical_reduce_scatter(mine, "dp", "dcn")
        return cc.hierarchical_all_gather(shard, "dp")

    def flat(x):
        r = cc.axis_index("dcn") * cc.axis_size("dp") + cc.axis_index("dp")
        mine = jax.lax.dynamic_index_in_dim(x, r, 0, keepdims=False)
        shard = cc.hierarchical_reduce_scatter(mine, ("dcn", "dp"), None)
        return cc.all_gather(shard, ("dcn", "dp"))

    out_h = cc.shard_over(hier, mesh=mesh, in_specs=P(), out_specs=P())(x)
    out_f = cc.shard_over(flat, mesh=mesh, in_specs=P(), out_specs=P())(x)
    ref = np.asarray(x).sum(axis=0)
    np.testing.assert_allclose(np.asarray(out_h), ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out_f), ref, rtol=1e-6)


def test_hierarchical_outer_noop_on_single_slice():
    """outer_axis on a size-1 dcn axis must be a no-op (the 'correct at
    any scale' default)."""
    mesh = parallel.initialize_model_parallel()  # dcn=1, dp=8
    x = jnp.ones((8, 16), jnp.float32)

    def f(x):
        mine = x  # same on every rank: in_specs P() replicates
        return cc.hierarchical_reduce_scatter(mine[0], "dp", "dcn")

    out = cc.shard_over(f, mesh=mesh, in_specs=P(), out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_grad_accumulation_transform_matches_full_batch():
    """grad_accumulation(grad_fn, N) == grad_fn on the whole batch for a
    mean loss (no mesh needed; N=4 microbatches)."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 3).astype(np.float32))}
    X = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    Y = jnp.asarray(rng.randn(16, 3).astype(np.float32))

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    grad_fn = lambda p, b: jax.value_and_grad(loss_fn)(p, b)
    loss_full, g_full = grad_fn(params, (X, Y))
    loss_acc, g_acc = grad_accumulation(grad_fn, 4)(params, (X, Y))
    np.testing.assert_allclose(float(loss_acc), float(loss_full), rtol=1e-6)
    assert_tree_close(g_acc, g_full, rtol=1e-5, atol=1e-6)
    # indivisible batch is a loud error, not silent truncation
    with pytest.raises(ValueError, match="divisible"):
        grad_accumulation(grad_fn, 3)(params, (X, Y))


def test_state_partition_specs_structure():
    """Spec tree mirrors init's state structure in both layouts."""
    params = {"w": jnp.ones((13, 7)),
              "h": jnp.ones((8,), jnp.bfloat16)}
    flat = DistributedFusedAdam(n_buckets=2)
    specs = flat.state_partition_specs(params)
    assert specs.step == P()
    # two dtype-groups x two buckets
    assert len(specs.master) == 2
    assert all(len(bufs) == 2 and all(s == P("dp") for s in bufs)
               for bufs in specs.master)
    leafy = DistributedFusedAdam(flat_bucket=False)
    specs = leafy.state_partition_specs(params)
    assert specs.slots["exp_avg"]["w"] == P("dp")


# ---------------------------------------------------------------------------
# Slow tier: full numeric-parity chain on the 8-device mesh
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("n_buckets", [1, 3])
def test_flat_bucket_adam_parity(n_buckets):
    """flat-bucket ZeRO == per-leaf ZeRO == replicated FusedAdam."""
    parallel.initialize_model_parallel()
    params = make_params(jax.random.PRNGKey(0))
    grads = per_rank_grads(params, jax.random.PRNGKey(1), 8)
    ref = run_replicated(
        FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True),
        params, grads)
    flat = run_sharded(
        DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                             n_buckets=n_buckets),
        params, grads)
    assert_tree_close(flat, ref)
    if n_buckets == 1:
        leaf = run_sharded(
            DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                 flat_bucket=False),
            params, grads)
        assert_tree_close(flat, leaf, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_flat_bucket_adam_mixed_dtype_groups():
    """bf16 + fp32 leaves split into dtype-groups; params keep their
    dtypes through the bucketed gather."""
    parallel.initialize_model_parallel()
    params = make_params(jax.random.PRNGKey(2))
    params["h"] = jax.random.normal(
        jax.random.PRNGKey(3), (9, 3)).astype(jnp.bfloat16)
    grads = per_rank_grads(params, jax.random.PRNGKey(4), 8)
    a = run_sharded(DistributedFusedAdam(lr=1e-2, weight_decay=0.01),
                    params, grads)
    b = run_sharded(DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                         flat_bucket=False),
                    params, grads)
    assert_tree_close(a, b, rtol=1e-4, atol=1e-4)
    for k in params:
        assert a[k].dtype == params[k].dtype


@pytest.mark.slow
def test_flat_bucket_lamb_parity():
    """flat-bucket ZeRO LAMB (segmented trust-ratio norms) == per-leaf
    ZeRO LAMB == replicated FusedLAMB, incl. the global-norm clip."""
    parallel.initialize_model_parallel()
    params = make_params(jax.random.PRNGKey(6))
    grads = per_rank_grads(params, jax.random.PRNGKey(7), 8)
    ref = run_replicated(
        FusedLAMB(lr=1e-2, weight_decay=0.01, master_weights=True),
        params, grads)
    flat = run_sharded(DistributedFusedLAMB(lr=1e-2, weight_decay=0.01),
                       params, grads)
    assert_tree_close(flat, ref)
    leaf = run_sharded(
        DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                             flat_bucket=False),
        params, grads)
    assert_tree_close(flat, leaf, rtol=1e-6, atol=1e-6)
    # tiny max_grad_norm: the clip engages and still matches per-leaf
    a = run_sharded(DistributedFusedLAMB(lr=1e-2, max_grad_norm=0.5),
                    params, grads)
    b = run_sharded(DistributedFusedLAMB(lr=1e-2, max_grad_norm=0.5,
                                         flat_bucket=False),
                    params, grads)
    assert_tree_close(a, b, rtol=2e-6, atol=2e-6)


@pytest.mark.slow
def test_hierarchical_2x2_parity():
    """2x2 (dcn, dp) mesh: hierarchical reduction (RS over ICI dp +
    shard all-reduce over DCN) == flat reduction over the combined axis
    == replicated FusedAdam on the 4-replica mean grads."""
    parallel.initialize_model_parallel(
        dcn_data_parallel_size=2, devices=jax.devices()[:4])
    params = make_params(jax.random.PRNGKey(8))
    grads = per_rank_grads(params, jax.random.PRNGKey(9), 4)
    ref = run_replicated(
        FusedAdam(lr=1e-2, weight_decay=0.01, master_weights=True),
        params, grads)

    def rank_fn():
        return cc.axis_index("dcn") * cc.axis_size("dp") \
            + cc.axis_index("dp")

    hier = run_sharded(
        DistributedFusedAdam(lr=1e-2, weight_decay=0.01),  # outer="dcn"
        params, grads, rank_fn=rank_fn)
    assert_tree_close(hier, ref)
    flat = run_sharded(
        DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                             axis=("dcn", "dp"), outer_axis=None),
        params, grads, rank_fn=rank_fn)
    assert_tree_close(hier, flat, rtol=1e-5, atol=1e-6)
    # bf16 DCN wire: same update within bf16 wire noise
    bf16_wire = run_sharded(
        DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                             dcn_reduce_dtype=jnp.bfloat16),
        params, grads, rank_fn=rank_fn)
    assert_tree_close(bf16_wire, ref, rtol=5e-2, atol=5e-2)


@pytest.mark.slow
def test_zero_checkpoint_roundtrip_hierarchical():
    """gather/scatter_zero_state on a (dcn=2, dp=2) mesh: bitwise
    round-trip of bucketed state.  Regression: eager jnp ops on the
    dp-sharded (dcn-replicated) shard_map outputs used to SUM the
    replicated dim in the gather concat (values doubled by the dcn
    size); the gather is numpy-first now."""
    from apex_tpu.checkpoint import gather_zero_state, scatter_zero_state

    mesh = parallel.initialize_model_parallel(
        dcn_data_parallel_size=2, devices=jax.devices()[:4])
    params = make_params(jax.random.PRNGKey(12))
    grads = {k: jnp.ones_like(v) for k, v in params.items()}
    opt = DistributedFusedAdam(lr=1e-2, n_buckets=2)
    specs = opt.state_partition_specs(params)

    def local(p, g):
        s = opt.init(p)
        return opt.step(g, s, p)

    p2, s2 = cc.shard_over(
        local, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), specs))(params, grads)
    portable = gather_zero_state(opt, s2, p2)
    # grads identical on all 4 replicas -> mean grad 1 -> exp_avg exactly
    # (1 - beta1); a replicated-dim double-count would read 2x that
    ea = np.asarray(portable["slots"]["exp_avg"]["b"])
    np.testing.assert_allclose(ea, 0.1, rtol=1e-6)
    resharded = scatter_zero_state(opt, portable, s2, p2)
    for a, b in zip(jax.tree_util.tree_leaves(s2),
                    jax.tree_util.tree_leaves(resharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_grad_accumulation_train_step_parity():
    """zero_data_parallel_train_step with microbatches=2 == microbatches=1
    == replicated FusedAdam pjit path, on the same total batch."""
    mesh = parallel.initialize_model_parallel()
    rng = np.random.RandomState(1)
    w0 = rng.randn(4, 2).astype(np.float32)
    X = rng.randn(64, 4).astype(np.float32)
    Y = (X @ rng.randn(4, 2)).astype(np.float32)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    batch = dp_shard_batch((jnp.asarray(X), jnp.asarray(Y)), mesh)

    def train_zero(microbatches):
        opt = DistributedFusedAdam(lr=0.05)
        p = replicate({"w": jnp.asarray(w0)}, mesh)
        s = zero_init(opt, p, mesh)
        step = zero_data_parallel_train_step(
            loss_fn, opt, mesh=mesh, donate=False,
            microbatches=microbatches)
        for _ in range(5):
            p, s, loss = step(p, s, batch)
        return p, float(loss)

    p1, l1 = train_zero(1)
    p2, l2 = train_zero(2)
    assert_tree_close(p1, p2, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)

    from apex_tpu.parallel import data_parallel_train_step

    opt = FusedAdam(lr=0.05)
    p = replicate({"w": jnp.asarray(w0)}, mesh)
    s = replicate(opt.init(p), mesh)
    step = data_parallel_train_step(loss_fn, opt, mesh=mesh, donate=False)
    for _ in range(5):
        p, s, _ = step(p, s, batch)
    assert_tree_close(p1, p, rtol=1e-5, atol=1e-6)
