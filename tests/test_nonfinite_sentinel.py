"""The unified non-finite sentinel, across every trainer path.

Acceptance (ISSUE 3): an injected NaN-grad step is skipped by the amp
path, the ZeRO flat-bucket AND per-leaf paths, and the 3D GPT trainer
alike — params and optimizer state bit-unchanged across the skipped
step, ``skipped_steps`` increments, and the guard adds no host round
trip (the ``lax.cond``-guarded apply survives as a ``conditional`` in
ONE compiled program, checked by the shared analyzer rule APX203 —
``apex_tpu.analysis``, ISSUE 4 — instead of per-test string asserts).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import parallel
from apex_tpu.amp.scaler import DynamicLossScale
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (
    guarded_optimizer_step,
    sentinel_init,
    sentinel_update,
)
from apex_tpu.analysis import compiled_hlo, lint_hlo
from apex_tpu.testing import faults


def _assert_guard_survives(hlo_text):
    """The sentinel contract, checked by the ONE shared implementation
    (analyzer rule APX203) every consumer uses — tests, the CLI over the
    registered entries, and ``scripts/graph_lint.sh``."""
    report = lint_hlo(hlo_text, name="sentinel-step",
                      expect_conditional=True)
    assert report.ok, report.format()


def _leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# amp path: guarded_optimizer_step over a replicated fused optimizer
# ---------------------------------------------------------------------------


class TestAmpPath:
    def _setup(self):
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (6, 3))}
        opt = FusedAdam(lr=1e-2)
        scaler = DynamicLossScale(init_scale=16.0, hysteresis=1)
        return params, opt, scaler

    def test_nan_step_skipped_counter_and_state(self):
        params, opt, scaler = self._setup()
        state = opt.init(params)
        sent = sentinel_init(scaler)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 6))

        @jax.jit
        def step(p, s, z, step_no):
            scale = z.scaler.scale

            def loss_fn(q):
                return jnp.mean((x @ q["w"]) ** 2) * scale

            loss, g = jax.value_and_grad(loss_fn)(p)
            g = faults.poison_grads(g, step=step_no, at_step=1)
            finite, z = sentinel_update(scaler, g, z)
            p, s = guarded_optimizer_step(opt, g, s, p, finite,
                                          grad_scale=scale)
            return p, s, z, loss / scale

        p1, s1, sent1, _ = step(params, state, sent, 0)
        assert int(sent1.skipped_steps) == 0
        assert int(s1.step) == 1
        # poisoned step: bit-unchanged params/state, counter increments,
        # scale backs off
        p2, s2, sent2, _ = step(p1, s1, sent1, 1)
        assert int(sent2.skipped_steps) == 1
        assert bool(sent2.scaler.found_inf)
        assert float(sent2.scaler.scale) == 8.0
        _leaves_equal(p1, p2)
        _leaves_equal(s1, s2)
        # clean step afterwards applies again
        p3, s3, sent3, _ = step(p2, s2, sent2, 2)
        assert int(sent3.skipped_steps) == 1
        assert int(s3.step) == 2
        with pytest.raises(AssertionError):
            _leaves_equal(p2, p3)

    def test_guard_is_one_compiled_program(self):
        params, opt, scaler = self._setup()
        state = opt.init(params)
        sent = sentinel_init(scaler)

        def step(p, s, z, g):
            finite, z = sentinel_update(scaler, g, z)
            p, s = guarded_optimizer_step(opt, g, s, p, finite)
            return p, s, z

        g = {"w": jnp.ones((6, 3))}
        _assert_guard_survives(compiled_hlo(step, params, state, sent, g))


# ---------------------------------------------------------------------------
# ZeRO path (flat-bucket AND per-leaf) through the shard_map train step
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("flat_bucket", [True, False])
class TestZeroPath:
    def _build(self, flat_bucket):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.parallel.distributed import (
            dp_shard_batch,
            zero_data_parallel_train_step,
            zero_init,
        )

        mesh = parallel.initialize_model_parallel()  # dp over 8 devices
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (13, 7)),
                  "b": jnp.zeros((7,))}

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        opt = DistributedFusedAdam(lr=1e-2, flat_bucket=flat_bucket,
                                   n_buckets=2)
        state = zero_init(opt, params, mesh)
        scaler = DynamicLossScale(init_scale=16.0)
        sent = sentinel_init(scaler)
        step = zero_data_parallel_train_step(
            loss_fn, opt, mesh=mesh, scaler=scaler, donate=False)
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 13))
        y = jax.random.normal(jax.random.PRNGKey(2), (16, 7))
        batch = dp_shard_batch((x, y), mesh)
        bad = dp_shard_batch((x.at[0, 0].set(np.nan), y), mesh)
        return params, state, sent, step, batch, bad

    def test_nan_from_one_rank_skips_globally(self, flat_bucket):
        """The NaN lands in ONE dp rank's local batch slice: the pmin
        agreement must veto the update on every rank (a rank-local flag
        would deadlock/diverge the collectives).  Also asserts the guard
        adds no host round-trip: the whole step — overflow check, scaler
        update, cond-guarded reduce-scatter/update/all-gather — is ONE
        compiled program whose ``conditional`` survives optimization
        (one build per layout keeps this in the fast tier)."""
        params, state, sent, step, batch, bad = self._build(flat_bucket)
        _assert_guard_survives(compiled_hlo(step, params, state, batch,
                                            sent))

        p1, s1, sent1, loss1 = step(params, state, batch, sent)
        assert int(sent1.skipped_steps) == 0
        assert np.isfinite(float(loss1))

        p2, s2, sent2, _ = step(p1, s1, bad, sent1)
        assert int(sent2.skipped_steps) == 1
        assert bool(sent2.scaler.found_inf)
        assert float(sent2.scaler.scale) == 8.0
        _leaves_equal(p1, p2)   # params bit-unchanged
        _leaves_equal(s1, s2)   # ZeRO-sharded state bit-unchanged

        # recovery: the next clean step trains again
        p3, s3, sent3, loss3 = step(p2, s2, batch, sent2)
        assert int(sent3.skipped_steps) == 1
        assert np.isfinite(float(loss3))
        with pytest.raises(AssertionError):
            _leaves_equal(p2, p3)


# ---------------------------------------------------------------------------
# 3D GPT trainer (dp x pp x tp+sp) — the integration point
# ---------------------------------------------------------------------------


class Test3DTrainerPath:
    def _build(self):
        from apex_tpu.parallel import mesh as mesh_lib
        from apex_tpu.transformer.testing import TransformerConfig
        from apex_tpu.transformer.testing.gpt_parallel_train import (
            build_gpt_3d,
        )

        mesh = mesh_lib.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=2,
            padded_vocab_size=64, max_position_embeddings=16,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp", sequence_parallel=True)
        init_fn, _, make_step = build_gpt_3d(
            cfg, num_chunks=1, num_microbatches=2, mesh=mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        params, specs = init_fn(jax.random.PRNGKey(0), tokens)
        return make_step, params, specs, tokens

    def test_skipped_steps_surface_and_state_frozen(self):
        """One build of the dp x pp x tp+sp trainer covers: skip counter
        surfacing, bit-frozen params/state across the poisoned step,
        post-skip recovery, and the one-compiled-program HLO proof."""
        make_step, params, specs, tokens = self._build()
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)
        scaler = DynamicLossScale(init_scale=8.0)
        sent = sentinel_init(scaler)
        step = jax.jit(make_step(opt, specs, scaler=scaler))
        poison = functools.partial(faults.poison_grads, step=1, at_step=1)
        poisoned_step = jax.jit(
            make_step(opt, specs, scaler=scaler, grad_tap=poison))

        _assert_guard_survives(compiled_hlo(step, params, state, tokens,
                                            sent))

        p1, s1, sent1, loss1 = step(params, state, tokens, sent)
        assert int(sent1.skipped_steps) == 0
        assert np.isfinite(float(loss1))

        p2, s2, sent2, _ = poisoned_step(p1, s1, tokens, sent1)
        assert int(sent2.skipped_steps) == 1
        assert float(sent2.scaler.scale) == 4.0
        _leaves_equal(p1, p2)
        _leaves_equal(s1, s2)

        # the sentinel step trains normally on clean grads: same loss
        # trajectory as an unguarded step would give (scale cancels)
        p3, s3, sent3, loss3 = step(p2, s2, tokens, sent2)
        assert int(sent3.skipped_steps) == 1
        assert float(loss3) < float(loss1)
