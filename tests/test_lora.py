"""apex_tpu.serving.lora — batched multi-LoRA serving (ISSUE 17).

The tentpole contracts the acceptance bar names: the gathered-delta
kernel pair (fused Pallas scalar-prefetch vs the jnp.take twin vs a
dense host loop), the refcounted adapter arena under 200-step
register/evict/pin churn (no slot ever strands), ``adapter_id=None``
bitwise token-identical to the bare engine — greedy, seeded AND
speculative with an int8 cache — zero decode/prefill recompiles across
mixed-adapter churn including a mid-flight hot-swap and an LRU
eviction, the unknown-adapter typed REJECTED, and the spec-layer
adapter checkpoint restore (corrupt newest falls back).

Engines are cached per shape and reused across tests (adapter mix,
registration churn and policies are all data — the test_speculative
reuse pattern); the shared tiny GPT comes from ``test_serving``'s
module-level model cache.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.serving import (
    LoRAConfig,
    SamplingParams,
    ServingConfig,
    SpeculativeConfig,
)
from apex_tpu.serving.lora import (
    AdapterArena,
    OutOfAdapterSlotsError,
    adapter_shapes,
    init_adapter_weights,
    lora_delta_fused,
    lora_delta_unfused,
    pack_adapter_values,
    restore_adapter_for_serving,
)
from apex_tpu.serving.scheduler import RequestState

from test_serving import MAX_SEQ, VOCAB, _build_engine, _tiny_cfg, _wave

# ------------------------------------------------------------- kernel


def _dense_delta_reference(x, a, b, slots):
    """O(everything) host loop: per batch slot, gather A/B and contract
    in fp64 (tighter than both kernels — the arbiter)."""
    S, B, _ = x.shape
    out = np.zeros((S, B, b.shape[2]), np.float64)
    for i in range(B):
        ai = np.asarray(a[slots[i]], np.float64)
        bi = np.asarray(b[slots[i]], np.float64)
        out[:, i, :] = np.asarray(x[:, i, :], np.float64) @ ai @ bi
    return out


def test_delta_kernel_fused_matches_unfused_and_dense():
    rng = np.random.RandomState(7)
    S, B, IN, r, OUT, n_slots = 4, 3, 32, 4, 24, 5
    x = jnp.asarray(rng.randn(S, B, IN), jnp.float32)
    a = jnp.asarray(rng.randn(n_slots, IN, r), jnp.float32)
    b = jnp.asarray(rng.randn(n_slots, r, OUT), jnp.float32)
    # slot 0 is the zero adapter; mixed repeats exercise the gather
    a = a.at[0].set(0.0)
    b = b.at[0].set(0.0)
    slots = jnp.asarray([2, 0, 4], jnp.int32)
    fused = lora_delta_fused(x, a, b, slots)
    unfused = lora_delta_unfused(x, a, b, slots)
    ref = _dense_delta_reference(np.asarray(x), np.asarray(a),
                                 np.asarray(b), np.asarray(slots))
    np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(fused), ref, atol=2e-4)
    # the zero slot produces EXACT zeros — that exactness is what makes
    # adapter_id=None bitwise the bare engine, not merely close to it
    assert np.abs(np.asarray(fused[:, 1, :])).max() == 0.0


# -------------------------------------------------------------- arena


def test_arena_refcount_churn_strands_no_capacity():
    """The satellite bar: 200 steps of register / pin / unpin /
    unregister churn against a 4-slot arena — the allocator invariants
    hold at every step, and after the storm drains every slot but the
    permanent zero adapter is free again."""
    rng = np.random.RandomState(17)
    arena = AdapterArena(n_slots=5)          # zero slot + 4 residents
    ids = [f"tenant-{i}" for i in range(12)]
    live_pins = {}                            # rid -> adapter_id
    next_rid = [0]
    for step in range(200):
        op = rng.randint(4)
        if op == 0:                           # register (may LRU-evict)
            aid = ids[rng.randint(len(ids))]
            try:
                slot, evicted = arena.register(aid)
                assert 0 < slot < arena.n_slots
                assert evicted is None or not arena.resident(evicted)
            except OutOfAdapterSlotsError:
                # legal exactly when every resident adapter is pinned
                pinned = set(live_pins.values())
                assert all(r in pinned for r in arena.residents())
        elif op == 1 and arena.residents():   # pin a resident
            aid = arena.residents()[rng.randint(len(arena.residents()))]
            rid = next_rid[0]
            next_rid[0] += 1
            arena.pin(aid, rid)
            live_pins[rid] = aid
        elif op == 2 and live_pins:           # a request finishes
            rid = list(live_pins)[rng.randint(len(live_pins))]
            del live_pins[rid]
            arena.unpin(rid)
        elif op == 3 and arena.residents():   # unregister a resident
            aid = arena.residents()[rng.randint(len(arena.residents()))]
            arena.unregister(aid)
        arena.check()
    for rid in list(live_pins):
        arena.unpin(rid)
    for aid in list(arena.residents()):
        arena.unregister(aid)
    arena.check()
    # nothing stranded: all 4 resident slots free, zero slot held
    assert arena.allocator.n_free == arena.n_slots - 1
    assert arena.active == 0
    assert arena.loads > 0 and arena.evictions > 0, \
        "the churn never exercised eviction — the test is not testing"


def test_arena_all_pinned_raises_and_unpin_is_idempotent():
    arena = AdapterArena(n_slots=3)           # zero slot + 2 residents
    arena.register("a")
    arena.register("b")
    arena.pin("a", rid=1)
    arena.pin("b", rid=2)
    with pytest.raises(OutOfAdapterSlotsError, match="pinned"):
        arena.register("c")
    # unregistered-but-pinned: the slot survives until the last unpin
    arena.unregister("b")
    assert not arena.resident("b")
    assert arena.allocator.n_free == 0        # rid=2 still holds it
    arena.unpin(2)
    assert arena.allocator.n_free == 1
    slot, evicted = arena.register("c")       # now it fits
    assert evicted is None
    arena.unpin(2)                            # idempotent no-op
    arena.unpin(99)                           # never-pinned no-op
    arena.check()


def test_pack_adapter_values_validates_shapes():
    cfg = _tiny_cfg()
    lora = LoRAConfig(rank=4, max_adapters=2)
    w = init_adapter_weights(cfg, lora, seed=0)
    vals = pack_adapter_values(cfg, lora, w, np.float32)
    assert len(vals) == 8
    # B comes back pre-scaled by alpha/rank
    np.testing.assert_allclose(
        vals[1], w["qkv"][1] * (lora.alpha / lora.rank), rtol=1e-6)
    with pytest.raises(ValueError, match="missing projection"):
        pack_adapter_values(cfg, lora, {"qkv": w["qkv"]}, np.float32)
    bad = dict(w)
    bad["fc1"] = (w["fc1"][0][:, :-1, :], w["fc1"][1])
    with pytest.raises(ValueError, match="do not match arena"):
        pack_adapter_values(cfg, lora, bad, np.float32)


# ------------------------------------------------------------- engine

# One cached engine per (lora, speculative+int8) shape, reused across
# waves — registration churn, adapter mixes and sampling policies are
# data, so reuse keeps the tier-1 compile budget flat.
_ENGINES = {}


def _engine(*, lora=False, spec_int8=False):
    key = (lora, spec_int8)
    if key not in _ENGINES:
        _, _, eng = _build_engine(
            tp=1, serving=ServingConfig(
                max_batch=3, block_size=4, max_seq=MAX_SEQ,
                prefill_len=8,
                cache_dtype=jnp.int8 if spec_int8 else None,
                speculative=(SpeculativeConfig(k=2, backoff=4)
                             if spec_int8 else None),
                lora=LoRAConfig(rank=4, max_adapters=3) if lora else None))
        _ENGINES[key] = eng
    return _ENGINES[key]


def _serve(eng, wave, *, sampling=None):
    reqs = [eng.submit(p, n, sampling=sampling) for p, n in wave]
    eng.run_until_drained(max_steps=5000)
    eng.scheduler.allocator.check()
    assert eng.decode_compile_count() == 1, \
        "adapter churn must never recompile the decode step"
    assert eng.prefill_compile_count() == 1
    assert all(r.state is RequestState.FINISHED for r in reqs)
    return [r.output_tokens for r in reqs]


def test_adapter_none_bitwise_identity_vs_bare_engine():
    """The acceptance bar: a lora-enabled engine (adapters registered,
    arena non-trivial) serving ``adapter_id=None`` requests emits
    BITWISE the bare engine's streams — greedy and seeded — because the
    zero-slot gather contributes an exact-zero delta, not a small one."""
    bare = _engine(lora=False)
    lora = _engine(lora=True)
    for aid in ("tenant-a", "tenant-b"):      # non-trivial arena rows
        lora.register_adapter(aid)
    wave = _wave(seed=5, n=5)
    assert _serve(lora, wave) == _serve(bare, wave)
    sp = SamplingParams(temperature=1.2, top_p=0.9, seed=42)
    assert _serve(lora, wave, sampling=sp) == _serve(bare, wave,
                                                     sampling=sp)


def test_adapter_none_identity_speculative_int8():
    """Same identity through the hard path: speculative drafting (k=2
    with the k+1 verify) over an int8 KV cache."""
    bare = _engine(lora=False, spec_int8=True)
    lora = _engine(lora=True, spec_int8=True)
    lora.register_adapter("tenant-a")
    wave = _wave(seed=9, n=5)
    assert _serve(lora, wave) == _serve(bare, wave)
    assert lora.spec_proposed > 0, \
        "speculation never engaged — the test is not testing"


def test_mixed_adapter_churn_zero_recompiles_and_eviction():
    """Mixed tagged/bare batches, a mid-flight hot-swap and an LRU
    eviction: all data, zero recompiles, distinct adapters produce
    distinct streams, the same adapter reproduces its stream, and the
    arena books close."""
    eng = _engine(lora=True)
    arena = eng.adapter_arena
    snap0 = eng.registry.snapshot()
    for aid in ("t0", "t1", "t2"):
        eng.register_adapter(aid)
    prompt = [9, 8, 7, 6]
    reqs = {
        aid: eng.submit(prompt, 6, sampling=SamplingParams(adapter_id=aid)
                        if aid else None)
        for aid in ("t0", "t1", None)
    }
    eng.step()                      # admit + first tokens (pins live)
    # hot-swap t2 mid-flight (resident, unpinned: in-place, no evict)
    eng.register_adapter("t2")
    eng.run_until_drained(max_steps=5000)
    # a 4th adapter LRU-evicts the coldest unpinned resident
    eng.register_adapter("t3")
    assert len(arena) == 3
    late = eng.submit(prompt, 6,
                      sampling=SamplingParams(adapter_id="t3"))
    again = eng.submit(prompt, 6,
                       sampling=SamplingParams(adapter_id="t0")
                       if arena.resident("t0") else None)
    eng.run_until_drained(max_steps=5000)
    arena.check()
    assert eng.decode_compile_count() == 1
    assert eng.prefill_compile_count() == 1
    streams = {aid: r.output_tokens for aid, r in reqs.items()}
    # the LOUD fixture weights guarantee visible divergence per tenant
    assert streams["t0"] != streams[None]
    assert streams["t1"] != streams[None]
    assert streams["t0"] != streams["t1"]
    assert late.state is RequestState.FINISHED
    assert late.output_tokens != streams[None]
    if again.sampling is not None and again.sampling.adapter_id == "t0":
        # same id -> same default seed -> same weights -> same stream
        assert again.output_tokens == streams["t0"]
    snap = eng.registry.snapshot()
    assert snap["serving/adapter_loads"] - \
        snap0.get("serving/adapter_loads", 0.0) == 5.0
    assert snap["serving/adapter_evictions"] - \
        snap0.get("serving/adapter_evictions", 0.0) >= 1.0
    assert arena.active == 0        # every pin released at finish
    intro = eng.introspect()
    assert set(intro["adapters_resident"]) == set(arena.residents())
    assert intro["adapter_active"] == 0


def test_unknown_adapter_submit_typed_rejected():
    """An unknown (or never-enabled) adapter id is refused AT THE DOOR
    with the same typed terminal REJECTED the drain window uses — never
    queued, never a hang, counted for the router to re-route on."""
    eng = _engine(lora=True)
    before = eng.registry.snapshot().get("serving/requests_rejected", 0.0)
    ghost = eng.submit([1, 2, 3], 4,
                       sampling=SamplingParams(adapter_id="ghost"))
    assert ghost.state is RequestState.REJECTED and ghost.done
    assert ghost.output_tokens == []
    snap = eng.registry.snapshot()
    assert snap["serving/requests_rejected"] - before == 1.0
    assert eng.scheduler.idle        # never entered the queue
    # a lora-less engine rejects EVERY adapter-tagged submit the same way
    bare = _engine(lora=False)
    before = bare.registry.snapshot().get("serving/requests_rejected", 0.0)
    req = bare.submit([1, 2, 3], 4,
                      sampling=SamplingParams(adapter_id="tenant-a"))
    assert req.state is RequestState.REJECTED
    assert bare.registry.snapshot()["serving/requests_rejected"] \
        - before == 1.0
    # an unregister closes the door for NEW submits of that id
    eng.register_adapter("fleeting")
    eng.unregister_adapter("fleeting")
    gone = eng.submit([1, 2], 3,
                      sampling=SamplingParams(adapter_id="fleeting"))
    assert gone.state is RequestState.REJECTED


# ------------------------------------------------- checkpoint restore


def test_restore_adapter_round_trip_with_corrupt_fallback(tmp_path):
    """The spec-layer restore path on adapter checkpoints: save two
    steps, corrupt the newest, and the restore falls back to the intact
    step with the weights bit-exact — then registers clean."""
    from apex_tpu.resilience import CheckpointManager

    cfg = _tiny_cfg()
    lora = LoRAConfig(rank=4, max_adapters=2)
    root = str(tmp_path / "adapters")

    def tree(seed):
        w = init_adapter_weights(cfg, lora, seed=seed)
        return w, {"lora": {proj: {"a": a, "b": b}
                            for proj, (a, b) in w.items()}}

    mgr = CheckpointManager(root, sharded=False)
    w0, t0 = tree(seed=0)
    mgr.save(t0, 0)
    _, t1 = tree(seed=1)
    path1 = mgr.save(t1, 1)
    with open(path1, "r+b") as f:             # torn newest
        f.seek(0)
        f.write(b"\x00" * 64)
    weights, step = restore_adapter_for_serving(
        root, cfg, lora, sharded=False, with_step=True)
    assert step == 0, "corrupt newest must fall back, not fail"
    shapes = adapter_shapes(cfg, lora)
    for proj, (a, b) in weights.items():
        np.testing.assert_array_equal(a, w0[proj][0], err_msg=proj)
        np.testing.assert_array_equal(b, w0[proj][1], err_msg=proj)
        assert a.shape == (cfg.num_layers,) + shapes[proj][0]
    eng = _engine(lora=True)
    slot = eng.register_adapter("restored", weights=weights)
    assert 0 < slot < eng.lora.n_slots
    # a wrong-rank checkpoint refuses loudly at registration
    with pytest.raises(ValueError, match="do not match arena"):
        eng.register_adapter(
            "bad-rank",
            weights=init_adapter_weights(cfg, LoRAConfig(rank=2), seed=3))
    with pytest.raises(FileNotFoundError, match="no adapter checkpoint"):
        restore_adapter_for_serving(str(tmp_path / "empty"), cfg, lora,
                                    sharded=False)
