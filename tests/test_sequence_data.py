"""Packed-sequence LM streaming (apex_tpu.data.sequence): pack round
trip, loader contracts (shared ProducerLoader machinery), segment loss
masks, and ingestion into the ZeRO and 3D GPT trainers — the LM paths'
first real-data input pipeline (ISSUE 8 tentpole layer 3)."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apex_tpu.data import (  # noqa: E402
    PackedSequenceDataset,
    PackedSequenceLoader,
    pack_token_documents,
    prefetch_to_device,
    segment_loss_mask,
    synthetic_token_documents,
)

VOCAB, SEQ, EOS = 64, 32, 63


@pytest.fixture(scope="module")
def docs():
    return synthetic_token_documents(48, vocab=VOCAB, mean_len=20, seed=0)


@pytest.fixture(scope="module")
def packed(docs, tmp_path_factory):
    prefix = str(tmp_path_factory.mktemp("seq") / "train")
    ds = pack_token_documents(docs, prefix, seq_len=SEQ, eos_id=EOS)
    return prefix, ds


def test_pack_round_trip(docs, packed):
    """Concatenated non-padding tokens reproduce the document stream
    exactly — packing loses nothing and pads only the final row tail."""
    prefix, ds = packed
    stream = np.concatenate([np.asarray(d + [EOS], np.int32) for d in docs])
    flat_tok = np.asarray(ds.tokens).ravel()
    flat_seg = np.asarray(ds.segments).ravel()
    np.testing.assert_array_equal(flat_tok[flat_seg > 0], stream)
    # padding exists only at the very tail (one partial final row)
    pad = np.flatnonzero(flat_seg == 0)
    if pad.size:
        assert pad[0] == flat_seg.size - pad.size
    # a fresh open sees the same bytes
    ds2 = PackedSequenceDataset(prefix)
    assert ds2.seq_len == SEQ and len(ds2) == len(ds)
    np.testing.assert_array_equal(np.asarray(ds2.tokens),
                                  np.asarray(ds.tokens))


def test_segments_mark_document_boundaries(packed):
    _, ds = packed
    seg = np.asarray(ds.segments)
    # per-row ids are 1-based and contiguous; 0 only as tail padding
    for row in seg:
        ids = row[row > 0]
        uniq = np.unique(ids)
        np.testing.assert_array_equal(uniq, np.arange(1, uniq.size + 1))
        # non-decreasing within a row (documents are laid out in order)
        assert (np.diff(ids) >= 0).all()


def test_pack_rejects_empty_and_bad_version(tmp_path):
    import json

    with pytest.raises(ValueError):
        pack_token_documents([], str(tmp_path / "out"), seq_len=8)
    prefix = str(tmp_path / "bad")
    with open(prefix + ".json", "w") as f:
        json.dump({"n": 1, "seq_len": 8, "n_docs": 1, "version": 99}, f)
    with pytest.raises(ValueError, match="version"):
        PackedSequenceDataset(prefix)


def test_loader_shapes_and_disjoint_dp_shards(packed):
    _, ds = packed
    with PackedSequenceLoader(ds, local_batch=4,
                              data_parallel_size=2) as loader:
        tokens, segments = next(iter(loader))
    assert tokens.shape == (8, SEQ) and tokens.dtype == np.int32
    assert segments.shape == (8, SEQ) and segments.dtype == np.int32
    fresh = PackedSequenceLoader(ds, local_batch=4, data_parallel_size=2)
    idx = [next(iter(s)) for s in fresh.samplers]
    assert not set(idx[0]) & set(idx[1]), "dp shards overlap"
    np.testing.assert_array_equal(tokens[:4], ds.tokens[idx[0]])
    np.testing.assert_array_equal(tokens[4:], ds.tokens[idx[1]])
    fresh.close()


def test_loader_resume_contract(packed):
    """The ProducerLoader contracts hold for the sequence subclass:
    consumed_samples counts yielded batches only, and a fresh loader
    from the checkpoint continues bit-exact."""
    _, ds = packed
    loader = PackedSequenceLoader(ds, local_batch=4)
    it = iter(loader)
    for _ in range(3):
        next(it)
    consumed = loader.consumed_samples
    assert consumed == 12
    loader.close()
    with PackedSequenceLoader(ds, local_batch=4,
                              consumed_samples=consumed) as l2:
        nxt = next(iter(l2))
    with PackedSequenceLoader(ds, local_batch=4) as l3:
        it3 = iter(l3)
        for _ in range(3):
            next(it3)
        expect = next(it3)
    np.testing.assert_array_equal(nxt[0], expect[0])
    np.testing.assert_array_equal(nxt[1], expect[1])


def test_dp_ranks_host_shard_is_global_batch_window(packed):
    """A dp_ranks-restricted loader yields exactly its ranks' windows of
    the full loader's global batch — the per-host no-redundant-decode
    contract."""
    _, ds = packed
    with PackedSequenceLoader(ds, local_batch=2,
                              data_parallel_size=2) as full, \
            PackedSequenceLoader(ds, local_batch=2, data_parallel_size=2,
                                 dp_ranks=[1]) as host1:
        t_full, s_full = next(iter(full))
        t_h1, s_h1 = next(iter(host1))
    assert t_h1.shape == (2, SEQ)
    np.testing.assert_array_equal(t_h1, t_full[2:])
    np.testing.assert_array_equal(s_h1, s_full[2:])
    # consumed_samples stays GLOBAL on the host-sharded loader
    assert host1.consumed_samples == full.consumed_samples == 4


def test_segment_loss_mask_semantics():
    seg = np.array([[1, 1, 2, 2, 0, 0]], np.int32)
    m = segment_loss_mask(seg)
    # positions: (1,1)=1 same doc; (1,2)=0 boundary; (2,2)=1; (2,0)=0 pad;
    # (0,0)=0 pad
    np.testing.assert_array_equal(m, [[1.0, 0.0, 1.0, 0.0, 0.0]])


def test_device_prefetch_composition(packed):
    _, ds = packed
    with PackedSequenceLoader(ds, local_batch=4) as loader:
        pf = prefetch_to_device(loader, depth=1, place=lambda b: b)
        t, s = next(pf)
        assert t.shape == (4, SEQ)
        assert pf.consumed_samples == 4
        pf.close()
    # close() passthrough + rewind: loader agrees with the wrapper
    assert loader.consumed_samples == 4


def test_zero_trainer_ingests_packed_stream(packed):
    """The ZeRO data-parallel step consumes (tokens, segments) batches
    directly (its batch handling is pytree-generic): a tiny embedding LM
    with a segment-masked next-token loss trains on the real stream."""
    from apex_tpu import parallel
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.parallel.distributed import (
        zero_data_parallel_train_step,
        zero_init,
    )

    _, ds = packed
    mesh = parallel.initialize_model_parallel()  # dp=8
    try:
        def loss_fn(params, batch):
            tokens, segments = batch
            h = params["emb"][tokens]                       # [b, s, d]
            logits = jnp.einsum("bsd,vd->bsv", h, params["emb"])
            logp = jax.nn.log_softmax(logits[:, :-1])
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(
                logp, tgt[..., None], axis=-1)[..., 0]      # [b, s-1]
            m = segment_loss_mask(segments)
            return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)

        params = {"emb": jnp.asarray(
            np.random.RandomState(0).randn(VOCAB, 16), jnp.float32)}
        opt = DistributedFusedAdam(lr=1e-2)
        state = zero_init(opt, params, mesh)
        step = zero_data_parallel_train_step(loss_fn, opt, mesh=mesh)

        with PackedSequenceLoader(ds, local_batch=1,
                                  data_parallel_size=8) as loader:
            dev = prefetch_to_device(loader, mesh, depth=2)
            losses = []
            for _ in range(3):
                batch = next(dev)
                params, state, loss = step(params, state, batch)
                losses.append(float(loss))
            dev.close(close_source=False)
        assert all(np.isfinite(losses)), losses
    finally:
        parallel.mesh.destroy_model_parallel()


@pytest.mark.slow
def test_gpt3d_packed_inputs_end_to_end(packed):
    """build_gpt_3d(packed_inputs=True) trains from the real packed
    stream on the full dp x pp x tp(+sp) mesh."""
    from apex_tpu import parallel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        TransformerConfig,
    )

    _, ds = packed
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    try:
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp", sequence_parallel=True)
        init_fn, _, make_train_step = build_gpt_3d(
            cfg, num_microbatches=2, mesh=mesh, packed_inputs=True)
        params, specs = init_fn(jax.random.PRNGKey(0),
                                jnp.zeros((8, SEQ), jnp.int32))
        opt = FusedAdam(lr=1e-3)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(opt, specs))
        with PackedSequenceLoader(ds, local_batch=4,
                                  data_parallel_size=2) as loader:
            dev = prefetch_to_device(loader, mesh, depth=2)
            losses = []
            for _ in range(2):
                params, opt_state, loss = step(params, opt_state, next(dev))
                losses.append(float(loss))
            dev.close(close_source=False)
        assert all(np.isfinite(losses)), losses
        assert loader.consumed_samples == 16
    finally:
        parallel.mesh.destroy_model_parallel()


def test_gpt3d_packed_loss_matches_manual_mask():
    """packed_inputs loss == hand-masked serial computation on a dp-only
    mesh (pp=tp=1): the ingestion path changes only the masking."""
    from apex_tpu import parallel
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        TransformerConfig,
    )

    mesh = parallel.initialize_model_parallel()  # dp=8, pp=tp=1
    try:
        cfg = TransformerConfig(
            hidden_size=16, num_layers=1, num_attention_heads=2,
            padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp")
        rng = np.random.RandomState(5)
        tokens = jnp.asarray(rng.randint(1, VOCAB, size=(16, SEQ)),
                             jnp.int32)
        segments = np.ones((16, SEQ), np.int32)
        segments[:, SEQ // 2:] = 2          # a doc boundary mid-sequence
        segments[:, -3:] = 0                # and a padded tail
        segments = jnp.asarray(segments)

        init_fn, make_loss_fn, _ = build_gpt_3d(
            cfg, num_microbatches=2, mesh=mesh, packed_inputs=True)
        params, specs = init_fn(jax.random.PRNGKey(1), tokens)
        loss = jax.jit(make_loss_fn(specs))(params, (tokens, segments))

        # manual: unmasked per-token losses from the unpacked builder's
        # loss are not directly exposed, so recompute the mask algebra:
        # the packed loss must equal sum(per_tok * mask)/sum(mask) where
        # per_tok comes from the SAME model — proxy check: full-coverage
        # segments reproduce the unpacked mean loss bitwise.
        ones = jnp.ones_like(segments)
        init2, make_loss2, _ = build_gpt_3d(
            cfg, num_microbatches=2, mesh=mesh)
        loss_unpacked = jax.jit(make_loss2(specs))(params, tokens)
        loss_allones = jax.jit(make_loss_fn(specs))(params, (tokens, ones))
        np.testing.assert_allclose(np.asarray(loss_allones),
                                   np.asarray(loss_unpacked),
                                   rtol=1e-6, atol=1e-6)
        # and masking strictly changes the loss (boundary + pad excluded)
        assert not np.allclose(np.asarray(loss), np.asarray(loss_unpacked))
    finally:
        parallel.mesh.destroy_model_parallel()


def test_gpt3d_block_diagonal_attention():
    """ISSUE 9 satellite (PR 7 follow-up): with ``block_diagonal=True``
    the packed trainer masks ATTENTION at document boundaries (flash
    segment ids riding the pipeline), not just the loss.

    - full-coverage segments reproduce the plain-causal packed forward
      BITWISE (the combined causal∧same-segment mask degenerates to the
      causal mask, so the kernel arithmetic is unchanged);
    - a mid-row document boundary changes the loss vs loss-mask-only
      packing (positions after the boundary no longer read the previous
      document);
    - gradients flow (the int32 segment carry is tangent-free but the
      transposed pipeline still runs).
    """
    from apex_tpu import parallel
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        TransformerConfig,
    )

    # dp=1 sub-mesh: the contract under test is the segment carry
    # through the pp rotation + tp flash, not dp replication (which
    # every other 3D test covers) — halves the SPMD compile
    mesh = parallel.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
        devices=jax.devices()[:4])
    try:
        cfg = TransformerConfig(
            hidden_size=32, num_layers=2, num_attention_heads=4,
            padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_axis="tp", sequence_parallel=True,
            use_flash_attention=True)
        rng = np.random.RandomState(5)
        tokens = jnp.asarray(rng.randint(1, VOCAB, size=(4, SEQ)),
                             jnp.int32)
        segs = np.ones((4, SEQ), np.int32)
        segs[:, SEQ // 2:] = 2
        segs[:, -2:] = 0
        segs = jnp.asarray(segs)
        ones = jnp.ones_like(segs)

        kw = dict(num_microbatches=2, mesh=mesh, packed_inputs=True)
        init_fn, make_loss_bd, _ = build_gpt_3d(
            cfg, block_diagonal=True, **kw)
        _, make_loss_plain, _ = build_gpt_3d(cfg, **kw)
        params, specs = init_fn(jax.random.PRNGKey(0), tokens)

        bd = jax.jit(jax.value_and_grad(make_loss_bd(specs)))
        plain = jax.jit(make_loss_plain(specs))
        l_bd, _ = bd(params, (tokens, ones))
        l_plain = plain(params, (tokens, ones))
        assert float(l_bd) == float(l_plain)   # bitwise, not allclose

        l_masked, g = bd(params, (tokens, segs))
        l_leaky = plain(params, (tokens, segs))
        assert float(l_masked) != float(l_leaky)

        flat = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(x)).all() for x in flat)
        assert any(float(jnp.abs(x).max()) > 0 for x in flat)
    finally:
        parallel.mesh.destroy_model_parallel()


def test_gpt3d_block_diagonal_validation():
    """block_diagonal without packed inputs or without the flash core
    (whose segment mechanism it rides) is refused, not silently
    ignored."""
    from apex_tpu import parallel
    from apex_tpu.transformer.testing.gpt_parallel_train import build_gpt_3d
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        TransformerConfig,
    )

    mesh = parallel.initialize_model_parallel()
    try:
        flash = TransformerConfig(
            hidden_size=16, num_layers=1, num_attention_heads=2,
            padded_vocab_size=VOCAB, max_position_embeddings=SEQ,
            tensor_axis="tp", use_flash_attention=True)
        with pytest.raises(ValueError, match="packed_inputs"):
            build_gpt_3d(flash, mesh=mesh, block_diagonal=True)
        fused = dataclasses.replace(flash, use_flash_attention=False)
        with pytest.raises(ValueError, match="use_flash_attention"):
            build_gpt_3d(fused, mesh=mesh, packed_inputs=True,
                         block_diagonal=True)
    finally:
        parallel.mesh.destroy_model_parallel()
